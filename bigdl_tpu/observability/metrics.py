"""Live fleet telemetry: metrics registry, /metrics exporter, SLO burn.

Everything observability built so far (StepTelemetry JSONL, health
events, trusted timing, the HLO audit) is post-hoc: the artifacts tell
you a run was sick AFTER it ends.  A serving engine under live traffic
-- and the train->serve loop around it -- needs the *current* queue
depth, the *rolling* p99, the error-budget burn and the restart churn
while the process is still alive.  The reference leaned on Spark's live
web UI for exactly this role (BigDL, arxiv 1804.05839); this module is
the JAX-rebuild equivalent, with zero dependencies beyond the stdlib:

- ``Counter`` / ``Gauge`` / ``Histogram`` -- thread-safe, labeled
  metric primitives.  Histograms keep cumulative Prometheus buckets
  AND a bounded reservoir of recent samples, so live percentiles
  (nearest-rank, the one shared definition in ``profiling.percentile``)
  are queryable without unbounded memory.
- ``MetricsRegistry`` -- the process-wide metric hub.  Besides
  get-or-create metric constructors and the Prometheus text rendering,
  it carries the telemetry bridge (``observe_event``): attach it to a
  ``StepTelemetry`` (``tel.attach_metrics(registry)``) and every event
  the run records -- serving ticks, training steps, health samples,
  anomalies, recovery restarts -- updates the live series.  One bridge
  wires all three tiers: ``ServingEngine`` (queue depth, batch fill,
  pad waste, request latency, per-bucket requests, recompiles,
  ``refresh_params`` swaps), the shared driver loop (step times,
  data-wait fraction, MFU when the compiled step's cost is attached,
  wire bytes, anomaly counts) and ``RunSupervisor`` (restart/backoff
  counters).
- ``MetricsExporter`` -- a stdlib ``http.server`` thread serving the
  registry in Prometheus text format on ``/metrics`` plus a
  ``/healthz`` JSON endpoint whose status (``ok`` / ``degraded`` /
  ``halted``) derives from the watchdog/health layer: anomalies mark
  the run degraded (a ``halt``-policy finding: halted), an active SLO
  breach marks it degraded while it burns.
- ``SloTracker`` -- declarative objectives (``p99_latency_ms <= X at
  99.9%`` style: per-sample good/bad against a threshold, a compliance
  target) evaluated over rolling windows with multi-window burn-rate
  alerting (the SRE pattern: a breach needs BOTH the short and the
  long window burning faster than ``factor`` x budget, so a single
  slow request cannot page and a slow hour cannot hide).  A breach
  emits a durable ``kind: "slo"`` telemetry event and feeds the same
  warn/dump/halt policy framework as the numerics watchdogs -- under
  ``policy="halt"`` an SLO breach raises ``TrainingHaltedError`` out
  of the recording driver loop exactly like a NaN.

Metric naming scheme (docs/observability.md, "Live metrics & SLOs"):
``bigdl_<tier>_<what>[_total|_seconds]`` with tiers ``serving`` /
``train`` / ``recovery`` / ``slo``.  No jax/numpy at module top: a
supervisor process exporting restart counters needs no accelerator.
"""

import json
import logging
import threading
import time

from bigdl_tpu.observability.profiling import percentile

log = logging.getLogger("bigdl_tpu.observability")

#: /healthz statuses in escalation order (worst wins)
HEALTH_STATUSES = ("ok", "degraded", "halted")

#: default Histogram buckets: latency-shaped, 1 ms .. 60 s (Prometheus
#: convention: upper bounds, +Inf implicit)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _validate_name(name):
    ok = name and (name[0].isalpha() or name[0] == "_") and all(
        c.isalnum() or c in "_:" for c in name)
    if not ok:
        raise ValueError(f"invalid metric name {name!r} (Prometheus: "
                         "[a-zA-Z_:][a-zA-Z0-9_:]*)")
    return name


def _escape_label(value):
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt(value):
    """Prometheus float formatting: integers stay integral."""
    f = float(value)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Metric:
    """Shared label plumbing: a metric owns child series keyed by the
    label-value tuple (the empty tuple for an unlabeled metric).  One
    lock per metric serializes child creation and value updates -- the
    scraper renders under the same lock, so a reader can never see a
    torn update."""

    type = "untyped"

    def __init__(self, name, help="", labelnames=()):
        self.name = _validate_name(name)
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children = {}

    def _labelvalues(self, labels):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} expects labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def _child(self, labels):
        key = self._labelvalues(labels)
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = self._new_child()
        return child

    def _series_name(self, key, suffix="", extra=()):
        """``extra`` label pairs come FIRST: the scoped-view injection
        (``render_scoped``: one /metrics port, N registries, a
        ``replica=`` label) without touching the child keys."""
        pairs = [f'{n}="{_escape_label(v)}"' for n, v in extra]
        pairs += [f'{n}="{_escape_label(v)}"'
                  for n, v in zip(self.labelnames, key)]
        if not pairs:
            return self.name + suffix
        return f"{self.name}{suffix}{{{','.join(pairs)}}}"

    def render_series(self, extra=()):
        """Just the sample lines (no HELP/TYPE headers) -- what a
        scoped multi-registry render groups under ONE family header."""
        with self._lock:
            return [line for key in sorted(self._children)
                    for line in self._render_child(
                        key, self._children[key], extra)]

    def render(self):
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.type}")
        lines.extend(self.render_series())
        return lines


class Counter(_Metric):
    """Monotonically increasing value (resets only with the process)."""

    type = "counter"

    def _new_child(self):
        return [0.0]

    def inc(self, amount=1.0, **labels):
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {amount})")
        with self._lock:
            self._child(labels)[0] += float(amount)

    def value(self, **labels):
        with self._lock:
            return self._child(labels)[0]

    def _render_child(self, key, child, extra=()):
        return [f"{self._series_name(key, extra=extra)} "
                f"{_fmt(child[0])}"]


class Gauge(_Metric):
    """A value that goes up and down (current queue depth, last loss)."""

    type = "gauge"

    def _new_child(self):
        return [0.0]

    def set(self, value, **labels):
        with self._lock:
            self._child(labels)[0] = float(value)

    def inc(self, amount=1.0, **labels):
        with self._lock:
            self._child(labels)[0] += float(amount)

    def dec(self, amount=1.0, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels):
        with self._lock:
            return self._child(labels)[0]

    def _render_child(self, key, child, extra=()):
        return [f"{self._series_name(key, extra=extra)} "
                f"{_fmt(child[0])}"]


class Histogram(_Metric):
    """Cumulative-bucket histogram + a BOUNDED reservoir.

    The buckets render in Prometheus text format (``_bucket{le=...}`` /
    ``_sum`` / ``_count``); the reservoir keeps the most recent
    ``reservoir_size`` observations per child so live percentiles
    (``quantile_value``) answer from recent data with memory bounded no
    matter how long the process serves.  Percentiles use the shared
    nearest-rank definition (``profiling.percentile``) -- a scraped p99
    and an obs_report p99 over the same samples agree exactly.
    """

    type = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets=DEFAULT_BUCKETS, reservoir_size=1024):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {self.name} needs >= 1 bucket")
        self.reservoir_size = int(reservoir_size)
        if self.reservoir_size < 1:
            raise ValueError(f"histogram {self.name}: reservoir_size "
                             f"must be >= 1, got {reservoir_size}")

    def _new_child(self):
        from collections import deque
        return {"counts": [0] * (len(self.buckets) + 1), "sum": 0.0,
                "count": 0,
                "reservoir": deque(maxlen=self.reservoir_size),
                # bucket index -> (trace_id, value, unix ts): the most
                # recent sampled request that landed in that bucket
                "exemplars": {}}

    def observe(self, value, exemplar=None, **labels):
        """Record one observation.  ``exemplar`` (a trace_id string)
        attaches the observation to a distributed trace: the rendered
        bucket line gains an OpenMetrics exemplar (``# {trace_id=...}
        value ts``), which is how a dashboard jumps from "the p99
        bucket is filling" to ONE concrete slow request's trace."""
        v = float(value)
        with self._lock:
            child = self._child(labels)
            i = len(self.buckets)
            for j, b in enumerate(self.buckets):
                if v <= b:
                    i = j
                    break
            child["counts"][i] += 1
            child["sum"] += v
            child["count"] += 1
            child["reservoir"].append(v)
            if exemplar:
                child["exemplars"][i] = (str(exemplar), v, time.time())

    def count(self, **labels):
        with self._lock:
            return self._child(labels)["count"]

    def quantile_value(self, q, **labels):
        """Nearest-rank percentile over the (bounded) reservoir of the
        most recent observations; None before the first sample."""
        with self._lock:
            samples = sorted(self._child(labels)["reservoir"])
        return percentile(samples, q)

    def _bucket_series(self, key, le, extra=()):
        # the le label joins the child's own labels in one brace set
        pairs = [f'{n}="{_escape_label(v)}"' for n, v in extra]
        pairs += [f'{n}="{_escape_label(v)}"'
                  for n, v in zip(self.labelnames, key)]
        pairs.append(f'le="{le}"')
        return f"{self.name}_bucket{{{','.join(pairs)}}}"

    @staticmethod
    def _exemplar_suffix(child, i):
        ex = child["exemplars"].get(i)
        if ex is None:
            return ""
        tid, v, ts = ex
        return (f' # {{trace_id="{_escape_label(tid)}"}} '
                f'{_fmt(v)} {ts:.3f}')

    def _render_child(self, key, child, extra=()):
        lines, cum = [], 0
        for j, (b, n) in enumerate(zip(self.buckets, child["counts"])):
            cum += n
            lines.append(
                f"{self._bucket_series(key, _fmt(b), extra)} {cum}"
                f"{self._exemplar_suffix(child, j)}")
        cum += child["counts"][-1]
        lines.append(f"{self._bucket_series(key, '+Inf', extra)} {cum}"
                     f"{self._exemplar_suffix(child, len(self.buckets))}")
        lines.append(f"{self._series_name(key, '_sum', extra)} "
                     f"{_fmt(child['sum'])}")
        lines.append(f"{self._series_name(key, '_count', extra)} "
                     f"{child['count']}")
        return lines


# --------------------------------------------------------------------------- #
# The registry: metric hub + telemetry bridge + health state.
# --------------------------------------------------------------------------- #


class MetricsRegistry:
    """Process-local metric hub.

    >>> reg = MetricsRegistry()
    >>> reg.counter("bigdl_requests_total", "served requests").inc()
    >>> print(reg.render())                    # Prometheus text format

    ``observe_event(event)`` is the telemetry bridge: attach the
    registry to a run's ``StepTelemetry`` and every recorded event
    updates the live series -- the serving/training/recovery metric
    families below come from the SAME event dicts the JSONL records, so
    a scrape and the artifact can never disagree about what happened.
    ``health()`` aggregates the watchdog-derived run status that
    ``MetricsExporter`` serves on ``/healthz``.
    """

    def __init__(self, prefix="bigdl"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._metrics = {}
        # reason -> status; /healthz reports the worst active one
        self._health = {}
        # header facts the bridge needs for derived gauges (MFU)
        self._flops_per_step = None
        self._peak_flops = None

    # ----- constructors (get-or-create, type-checked) ----------------------- #
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help,
                                              labelnames=labelnames, **kw)
            elif not isinstance(m, cls) or \
                    m.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name} already registered as "
                    f"{type(m).__name__}{m.labelnames}, not "
                    f"{cls.__name__}{tuple(labelnames)}")
            return m

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS, reservoir_size=1024):
        h = self._get_or_create(Histogram, name, help, labelnames,
                                buckets=buckets,
                                reservoir_size=reservoir_size)
        # class/labelnames conflicts raise above; a silently-dropped
        # bucket layout would serve le= boundaries the caller never
        # configured -- reject that mismatch just as loudly
        want = tuple(sorted(float(b) for b in buckets))
        if h.buckets != want or h.reservoir_size != int(reservoir_size):
            raise ValueError(
                f"histogram {name} already registered with buckets "
                f"{h.buckets} / reservoir {h.reservoir_size}, not "
                f"{want} / {reservoir_size}")
        return h

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def render(self):
        """The whole registry in Prometheus text exposition format."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    # ----- health state ------------------------------------------------------ #
    def set_health(self, reason, status):
        """Mark one named condition (``"slo:p99_latency"``,
        ``"watchdog:nonfinite"``) at a status; ``/healthz`` reports the
        worst across all active conditions."""
        if status not in HEALTH_STATUSES:
            raise ValueError(f"unknown health status {status!r}; expected "
                             f"one of {HEALTH_STATUSES}")
        with self._lock:
            if status == "ok":
                self._health.pop(reason, None)
            else:
                self._health[reason] = status

    def clear_health(self, reason):
        self.set_health(reason, "ok")

    def health(self):
        """-> ``{"status", "reasons"}`` -- the /healthz payload core."""
        with self._lock:
            conditions = dict(self._health)
        status = "ok"
        for s in conditions.values():
            if HEALTH_STATUSES.index(s) > HEALTH_STATUSES.index(status):
                status = s
        return {"status": status,
                "reasons": [{"reason": r, "status": s}
                            for r, s in sorted(conditions.items())]}

    # ----- the telemetry bridge ---------------------------------------------- #
    def observe_event(self, event):
        """Map one recorded telemetry event onto the live series.

        Attach via ``StepTelemetry.attach_metrics(registry)`` (or pass
        ``metrics=`` at telemetry construction): the driver loop's step
        events, the serving engine's tick events, the supervisor's
        recovery events, health samples and anomaly findings all flow
        through ``record()`` and land here.  Unknown kinds are ignored
        -- the bridge must never make recording an event unsafe."""
        kind = event.get("kind")
        if kind == "header":
            self._note_cost((event.get("cost") or {}), event)
            if event.get("serving"):
                self._observe_serving_info(event["serving"])
        elif kind == "cost":
            self._note_cost((event.get("cost") or {}), None)
        elif kind == "serving_info":
            self._observe_serving_info(event.get("serving") or {})
        elif kind == "deploy":
            self._observe_deploy(event)
        elif kind == "fleet":
            self._observe_fleet(event)
        elif kind == "step":
            self._observe_step(event)
        elif kind == "inference":
            self._observe_inference(event)
        elif kind == "health":
            self._observe_health(event)
        elif kind == "anomaly":
            self._observe_anomaly(event)
        elif kind == "recovery":
            self._observe_recovery(event)
        elif kind == "reshard":
            self._observe_reshard(event)
        elif kind == "slo":
            self._observe_slo(event)
        elif kind == "memory":
            self._observe_memory(event)
        elif kind == "memory_dump":
            self._observe_memory_dump(event)
        elif kind == "param_refresh":
            self.counter(
                f"{self.prefix}_serving_param_refresh_total",
                "ServingEngine.refresh_params outcomes",
                labelnames=("outcome",)).inc(
                    outcome=event.get("outcome", "ok"))

    def _note_cost(self, cost, header):
        if cost.get("flops_per_step"):
            self._flops_per_step = float(cost["flops_per_step"])
        if header and header.get("peak_flops"):
            self._peak_flops = float(header["peak_flops"])

    # -- training tier -------------------------------------------------------- #
    def _observe_step(self, event):
        p = self.prefix
        self.counter(f"{p}_train_steps_total", "completed train steps") \
            .inc()
        wall = event.get("wall_s")
        if wall is not None:
            self.histogram(f"{p}_train_step_wall_seconds",
                           "per-step wall time").observe(wall)
        loss = event.get("loss")
        if isinstance(loss, (int, float)) and loss == loss:  # not NaN
            self.gauge(f"{p}_train_loss", "last synced loss").set(loss)
        if event.get("records_per_s") is not None:
            self.gauge(f"{p}_train_records_per_second",
                       "last step's records/s").set(event["records_per_s"])
        if wall and event.get("data_wait_s") is not None:
            self.gauge(
                f"{p}_train_data_wait_fraction",
                "host input work fraction of the last step's wall time"
            ).set(min(1.0, event["data_wait_s"] / wall))
        blocked = event.get("step_blocked_s")
        if blocked is not None:
            self.histogram(f"{p}_train_step_blocked_seconds",
                           "fenced per-step time (trusted basis)") \
                .observe(blocked)
        # MFU needs the compiled step's cost (attach_cost header) and
        # the device peak; published basis mirrors obs_report: blocked
        # when the run is fenced, wall otherwise (labeled, so a scrape
        # can never pass an un-fenced number off as a fenced one)
        basis_s = blocked if blocked else wall
        if self._flops_per_step and self._peak_flops and basis_s:
            self.gauge(f"{p}_train_mfu",
                       "model flops utilization of the last step",
                       labelnames=("basis",)).set(
                self._flops_per_step / basis_s / self._peak_flops,
                basis="blocked" if blocked else "wall")
        if event.get("wire_bytes"):
            self.counter(f"{p}_train_wire_bytes_total",
                         "collective wire bytes moved") \
                .inc(event["wire_bytes"])
        if event.get("recompiles"):
            self.counter(f"{p}_train_recompiles_total",
                         "post-warmup compiles inside step windows") \
                .inc(event["recompiles"])
        if event.get("queue_depth") is not None:
            self.gauge(f"{p}_train_prefetch_queue_depth",
                       "prefetch queue occupancy") \
                .set(event["queue_depth"])

    # -- serving tier --------------------------------------------------------- #
    def _observe_inference(self, event):
        p = self.prefix
        self.counter(f"{p}_serving_ticks_total", "dispatcher ticks").inc()
        bucket = event.get("bucket")
        self.counter(f"{p}_serving_requests_total",
                     "requests served, by batch bucket",
                     labelnames=("bucket",)) \
            .inc(event.get("records", 0) or 0,
                 bucket=str(bucket) if bucket is not None else "none")
        if event.get("queue_depth") is not None:
            self.gauge(f"{p}_serving_queue_depth",
                       "pending requests after the last tick drained") \
                .set(event["queue_depth"])
        if event.get("queue_capacity") is not None:
            self.gauge(f"{p}_serving_queue_capacity",
                       "bounded request-queue capacity") \
                .set(event["queue_capacity"])
        if event.get("batch_fill") is not None:
            self.gauge(f"{p}_serving_batch_fill",
                       "real rows / bucket rows of the last tick") \
                .set(event["batch_fill"])
        if event.get("pad_waste") is not None:
            self.gauge(f"{p}_serving_pad_waste",
                       "padded-row fraction of the last tick") \
                .set(event["pad_waste"])
        lat = self.histogram(f"{p}_serving_request_latency_seconds",
                             "end-to-end request latency")
        # request_traces is parallel to request_latency_s (None for
        # untraced rows): sampled requests become bucket exemplars
        traces = event.get("request_traces") or []
        for i, v in enumerate(event.get("request_latency_s") or []):
            lat.observe(v, exemplar=traces[i] if i < len(traces)
                        else None)
        # generation ticks (serving/generation.py) additionally stamp
        # tick_kind ("prefill"/"decode"), tokens emitted and slot
        # occupancy -- the live tokens/s + slot-utilization signals
        if event.get("tokens"):
            self.counter(f"{p}_serving_tokens_total",
                         "generated tokens, by tick kind",
                         labelnames=("kind",)) \
                .inc(event["tokens"],
                     kind=str(event.get("tick_kind") or "decode"))
        if event.get("slots_total"):
            self.gauge(f"{p}_serving_slot_fill",
                       "occupied decode slots / slot pool size") \
                .set((event.get("slots_active") or 0)
                     / event["slots_total"])
        if event.get("generate_latency_s"):
            glat = self.histogram(
                f"{p}_serving_generate_latency_seconds",
                "end-to-end generation latency (submit -> last token); "
                "its own family so second-scale generations never "
                "pollute the predict latency series an SLO is tuned "
                "against")
            gtraces = event.get("generate_traces") or []
            for i, v in enumerate(event["generate_latency_s"]):
                glat.observe(v, exemplar=gtraces[i]
                             if i < len(gtraces) else None)
            # the segregated split (serving/generation.py): queue wait
            # for a free decode slot vs actual prefill+decode time --
            # one merged series reads slot starvation as slow decode
            for fam, field, doc in (
                    ("generate_queue_wait", "generate_queue_wait_s",
                     "generation time queued waiting for a decode slot"),
                    ("generate_decode", "generate_decode_s",
                     "generation time actually prefilling/decoding")):
                vals = event.get(field)
                if vals:
                    h = self.histogram(f"{p}_serving_{fam}_seconds", doc)
                    for i, v in enumerate(vals):
                        h.observe(v, exemplar=gtraces[i]
                                  if i < len(gtraces) else None)
        # paged-KV ticks (serving/paging.py) stamp block-pool occupancy
        # and prefix-cache hit deltas: the capacity signal ("are we
        # about to shed?") and the sharing payoff ("what fraction of
        # prefill compute did the cache absorb?")
        if event.get("kv_blocks_total"):
            occ = self.gauge(f"{p}_serving_kv_blocks",
                             "KV block-pool occupancy, by state",
                             labelnames=("state",))
            for state in ("used", "cached", "free"):
                occ.set(event.get(f"kv_blocks_{state}") or 0, state=state)
        if event.get("prefix_hits"):
            self.counter(f"{p}_serving_prefix_hits_total",
                         "prompt blocks served from the prefix cache") \
                .inc(event["prefix_hits"])
        if event.get("prefix_hit_tokens"):
            self.counter(f"{p}_serving_prefix_hit_tokens_total",
                         "prompt positions whose prefill compute the "
                         "prefix cache absorbed") \
                .inc(event["prefix_hit_tokens"])
        # speculative ticks (serving/generation.py SpeculativeScheduler)
        # stamp drafted/accepted deltas: accepted/drafted is the live
        # acceptance rate, and accepted+rounds bounds tokens-per-verify
        if event.get("spec_drafted"):
            self.counter(f"{p}_serving_spec_drafted_total",
                         "draft tokens proposed by the speculative "
                         "drafter") \
                .inc(event["spec_drafted"])
        if event.get("spec_accepted"):
            self.counter(f"{p}_serving_spec_accepted_total",
                         "draft tokens the fp32 verifier accepted") \
                .inc(event["spec_accepted"])
        if event.get("compiles"):
            self.counter(f"{p}_serving_recompiles_total",
                         "XLA compiles inside serving ticks (nonzero "
                         "after precompile = a shape leak)") \
                .inc(event["compiles"])

    def _observe_serving_info(self, info):
        """Which model version a replica serves, as the Prometheus
        version-info idiom: ``bigdl_serving_version_info{version,
        digest}`` is 1 for the currently-served version and 0 for every
        version this process served before -- a scrape (or a PromQL
        join) can always answer "which checkpoint is live?"."""
        if info.get("version") is None:
            return
        g = self.gauge(f"{self.prefix}_serving_version_info",
                       "1 on the currently-served model version",
                       labelnames=("version", "digest"))
        # zero the predecessors AND raise the new version under ONE
        # lock acquisition (render() scrapes under the same lock): a
        # scrape must never observe the all-zero in-between state
        with g._lock:
            for child in g._children.values():
                child[0] = 0.0
            g._child({"version": str(info["version"]),
                      "digest": str(info.get("digest") or "")})[0] = 1.0

    # -- deploy tier ----------------------------------------------------------- #
    def _observe_deploy(self, event):
        """Staged-rollout verdicts (serving/deploy.py): one counter per
        (stage, verdict) so a fleet dashboard sees cutovers, rejections
        and rollbacks as they land."""
        self.counter(f"{self.prefix}_deploy_total",
                     "deploy stage verdicts, by stage and outcome",
                     labelnames=("stage", "outcome")) \
            .inc(stage=str(event.get("stage", "?")),
                 outcome=str(event.get("verdict", "?")))
        if event.get("stage") == "rollback":
            self.counter(f"{self.prefix}_deploy_rollbacks_total",
                         "automatic/operator rollbacks").inc()

    # -- fleet tier ------------------------------------------------------------ #
    def _observe_fleet(self, event):
        """Replica lifecycle + breaker edges + supervisor restarts
        (serving/fleet.py).  The request-path counters
        (requests/retries/hedges/sheds) are updated DIRECTLY by the
        fleet -- they are not telemetry events -- so the bridge only
        owns the durable-event-backed series; neither side double
        counts."""
        p = self.prefix
        what = event.get("event")
        rid = str(event.get("replica", "?"))
        if what == "breaker":
            self.counter(f"{p}_fleet_breaker_transitions_total",
                         "circuit-breaker state edges, by replica and "
                         "target state",
                         labelnames=("replica", "to")) \
                .inc(replica=rid, to=str(event.get("to", "?")))
        elif what == "state":
            g = self.gauge(f"{p}_fleet_replica_state",
                           "1 on each replica's current lifecycle "
                           "state", labelnames=("replica", "state"))
            # one-hot per replica, zeroed + set under ONE lock like the
            # serving version-info gauge: a scrape never sees two
            # states (or none) active for a replica
            with g._lock:
                for key, child in g._children.items():
                    if key[0] == rid:
                        child[0] = 0.0
                g._child({"replica": rid,
                          "state": str(event.get("state", "?"))})[0] = 1.0
            if event.get("state") == "dead":
                self.counter(f"{p}_fleet_replica_deaths_total",
                             "replica processes observed dead, by "
                             "replica", labelnames=("replica",)) \
                    .inc(replica=rid)
        elif what == "restart":
            self.counter(f"{p}_fleet_restarts_total",
                         "supervisor restarts of dead replicas, by "
                         "replica", labelnames=("replica",)) \
                .inc(replica=rid)
        elif what == "wire":
            # the fleet flushes per-verb wire deltas as durable events
            # (serving/fleet.py _note_wire); counters and the RTT
            # histogram are event-backed ONLY, so replaying a
            # telemetry file into a fresh registry reproduces them
            verb = str(event.get("verb", "?"))
            c = self.counter(f"{p}_fleet_wire_bytes_total",
                             "bytes over the fleet worker wire, by "
                             "verb and direction",
                             labelnames=("verb", "direction"))
            c.inc(float(event.get("bytes_sent") or 0),
                  verb=verb, direction="sent")
            c.inc(float(event.get("bytes_recv") or 0),
                  verb=verb, direction="recv")
            h = self.histogram(f"{p}_fleet_wire_rtt_seconds",
                               "worker RPC round-trip latency, by "
                               "verb", labelnames=("verb",))
            for rtt in (event.get("rtt_s") or ())[:4096]:
                if isinstance(rtt, (int, float)):
                    h.observe(float(rtt), verb=verb)

    # -- health / anomalies --------------------------------------------------- #
    def _observe_health(self, event):
        p = self.prefix
        gn = event.get("grad_norm")
        if isinstance(gn, (int, float)) and gn == gn:
            self.gauge(f"{p}_train_grad_norm",
                       "last sampled global gradient norm").set(gn)
        nf = (event.get("nonfinite_grads") or 0) + \
            (event.get("nonfinite_params") or 0)
        if nf:
            self.counter(f"{p}_train_nonfinite_total",
                         "non-finite elements seen in health samples") \
                .inc(nf)

    def _observe_anomaly(self, event):
        self.counter(f"{self.prefix}_train_anomalies_total",
                     "watchdog findings, by watchdog",
                     labelnames=("watchdog",)) \
            .inc(watchdog=event.get("watchdog", "?"))
        # the watchdog layer drives /healthz: any finding degrades the
        # run; a halt-policy finding is exactly a halted run
        status = "halted" if event.get("policy") == "halt" else "degraded"
        self.set_health(f"watchdog:{event.get('watchdog', '?')}", status)

    # -- recovery tier -------------------------------------------------------- #
    def _observe_recovery(self, event):
        p = self.prefix
        self.counter(f"{p}_recovery_restarts_total",
                     "supervisor restarts, by cause",
                     labelnames=("cause",)) \
            .inc(cause=event.get("cause", "?"))
        if event.get("backoff_s"):
            self.counter(f"{p}_recovery_backoff_seconds_total",
                         "total backoff slept before restarts") \
                .inc(event["backoff_s"])
        if event.get("steps_replayed"):
            self.counter(f"{p}_recovery_steps_replayed_total",
                         "steps re-run after restarts") \
                .inc(event["steps_replayed"])

    def _observe_reshard(self, event):
        """Cross-layout redistributions (parallel/reshard.py): how
        often checkpoints move between mesh layouts, and how many host
        bytes/seconds each move costs -- the elastic-restart and
        layout-aware-serving-refresh audit series."""
        p = self.prefix
        self.counter(f"{p}_reshard_total",
                     "checkpoint redistributions, by src/dst layout",
                     labelnames=("src", "dst")) \
            .inc(src=str(event.get("src", "?")),
                 dst=str(event.get("dst", "?")))
        if event.get("host_bytes"):
            self.counter(f"{p}_reshard_host_bytes_total",
                         "host bytes moved by redistributions") \
                .inc(event["host_bytes"])
        if event.get("wall_s"):
            self.counter(f"{p}_reshard_seconds_total",
                         "wall seconds spent redistributing") \
                .inc(event["wall_s"])

    # -- slo tier ------------------------------------------------------------- #
    def _observe_slo(self, event):
        p = self.prefix
        obj = event.get("objective", "?")
        if event.get("breach"):
            self.counter(f"{p}_slo_breaches_total",
                         "SLO burn-rate breaches, by objective",
                         labelnames=("objective",)).inc(objective=obj)
        self.gauge(f"{p}_slo_active",
                   "1 while the objective's burn-rate alert is firing",
                   labelnames=("objective",)) \
            .set(1.0 if event.get("breach") else 0.0, objective=obj)
        status = "ok"
        if event.get("breach"):
            status = "halted" if event.get("policy") == "halt" \
                else "degraded"
        self.set_health(f"slo:{obj}", status)

    # -- memory tier ----------------------------------------------------------- #
    #: headroom fraction below which /healthz degrades (memory:headroom)
    memory_headroom_warn_fraction = 0.1

    def _observe_memory(self, event):
        """``kind: "memory"`` ledger snapshots (observability/memory.py)
        -> the ``bigdl_memory_bytes{device,subsystem}`` gauge family.
        Subsystem attribution rows carry ``device="all"`` (the ledger
        sums across devices); per-device allocator truth carries
        ``subsystem="in_use"``; the reconciliation residual is its own
        subsystem row so a leak is scrapeable as a growing gauge."""
        p = self.prefix
        g = self.gauge(f"{p}_memory_bytes",
                       "live device bytes, by owning subsystem",
                       labelnames=("device", "subsystem"))
        for name, rec in (event.get("subsystems") or {}).items():
            b = rec.get("bytes") if isinstance(rec, dict) else rec
            if b is not None:
                g.set(b, device="all", subsystem=name)
        if event.get("residual_bytes") is not None:
            g.set(event["residual_bytes"], device="all",
                  subsystem="residual")
        if event.get("live_bytes") is not None:
            g.set(event["live_bytes"], device="all", subsystem="in_use")
        for dev, rec in (event.get("devices") or {}).items():
            if isinstance(rec, dict) and rec.get("bytes_in_use") is not None:
                g.set(rec["bytes_in_use"], device=dev, subsystem="in_use")
        if event.get("headroom_bytes") is not None:
            self.gauge(f"{p}_memory_headroom_bytes",
                       "device bytes left before the allocator limit") \
                .set(event["headroom_bytes"])
        frac = event.get("headroom_fraction")
        if frac is not None:
            self.gauge(f"{p}_memory_headroom_fraction",
                       "headroom as a fraction of the allocator limit") \
                .set(frac)
            # the memory watchdog side of /healthz: burning through
            # headroom degrades the run before the OOM kills it
            self.set_health(
                "memory:headroom",
                "ok" if frac >= self.memory_headroom_warn_fraction
                else "degraded")

    def _observe_memory_dump(self, event):
        """Forensic ``kind: "memory_dump"`` events: count them (by
        reason) and degrade /healthz -- a process that dumped its
        ledger hit an allocation wall even if it survived the shed."""
        self.counter(f"{self.prefix}_memory_dumps_total",
                     "forensic memory dumps, by reason",
                     labelnames=("reason",)) \
            .inc(reason=str(event.get("reason", "?")))
        self.set_health("memory:dump", "degraded")


def render_scoped(registries, label="replica"):
    """N registries on ONE Prometheus page: every series from
    ``registries[scope]`` gets ``label="scope"`` injected, and families
    sharing a metric name across registries merge under one HELP/TYPE
    header (the text format requires each family to appear once).

    This is how N serving replicas in one process share one /metrics
    port with a ``replica=`` label instead of N ports
    (docs/observability.md, "Live metrics & SLOs").  A name registered
    with a different TYPE in two registries cannot merge -- the later
    one is skipped with a warning rather than emitting an invalid
    page."""
    families = {}
    for scope in sorted(registries, key=str):
        reg = registries[scope]
        with reg._lock:
            metrics = sorted(reg._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            fam = families.get(m.name)
            if fam is None:
                fam = families[m.name] = {"type": m.type, "help": m.help,
                                          "members": []}
            elif fam["type"] != m.type:
                log.warning(
                    "scoped render: metric %s is a %s in scope %r but "
                    "a %s elsewhere; skipping the conflicting series",
                    m.name, m.type, scope, fam["type"])
                continue
            fam["members"].append((scope, m))
    lines = []
    for name in sorted(families):
        fam = families[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for scope, m in fam["members"]:
            lines.extend(m.render_series(extra=((label, str(scope)),)))
    return "\n".join(lines) + ("\n" if lines else "")


# --------------------------------------------------------------------------- #
# The exporter: /metrics + /healthz over a real socket.
# --------------------------------------------------------------------------- #


class MetricsExporter:
    """Serve a registry on ``/metrics`` (Prometheus text format) and
    ``/healthz`` (JSON) from a daemon ``http.server`` thread.

    >>> exp = MetricsExporter(registry, port=0)     # 0 = auto-assign
    >>> exp.url                                     # http://127.0.0.1:NNN
    >>> exp.close()

    ``/healthz`` aggregates the registry's watchdog-derived conditions
    with any extra ``health_sources`` (callables returning a
    ``{"status", ...}`` dict -- ``SloTracker.health_status`` is one);
    the worst status wins.  ``ok``/``degraded`` answer 200 (degraded is
    an alert, not an outage), ``halted`` answers 503 so a naive HTTP
    prober also notices.  Scraping must never perturb the run: requests
    are handled on the server thread(s), read the registry under its
    own locks, and any handler error answers 500 instead of raising
    into the serving/training process.

    ``registry`` may instead be a DICT of label-scoped registries
    (``{"0": reg0, "1": reg1}``): one port serves all of them with a
    ``scope_label`` (default ``replica``) injected into every series
    (``render_scoped``), and ``/healthz`` aggregates worst-of across
    the scopes (ok < degraded < halted) with each reason prefixed by
    its scope -- N replicas in one process, one scrape endpoint.
    ``add_registry`` grows the scoped view live.
    """

    def __init__(self, registry, port=0, host="127.0.0.1",
                 health_sources=(), scope_label="replica"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.registry = registry
        self.scope_label = str(scope_label)
        self._scoped = isinstance(registry, dict)
        self.registries = dict(registry) if self._scoped else None
        self.health_sources = list(health_sources)
        self._t0 = time.time()
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):       # scrape spam stays out of
                pass                         # the training console

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        body = exporter.render().encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/healthz":
                        health = exporter.healthz()
                        body = (json.dumps(health, indent=2) + "\n") \
                            .encode()
                        self.send_response(
                            503 if health["status"] == "halted" else 200)
                        self.send_header("Content-Type",
                                         "application/json")
                    else:
                        body = b"try /metrics or /healthz\n"
                        self.send_response(404)
                        self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:      # scraper hung up mid-write
                    pass
                except Exception:
                    log.exception("metrics exporter request failed")
                    try:
                        self.send_error(500)
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="bigdl-metrics-exporter", daemon=True)
        self._thread.start()

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def add_health_source(self, fn):
        """Register a ``() -> {"status": ..., ...}`` callable consulted
        by ``/healthz`` (e.g. ``SloTracker.health_status``)."""
        self.health_sources.append(fn)
        return self

    def add_registry(self, scope, registry):
        """Grow a SCOPED exporter live (a replica restarted with a
        fresh registry, a late-joining replica)."""
        if not self._scoped:
            raise ValueError(
                "add_registry needs a scoped exporter (construct with "
                "a dict of registries)")
        # copy-on-write: server threads iterate self.registries in
        # render_scoped/_aggregate_health without a lock -- an in-place
        # insert would race them into "dict changed size during
        # iteration" (a failed scrape exactly when topology changes)
        self.registries = {**self.registries, str(scope): registry}
        return self

    def render(self):
        if self._scoped:
            return render_scoped(self.registries, self.scope_label)
        return self.registry.render()

    def _aggregate_health(self):
        """Worst-of across the (possibly scoped) registries."""
        if not self._scoped:
            agg = self.registry.health()
            return agg["status"], list(agg["reasons"])
        status, reasons = "ok", []
        for scope in sorted(self.registries, key=str):
            agg = self.registries[scope].health()
            s = agg["status"]
            if HEALTH_STATUSES.index(s) > HEALTH_STATUSES.index(status):
                status = s
            for r in agg["reasons"]:
                reasons.append(
                    {"reason": f"{self.scope_label}={scope}: "
                               f"{r['reason']}",
                     "status": r["status"]})
        return status, reasons

    def healthz(self):
        status, reasons = self._aggregate_health()
        for src in self.health_sources:
            try:
                extra = src()
            except Exception:
                log.exception("healthz source %r failed", src)
                continue
            s = extra.get("status", "ok")
            if s not in HEALTH_STATUSES:
                continue
            if HEALTH_STATUSES.index(s) > HEALTH_STATUSES.index(status):
                status = s
            reasons.extend(extra.get("reasons", []))
        return {"status": status, "reasons": reasons,
                "uptime_s": round(time.time() - self._t0, 3)}

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# --------------------------------------------------------------------------- #
# SLO objectives + multi-window burn-rate alerting.
# --------------------------------------------------------------------------- #


class SloObjective:
    """One declarative objective: samples of ``field`` from telemetry
    events of ``kind`` are good when ``value <op> threshold``; the run
    complies when at least ``target`` of samples are good.

    >>> SloObjective("p99_latency", kind="inference",
    ...              field="request_latency_s", threshold=0.250,
    ...              target=0.999)            # p99_latency_ms<=250 @ 99.9%
    >>> SloObjective("step_time_p50", kind="step", field="step_blocked_s",
    ...              threshold=0.5, target=0.50)   # step_time_p50<=0.5s

    ``alerts`` is the multi-window burn-rate policy: ``(short_s,
    long_s, factor)`` triples; the alert fires when the error budget
    (``1 - target``) burns at >= ``factor`` x the sustainable rate over
    BOTH windows (SRE workbook chapter 5: the long window proves it is
    real, the short window proves it is still happening -- and clears
    the alert promptly once it stops).  ``min_samples`` keeps an empty
    window from dividing noise by a tiny budget.
    """

    def __init__(self, name, kind, field, threshold, target=0.999,
                 op="<=", alerts=((60.0, 300.0, 14.4),), policy="warn",
                 min_samples=10):
        if not 0.0 < float(target) < 1.0:
            raise ValueError(f"objective {name}: target must be in (0, 1) "
                             f"-- a budget of exactly zero cannot burn")
        if op not in ("<=", ">="):
            raise ValueError(f"objective {name}: op must be '<=' or '>=', "
                             f"got {op!r}")
        if policy not in ("warn", "dump", "halt"):
            raise ValueError(f"objective {name}: unknown policy "
                             f"{policy!r}; expected warn/dump/halt")
        self.name = str(name)
        self.kind = str(kind)
        self.field = str(field)
        self.threshold = float(threshold)
        self.target = float(target)
        self.op = op
        self.alerts = tuple((float(s), float(l), float(f))
                            for s, l, f in alerts)
        for s, l, f in self.alerts:
            if s > l:
                raise ValueError(
                    f"objective {name}: alert short window {s}s exceeds "
                    f"long window {l}s")
        self.policy = policy
        self.min_samples = int(min_samples)
        self.budget = 1.0 - self.target

    def good(self, value):
        v = float(value)
        return v <= self.threshold if self.op == "<=" \
            else v >= self.threshold

    def describe(self):
        return (f"{self.field}{self.op}{self.threshold:g} at "
                f"{self.target:.4%} (kind {self.kind})")


class SloTracker:
    """Evaluate ``SloObjective``s over rolling windows; alert on burn.

    >>> tracker = SloTracker([obj1, obj2])
    >>> tracker.bind(telemetry)       # samples flow in via record()
    >>> tracker.health_status()       # {"status": "ok"|"degraded"|...}

    Each observed sample is classified good/bad and appended to the
    objective's rolling window (pruned to the longest alert window,
    additionally bounded to ``max_samples`` -- memory stays flat under
    any request rate).  On every arrival the burn rates are re-derived:
    ``burn(W) = bad_fraction(W) / (1 - target)`` -- burn 1.0 spends the
    budget exactly at the sustainable rate.  A breach (every alert
    window >= its factor) emits a durable ``kind: "slo"`` telemetry
    event on its RISING edge and applies the objective's policy --
    ``warn`` logs, ``dump`` writes an incident bundle
    (``health.dump_incident``), ``halt`` raises ``TrainingHaltedError``
    into whatever loop recorded the sample: a training driver halts
    exactly like a NaN finding (the serving dispatcher's telemetry
    guard catches it, and /healthz reports ``halted`` instead).  The
    falling edge emits a resolving ``kind: "slo"`` event
    (``breach: false``) so the JSONL carries the full burn timeline.

    ``clock`` is injectable (tests drive windows without sleeping).
    """

    def __init__(self, objectives=(), telemetry=None, registry=None,
                 clock=time.monotonic, max_samples=8192,
                 incident_dir=None):
        self.objectives = []
        self.telemetry = telemetry
        self.registry = registry
        self.clock = clock
        self.max_samples = int(max_samples)
        self.incident_dir = incident_dir
        self._lock = threading.Lock()
        self._windows = {}          # name -> deque[(t, bad)]
        self._active = {}           # name -> bool (alert currently firing)
        self._halted = set()        # objectives whose halt policy fired
        for obj in objectives:
            self.add(obj)

    def add(self, objective=None, **kw):
        """Add an ``SloObjective`` (or construct one from kwargs).
        Safe on a LIVE tracker: the window state exists (under the
        lock) before the objective becomes visible to observer threads
        -- a serving dispatcher recording matching events mid-add must
        never hit a half-registered objective."""
        from collections import deque

        if objective is None:
            objective = SloObjective(**kw)
        with self._lock:
            if any(o.name == objective.name for o in self.objectives):
                raise ValueError(
                    f"duplicate SLO objective {objective.name!r}")
            self._windows[objective.name] = deque(maxlen=self.max_samples)
            self._active[objective.name] = False
            self.objectives.append(objective)
        return objective

    def bind(self, telemetry):
        """Subscribe to a run's telemetry: every recorded event is
        offered to ``observe_event``, and breach events are emitted
        back through the same recorder (durable)."""
        self.telemetry = telemetry
        telemetry.add_observer(self.observe_event)
        return self

    # ----- sample ingestion -------------------------------------------------- #
    def observe_event(self, event):
        kind = event.get("kind")
        if kind == "slo":          # never re-ingest our own emissions
            return
        for obj in self.objectives:
            if obj.kind != kind:
                continue
            value = event.get(obj.field)
            if value is None:
                continue
            values = value if isinstance(value, (list, tuple)) else [value]
            self.observe(obj.name, values)

    def observe(self, name, values, t=None):
        """Feed samples directly (bench drills, tests); evaluates the
        objective's alerts after ingestion."""
        obj = next((o for o in self.objectives if o.name == name), None)
        if obj is None:
            raise KeyError(f"unknown SLO objective {name!r}")
        t = self.clock() if t is None else float(t)
        finding = None
        with self._lock:
            window = self._windows[name]
            for v in values:
                window.append((t, not obj.good(v)))
            finding = self._evaluate(obj, t)
        # policy runs OUTSIDE the tracker lock: dump writes files, halt
        # raises into the caller -- neither may hold up a concurrent
        # scraper reading burn gauges
        if finding is not None:
            self._apply_policy(obj, finding)

    # ----- evaluation (under self._lock) ------------------------------------- #
    def _burn(self, obj, window, horizon_s, now):
        cutoff = now - horizon_s
        total = bad = 0
        for t, is_bad in reversed(window):
            if t < cutoff:
                break
            total += 1
            bad += int(is_bad)
        if total < obj.min_samples:
            return None, total
        return (bad / total) / max(obj.budget, 1e-12), total

    def _evaluate(self, obj, now):
        """Re-derive burn rates; returns a breach/resolve finding dict
        on an edge, else None."""
        window = self._windows[obj.name]
        longest = max(l for _, l, _ in obj.alerts)
        while window and window[0][0] < now - longest:
            window.popleft()
        burns, firing = [], True
        for short_s, long_s, factor in obj.alerts:
            b_short, n_short = self._burn(obj, window, short_s, now)
            b_long, n_long = self._burn(obj, window, long_s, now)
            burns.append({"short_s": short_s, "long_s": long_s,
                          "factor": factor,
                          "burn_short": None if b_short is None
                          else round(b_short, 4),
                          "burn_long": None if b_long is None
                          else round(b_long, 4),
                          "samples": n_long})
            if b_short is None or b_long is None \
                    or b_short < factor or b_long < factor:
                firing = False
        if self.registry is not None:
            g = self.registry.gauge(
                f"{self.registry.prefix}_slo_burn_rate",
                "error-budget burn rate (1.0 = sustainable)",
                labelnames=("objective", "window"))
            for b in burns:
                if b["burn_short"] is not None:
                    g.set(b["burn_short"], objective=obj.name,
                          window=f"{b['short_s']:g}s")
                if b["burn_long"] is not None:
                    g.set(b["burn_long"], objective=obj.name,
                          window=f"{b['long_s']:g}s")
        was = self._active[obj.name]
        if firing == was:
            return None
        self._active[obj.name] = firing
        return {"objective": obj.name, "breach": firing,
                "slo": obj.describe(), "threshold": obj.threshold,
                "target": obj.target, "policy": obj.policy,
                "alerts": burns}

    # ----- policy (outside the lock) ----------------------------------------- #
    def _apply_policy(self, obj, finding):
        from bigdl_tpu.utils.errors import TrainingHaltedError

        if self.telemetry is not None:
            try:
                self.telemetry.record("slo", **finding)
            except Exception:
                log.exception("slo telemetry record failed")
        if self.registry is not None and \
                getattr(self.telemetry, "metrics", None) \
                is not self.registry:
            # the record() above only reaches the registry when the
            # telemetry bridges to THIS registry; otherwise update the
            # live series directly (never both: no double counting)
            self.registry.observe_event({"kind": "slo", **finding})
        if not finding["breach"]:
            log.info("SLO %s recovered: burn back under the alert "
                     "thresholds", obj.name)
            return
        log.warning("SLO BREACH [%s]: %s -- burn %s", obj.name,
                    finding["slo"],
                    ", ".join(f"{b['burn_short']}x/{b['short_s']:g}s + "
                              f"{b['burn_long']}x/{b['long_s']:g}s "
                              f"(>= {b['factor']}x)"
                              for b in finding["alerts"]))
        if obj.policy in ("dump", "halt") and self.incident_dir is None \
                and self.telemetry is None:
            log.warning("SLO policy %r has nowhere to write an incident "
                        "bundle (no incident_dir, no telemetry)",
                        obj.policy)
        elif obj.policy in ("dump", "halt"):
            try:
                from bigdl_tpu.observability.health import dump_incident
                import os
                root = self.incident_dir or os.path.join(
                    self.telemetry.out_dir, "incidents")
                d = dump_incident(
                    root,
                    {"watchdog": "slo", "step": 0, **finding},
                    dict(finding))
                finding["incident_dir"] = d
                log.warning("SLO incident bundle written to %s", d)
            except Exception:
                log.exception("SLO incident dump failed")
        if obj.policy == "halt":
            self._halted.add(obj.name)
            raise TrainingHaltedError(
                f"SLO watchdog halted the run: objective {obj.name} "
                f"({finding['slo']}) is burning its error budget past "
                f"every alert window")

    # ----- status surface ---------------------------------------------------- #
    def active_breaches(self):
        with self._lock:
            return sorted(n for n, a in self._active.items() if a)

    def health_status(self):
        """``{"status", "reasons"}`` for /healthz: an actively burning
        objective degrades the run; one whose halt policy fired marks
        it halted (sticky -- the run was told to stop)."""
        with self._lock:
            active = [n for n, a in self._active.items() if a]
            halted = sorted(self._halted)
        status = "ok"
        reasons = []
        for n in active:
            s = "halted" if n in halted else "degraded"
            reasons.append({"reason": f"slo:{n}", "status": s})
        for n in halted:
            if n not in active:
                reasons.append({"reason": f"slo:{n}", "status": "halted"})
        for r in reasons:
            if HEALTH_STATUSES.index(r["status"]) \
                    > HEALTH_STATUSES.index(status):
                status = r["status"]
        return {"status": status, "reasons": reasons}
