"""Structured per-step training telemetry (JSONL) + run metadata.

One ``StepTelemetry`` instance owns a run directory and produces:

- ``telemetry.jsonl`` -- one JSON object per line.  The first event is
  the run header (``kind: "header"``: devices, platform, jax version,
  and the compiled step's ``cost_analysis`` flops/bytes when attached);
  every training step appends a ``kind: "step"`` event carrying the
  split timers (``wall_s`` / ``data_wait_s`` / ``device_s``), loss,
  ``records_per_s``, epoch/step counters, per-device memory stats,
  the deferred-loss-sync staleness (``sync_skew``, 0 when the loss is
  fresh) and -- when a ``PrefetchDataSet`` feeds the run -- the
  prefetch queue occupancy (``queue_depth`` / ``queue_capacity``).
- ``trace.json`` -- chrome-trace host spans (see ``spans.SpanTracer``),
  viewable in Perfetto next to the device xplane traces.

The watchdogs (``watchdogs.py``) ride on the same step cadence:
``step_begin``/``record_step`` bracket the no-compile window for the
recompile detector, and each step's ``bytes_in_use`` feeds the
memory-growth detector.  When a ``HealthMonitor`` is attached
(``health.py``), sampled steps additionally append ``kind: "health"``
numerics events (grad norms, update ratios, non-finite counts) and
``kind: "anomaly"`` watchdog findings -- both fsynced on write, so a
run that dies right after detecting its own divergence still leaves
the evidence on disk.  ``tools/obs_report.py`` merges the JSONL with
an xplane trace into one run report.

The recorder is driver-agnostic: the shared driver loop
(``optim/local_optimizer.py:_run_driver_loop``) emits the events, so
Local/Distri/Strategy training all produce the identical schema.
"""

import json
import logging
import os
import threading
import time

from bigdl_tpu.observability.spans import SpanTracer
from bigdl_tpu.observability.watchdogs import (MemoryWatchdog,
                                               RecompileWatchdog)

#: JSONL schema version (bump on breaking key changes)
SCHEMA_VERSION = 1

#: event kinds that must survive a crash on the NEXT line: flushed AND
#: fsynced to disk the moment they are recorded (a run that blows up
#: right after a health anomaly must leave the evidence on disk; a
#: timing-audit verdict is the line a perf claim stands on; a recovery
#: event is the record of a restart whose successor may itself die; an
#: slo breach under the halt policy is about to END the run; a reshard
#: event is the audit trail of a cross-layout restore whose run may
#: die before its first step; a deploy event is the stage/rollback
#: verdict of a live version swap -- the line the chaos drill audits
#: after SIGKILLing the server mid-cutover; a fleet event is a replica
#: lifecycle/breaker edge whose process may be SIGKILLed the next
#: instant -- the breaker open->half_open->closed trail the fleet
#: drill audits post-mortem; a memory event is the headroom timeline
#: an OOM'd run is judged by, and a memory_dump is the forensic ledger
#: written precisely because the process is about to die)
DURABLE_KINDS = frozenset({"health", "anomaly", "timing_audit",
                           "recovery", "slo", "reshard", "deploy",
                           "fleet", "memory", "memory_dump"})

log = logging.getLogger("bigdl_tpu.observability")


def peak_flops(device=None):
    """Peak bf16 FLOP/s for a device kind (bench.py's table); CPU and
    unknown hosts get a nominal 1 TFLOP/s so MFU stays computable (and
    obviously not chip-meaningful)."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    platform = getattr(device, "platform", "cpu")
    if platform != "tpu":
        return 1e12
    if "v6" in kind:
        return 918e12
    if "v5p" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    return 197e12  # v5e and unknown TPUs


def device_memory_stats():
    """Per-device ``{label: {"bytes_in_use", "peak_bytes_in_use"}}``, or
    None where the backend exposes no allocator stats (CPU)."""
    import jax

    out = {}
    for d in jax.devices():
        try:
            s = d.memory_stats()
        except Exception:
            s = None
        if not s:
            continue
        rec = {}
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in s:
                rec[key] = int(s[key])
        if rec:
            out[f"{d.platform}:{d.id}"] = rec
    return out or None


def _normalize_cost(analysis):
    """``compiled.cost_analysis()`` returns a dict (or a 1-list of dicts
    on older jax); pull out the portable totals."""
    if analysis is None:
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    out = {}
    if "flops" in analysis:
        out["flops_per_step"] = float(analysis["flops"])
    if "bytes accessed" in analysis:
        out["bytes_accessed_per_step"] = float(analysis["bytes accessed"])
    return out or None


class StepTelemetry:
    """Per-run structured telemetry recorder.

    >>> tel = StepTelemetry(run_dir)
    >>> opt.set_telemetry(tel)         # any of the optimizer drivers
    >>> opt.optimize()
    >>> tel.close()

    The driver loop calls ``step_begin``/``record_step`` around every
    step and ``flush`` when training ends, so artifacts are complete
    even if the caller forgets ``close()``.
    """

    def __init__(self, out_dir, run_name="train", trace=True,
                 recompile_warmup_steps=1, memory_window=25,
                 metrics=None):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.run_name = run_name
        self.jsonl_path = os.path.join(out_dir, "telemetry.jsonl")
        # truncate: one run dir = one run (two appended headers would
        # silently merge runs in obs_report); pick a fresh dir to keep
        # a previous attempt's artifacts
        self._f = open(self.jsonl_path, "w")
        self.tracer = SpanTracer(os.path.join(out_dir, "trace.json")) \
            if trace else None
        # distributed request-trace spans (docs/observability.md,
        # "Request tracing"): opened lazily on the first record_trace
        # so runs without serving traces leave no empty artifact
        self.traces_path = os.path.join(out_dir, "traces.jsonl")
        self._traces_f = None
        self._traces_lock = threading.Lock()
        self.recompile_watchdog = RecompileWatchdog(recompile_warmup_steps)
        self.memory_watchdog = MemoryWatchdog(memory_window)
        # sampled at construction -- BEFORE this run's own compiles land
        # in the cache dir, which a lazy header write would miscount
        from bigdl_tpu.utils.config import compilation_cache_status
        self._cache_status = compilation_cache_status()
        self._cost = None
        self._compiled_step = None
        self._memory_budget = None
        self._timing = None
        self._serving_info = None
        self._wrote_header = False
        self._closed = False
        # a ServingEngine records inference events from its dispatcher
        # thread while the owning thread may be training against the
        # same run dir: serialize the lazy header write and the JSONL
        # appends (reentrant -- record() calls write_header())
        self._write_lock = threading.RLock()
        # live-telemetry observers (docs/observability.md, "Live
        # metrics & SLOs"): every recorded event is offered to each
        self._observers = []
        self.metrics = None
        if metrics is not None:
            self.attach_metrics(metrics)

    # ----- generic event plumbing ----------------------------------------- #
    def add_observer(self, fn):
        """Subscribe ``fn(event_dict)`` to every recorded event -- the
        seam live consumers ride: a ``MetricsRegistry`` bridge turns
        events into scrapeable series, an ``SloTracker`` classifies
        them against objectives.  Observers run AFTER the line is on
        disk; an observer exception is logged and swallowed EXCEPT
        ``TrainingHaltedError`` -- that is an SLO/watchdog halt policy
        firing, and it must propagate into the recording loop exactly
        like a NaN finding does."""
        self._observers.append(fn)
        return self

    def attach_metrics(self, registry):
        """Bridge this run's events onto a live ``MetricsRegistry``
        (``observability/metrics.py``): serving ticks, training steps,
        health samples, anomalies and recovery events all become
        current Prometheus series a ``MetricsExporter`` can serve.
        Idempotent: re-attaching the registry already bridged (e.g.
        ``metrics=`` at construction AND an explicit call) must not
        subscribe it twice and double-count every counter."""
        if registry is self.metrics:
            return self
        self.metrics = registry
        return self.add_observer(registry.observe_event)

    def _notify(self, event):
        if not self._observers:
            return
        from bigdl_tpu.utils.errors import TrainingHaltedError
        for fn in self._observers:
            try:
                fn(event)
            except TrainingHaltedError:
                raise          # a halt-policy breach ends the run
            except Exception:
                log.exception("telemetry observer %r failed on a %r "
                              "event", fn, event.get("kind"))

    def record(self, kind, **fields):
        """Append one JSONL event (header is written lazily first).
        Health/anomaly/incident events are additionally fsynced: they
        are exactly the lines a crashing run must not lose."""
        with self._write_lock:
            if self._closed:
                # a still-running serving dispatcher may outlive the
                # owner's close(); dropping the event beats raising
                # "I/O operation on closed file" into its tick -- but a
                # DURABLE kind is exactly the line a run must not lose,
                # so its loss is at least loud
                if kind in DURABLE_KINDS:
                    log.warning(
                        "dropping %r telemetry event recorded after "
                        "close(): %s", kind, json.dumps(fields, default=str))
                return None
            if kind != "header" and not self._wrote_header:
                self.write_header()
            event = {"kind": kind, "ts": time.time(), **fields}
            self._f.write(json.dumps(event) + "\n")
            self._f.flush()
            if kind in DURABLE_KINDS:
                try:
                    os.fsync(self._f.fileno())
                except OSError:  # pragma: no cover - exotic filesystems
                    pass
        # observers run with the line already durable on disk, outside
        # the write lock where possible (a nested write_header call
        # still holds it -- the lock is reentrant and observers never
        # block on telemetry)
        self._notify(event)
        return event

    def write_header(self, **extra):
        """Run-level metadata event; called lazily before the first step
        (or eagerly by a driver once the compiled step's cost is known)."""
        with self._write_lock:   # held through the record() below, so a
            if self._wrote_header:   # concurrent first event can't land
                return None          # ahead of the header line
            self._wrote_header = True
            fields = {"run": self.run_name, "schema_version": SCHEMA_VERSION}
            try:
                import jax
                dev = jax.devices()[0]
                fields.update(
                    jax_version=jax.__version__,
                    platform=dev.platform,
                    device_kind=getattr(dev, "device_kind", ""),
                    device_count=jax.device_count(),
                    process_count=jax.process_count(),
                    peak_flops=peak_flops(dev))
            except Exception:
                pass
            try:
                # per-device allocator stats at run start, bounded to 8
                # devices so a big pod doesn't bloat every header; None
                # (CPU backends expose no memory_stats) is silently
                # fine -- no warning spam for the common host case
                mem = device_memory_stats()
            except Exception:
                mem = None
            if mem:
                labels = sorted(mem)
                fields["device_memory"] = {d: mem[d] for d in labels[:8]}
                if len(labels) > 8:
                    fields["device_memory_devices"] = len(labels)
            if self._memory_budget:
                # the compiled executable's static memory budget
                # (attach_cost + utils/hlo.memory_analysis_summary):
                # argument/output/temp/generated bytes, the number the
                # live MemoryLedger residual is read against
                fields["memory_budget"] = self._memory_budget
            if self._cache_status is not None:
                # hit/miss note for the run report: a warm cache means the
                # big XLA compiles were (probably) skipped this run
                fields["compilation_cache"] = self._cache_status
            if self._timing is not None:
                # the run's timing discipline (set_timing_mode): under
                # "blocking", step_blocked_s is the trust basis for any
                # MFU derived from this run's events
                fields["timing"] = self._timing
            if self._serving_info is not None:
                # which precision serves this run (ServingEngine stamps
                # it: quantized flag, weight dtype, model bytes) -- the
                # obs_report Serving section reads this
                fields["serving"] = self._serving_info
            if self._cost:
                fields["cost"] = self._cost
            if self._compiled_step:
                # the lowering-text audit (attach_cost): donation
                # coverage, dot/conv dtypes, collectives -- the
                # obs_report "Compiled step" section reads this
                fields["compiled_step"] = self._compiled_step
            fields.update(extra)
            return self.record("header", **fields)

    def set_timing_mode(self, mode, basis="step_blocked_s"):
        """Stamp the run's timing discipline on the header:
        ``timing: {"mode": "blocking", "trust_basis": "step_blocked_s"}``.
        Drivers call this when ``set_blocking_timing(True)`` is active,
        BEFORE the lazy header write; if the header already went out
        (e.g. ``attach_cost`` wrote it first), a standalone
        ``kind: "timing"`` event records the mode instead -- obs_report
        reads both (docs/observability.md, Profiling & trusted timing).
        """
        timing = {"mode": mode, "trust_basis": basis}
        with self._write_lock:
            if self._timing == timing:
                return None
            self._timing = timing
            if self._wrote_header:
                return self.record("timing", timing=timing)
        return None

    def set_serving_info(self, info):
        """Stamp the serving precision block on the header:
        ``serving: {quantized, weight_dtype, model_bytes, ...}``
        (``ServingEngine`` calls this at construction and after every
        successful ``refresh_params``).  If the header already went out
        (e.g. the engine shares a run with a training driver whose
        ``attach_cost`` wrote it first), a standalone
        ``kind: "serving_info"`` event records it instead -- obs_report
        reads both (docs/observability.md, "Serving telemetry")."""
        info = dict(info)
        with self._write_lock:
            if self._serving_info == info:
                return None
            self._serving_info = info
            if self._wrote_header:
                return self.record("serving_info", serving=info)
        return None

    @property
    def cost(self):
        """The attached compiled-step cost block (``attach_cost``), or
        None -- the flops source the end-of-run timing audit reads."""
        return self._cost

    # ----- step cadence ---------------------------------------------------- #
    def step_begin(self, step):
        """Open the no-compile window (call right before dispatch)."""
        self.recompile_watchdog.step_begin(step)

    def record_step(self, event):
        """Close the step window and append the step event.

        ``event`` must carry ``step``, ``wall_s``, ``data_wait_s`` and
        ``records_per_s`` (the documented schema); memory stats and any
        watchdog findings are attached here.
        """
        wd = self.recompile_watchdog
        compiles = wd.step_end(event.get("step"))
        if compiles:
            # "compiles": any backend compile inside the step window
            # (warmup included); "recompiles": only watchdog-FLAGGED
            # post-warmup compiles -- what reports alarm on
            event["compiles"] = compiles
            if wd.events and wd.events[-1]["step"] == event.get("step"):
                event["recompiles"] = compiles
        mem = device_memory_stats()
        if mem:
            event["memory"] = mem
            flagged = self.memory_watchdog.observe(
                event.get("step"),
                {dev: s["bytes_in_use"] for dev, s in mem.items()
                 if "bytes_in_use" in s})
            if flagged:
                event["memory_growth"] = flagged
        return self.record("step", **event)

    # ----- compiled-step cost ---------------------------------------------- #
    def attach_cost(self, jitted, *example_args, records_per_step=None,
                    arg_labels=None, memory_budget=False):
        """Lower the step for ``cost_analysis`` and put the flops/bytes
        totals on the run header.  The lowering's own cost analysis is
        preferred -- it needs no backend compile, so enabling telemetry
        does not pay the train step's XLA compile twice; only when the
        lowering exposes nothing is the AOT compile consulted.  Failure
        is never fatal -- cost is an annotation, not a dependency.

        The same lowering additionally feeds the compiled-step audit
        (``utils/hlo.py``, docs/observability.md "Compiled step
        audit"): per-plane buffer-donation coverage, dot/conv dtypes
        and collective counts parsed from the lowering TEXT (still no
        backend compile), stamped on the header as ``compiled_step``.
        ``arg_labels`` names the step's positional args (``("params",
        "mstate", "opt_state", ...)``) so the coverage reads per plane;
        the drivers all pass theirs.

        ``memory_budget=True`` additionally AOT-compiles the step and
        stamps its ``memory_analysis()`` (argument/output/temp/
        generated bytes, via ``utils/hlo.memory_analysis_summary``) on
        the header as ``memory_budget`` -- the static side of the live
        ``MemoryLedger``.  This pays one backend compile (usually
        served by the compilation cache); when the cost fallback
        already compiled, the same executable is reused for free."""
        try:
            lowered = jitted.lower(*example_args)
        except Exception:
            return None
        try:
            from bigdl_tpu.utils import hlo
            self._compiled_step = hlo.lowering_summary(
                lowered, example_args, arg_labels=arg_labels)
        except Exception:       # the audit is an annotation, like cost
            self._compiled_step = None
        compiled = None
        try:
            cost = _normalize_cost(lowered.cost_analysis())
        except Exception:
            cost = None
        if cost is None:
            try:
                compiled = lowered.compile()
                cost = _normalize_cost(compiled.cost_analysis())
            except Exception:
                cost = None
        if memory_budget and compiled is None:
            try:
                compiled = lowered.compile()
            except Exception:
                compiled = None
        if compiled is not None:
            try:
                from bigdl_tpu.utils import hlo
                self._memory_budget = hlo.memory_analysis_summary(compiled)
            except Exception:   # an annotation, never fatal
                self._memory_budget = None
        if cost is None and self._compiled_step is None \
                and self._memory_budget is None:
            return None
        if cost is not None and records_per_step:
            cost["records_per_step"] = int(records_per_step)
        self._cost = cost
        if not self._wrote_header:
            self.write_header()           # header carries the cost block
        else:
            fields = {"cost": cost}
            if self._compiled_step is not None:
                fields["compiled_step"] = self._compiled_step
            if self._memory_budget is not None:
                fields["memory_budget"] = self._memory_budget
            self.record("cost", **fields)
        return cost

    # ----- distributed request traces --------------------------------------- #
    def record_trace(self, name, ctx, t_wall, dur_s, status="ok",
                     **fields):
        """Append one request-trace span record to ``traces.jsonl``.

        ``ctx`` is a ``tracing.TraceContext`` (span identity),
        ``t_wall``/``dur_s`` the span's wall-clock start and duration.
        JSONL by design: a SIGKILLed process loses at most the line
        being written -- every flushed span of a dead worker is still
        stitchable by ``tools/trace_report.py``.  When a chrome tracer
        is attached the span is mirrored into ``trace.json`` too, so
        one Perfetto tab shows request spans next to host stages.
        """
        rec = {"trace": ctx.trace_id, "span": ctx.span_id,
               "parent": ctx.parent_id, "name": name,
               "ts": round(float(t_wall), 6),
               "dur_s": round(float(dur_s), 6), "status": status,
               "process": self.run_name, "pid": os.getpid()}
        if fields:
            rec.update(fields)
        with self._traces_lock:
            if self._closed:
                return None
            if self._traces_f is None:
                self._traces_f = open(self.traces_path, "w")
            self._traces_f.write(json.dumps(rec, default=str) + "\n")
            self._traces_f.flush()
        if self.tracer is not None:
            args = {"trace": ctx.trace_id, "status": status}
            if fields:
                args.update(fields)
            self.tracer.complete_at(name, t_wall, dur_s, **args)
        return rec

    # ----- spans ------------------------------------------------------------ #
    def span(self, name, **args):
        import contextlib

        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name, **args)

    # ----- lifecycle -------------------------------------------------------- #
    def flush(self):
        with self._write_lock:   # same shared-owner ordering as record():
            if not self._closed:     # a finally-path flush after another
                self._f.flush()      # owner's close() must not raise
        with self._traces_lock:
            if self._traces_f is not None and not self._traces_f.closed:
                self._traces_f.flush()
        if self.tracer is not None:
            self.tracer.flush()

    def close(self):
        with self._write_lock:            # don't close the file out from
            if self._closed:              # under a mid-record dispatcher
                return
            if not self._wrote_header:
                self.write_header()
            self._closed = True
            self._f.flush()
            try:
                os.fsync(self._f.fileno())  # the artifact is the deliverable
            except OSError:  # pragma: no cover - exotic filesystems
                pass
            self._f.close()
        with self._traces_lock:
            if self._traces_f is not None and not self._traces_f.closed:
                self._traces_f.flush()
                try:
                    os.fsync(self._traces_f.fileno())
                except OSError:  # pragma: no cover - exotic filesystems
                    pass
                self._traces_f.close()
        if self.tracer is not None:
            self.tracer.close()           # deactivates + terminates JSON

    def __enter__(self):
        """Context use additionally makes the tracer ambient, so
        module-level ``span()`` calls anywhere (user code, serving)
        land in this run's trace until exit."""
        if self.tracer is not None:
            self.tracer.activate()
        return self

    def __exit__(self, *exc):
        self.close()
        return False
