"""Silent-failure watchdogs: recompiles, memory growth, bad numerics.

Things go wrong on an accelerator without any exception being raised:
the jitted step silently recompiles every iteration (a shape or
static-arg leak -- each "step" is now a multi-second XLA compile),
device memory creeps up until an OOM hundreds of steps later, a
gradient goes non-finite and poisons the params long before the loss
shows it, or the loss spikes off its trend.  All are invisible in loss
curves at the moment they start; all are cheap to detect on the host.

``RecompileWatchdog`` counts backend compiles per step window via
``jax.monitoring``'s duration listener (every real XLA compile emits
``/jax/core/compile/backend_compile_duration``); where that API is
unavailable it falls back to polling the jit cache size of explicitly
``watch()``-ed functions.  Any compile after the warmup steps logs a
WARNING with the offending step number.

``MemoryWatchdog`` tracks per-device ``bytes_in_use`` and flags a
monotonic increase sustained across N consecutive observations.

``NonFiniteWatchdog`` / ``LossSpikeWatchdog`` ride the sampled numerics
stream (``health.HealthMonitor`` feeds them each ``health`` event) and
back the warn/dump/halt anomaly policy -- see docs/observability.md.
"""

import logging
import math
import threading

log = logging.getLogger("bigdl_tpu.observability")

#: duration events that indicate a real backend (XLA) compile
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_counter_lock = threading.Lock()
_compile_count = 0
_listener_state = None  # None = not tried, True = active, False = unavailable


def _on_duration(name, duration_secs=None, **kwargs):
    global _compile_count
    if name == _COMPILE_EVENT:
        with _counter_lock:
            _compile_count += 1


def _ensure_listener():
    """Register the (process-global, permanent) compile listener once."""
    global _listener_state
    if _listener_state is not None:
        return _listener_state
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_duration)
        _listener_state = True
    except Exception:  # pragma: no cover - jax without monitoring
        _listener_state = False
    return _listener_state


def backend_compile_count():
    """Process-wide count of backend compiles seen by the listener."""
    _ensure_listener()
    with _counter_lock:
        return _compile_count


class RecompileWatchdog:
    """Flags backend compiles that happen after warmup.

    Drive it with ``step_begin(step)`` / ``step_end(step)`` around the
    window where NO compile is expected (dispatch + loss sync in the
    driver loop; validation/checkpoint compiles stay outside the window
    and are never false-flagged).  The first ``warmup_steps`` completed
    steps are exempt -- that is where the train step legitimately
    compiles.
    """

    def __init__(self, warmup_steps=1):
        self.warmup_steps = warmup_steps
        self.events = []          # [{"step", "compiles"}] -- one per firing
        self._watched = []        # jitted fns for the cache-size fallback
        self._begin = None
        self._steps_seen = 0
        self._use_monitoring = _ensure_listener()

    def watch(self, fn):
        """Register a jitted function whose cache size becomes the
        compile signal.  Preferred over the process-global monitoring
        counter: cache growth is PER-FUNCTION, so a concurrent thread
        compiling something else (e.g. a serving request with a new
        shape) can never be misattributed to the training step."""
        if hasattr(fn, "_cache_size"):
            self._watched.append(fn)
        return fn

    def _signal(self):
        if self._watched:
            return sum(f._cache_size() for f in self._watched)
        if self._use_monitoring:
            return backend_compile_count()
        return 0

    def step_begin(self, step):
        self._begin = self._signal()

    def step_end(self, step):
        """Close the step window; returns the number of compiles seen
        inside it (0 when clean), WARNING-logging post-warmup compiles."""
        if self._begin is None:
            return 0
        delta = self._signal() - self._begin
        self._begin = None
        self._steps_seen += 1
        if delta > 0 and self._steps_seen > self.warmup_steps:
            self.events.append({"step": step, "compiles": delta})
            log.warning(
                "recompile detected at step %d (%d backend compile%s inside "
                "the step window): a shape or static argument is changing "
                "per step -- every such step pays a full XLA compile",
                step, delta, "s" if delta > 1 else "")
        return delta


class MemoryWatchdog:
    """Flags monotonic device-memory growth sustained over ``window``
    consecutive observations (a leak signature: steady-state training
    should plateau after the first steps)."""

    def __init__(self, window=25):
        self.window = window
        self.events = []          # [{"step", "device", "bytes_in_use"}]
        self._last = {}
        self._streak = {}

    def observe(self, step, bytes_in_use_by_device):
        """Feed ``{device_label: bytes_in_use}`` for one step; returns
        the devices flagged this call (usually empty)."""
        flagged = []
        for dev, used in (bytes_in_use_by_device or {}).items():
            prev = self._last.get(dev)
            self._last[dev] = used
            if prev is not None and used > prev:
                self._streak[dev] = self._streak.get(dev, 0) + 1
            else:
                self._streak[dev] = 0
            if self._streak[dev] >= self.window:
                self._streak[dev] = 0      # re-arm: fire again after N more
                self.events.append(
                    {"step": step, "device": dev, "bytes_in_use": used})
                flagged.append(dev)
                log.warning(
                    "device %s memory grew monotonically for %d consecutive "
                    "steps (now %.1f MiB in use) at step %d -- possible "
                    "leak (host-retained device arrays, growing cache, or "
                    "per-step constants)",
                    dev, self.window, used / 2**20, step)
        return flagged


class NonFiniteWatchdog:
    """Flags the first (and every) health sample carrying non-finite
    numerics: NaN/Inf in gradients, in the updated params, or in the
    loss itself.  Because the stats are sampled every ``stats_every``
    steps INSIDE the compiled step, the firing step bounds when the
    numerics went bad to one sampling window -- versus the many-steps-
    later NaN loss that is otherwise the first visible symptom."""

    def __init__(self):
        self.events = []
        self.first_step = None        # first sampled step seen non-finite

    def observe(self, step, event):
        """Feed one ``health`` event dict; returns a finding dict when
        the sample carries non-finite values, else None."""
        nf_g = int(event.get("nonfinite_grads", 0))
        nf_p = int(event.get("nonfinite_params", 0))
        loss = event.get("loss")
        loss_bad = loss is not None and not math.isfinite(loss)
        gn = event.get("grad_norm")
        gn_bad = gn is not None and not math.isfinite(gn)
        if not (nf_g or nf_p or loss_bad or gn_bad):
            return None
        if self.first_step is None:
            self.first_step = step
        worst = event.get("worst_layer")
        finding = {
            "watchdog": "nonfinite", "step": step,
            "nonfinite_grads": nf_g, "nonfinite_params": nf_p,
            "loss_finite": not loss_bad, "worst_layer": worst,
            "reason": "non-finite numerics (layer %s)" % worst,
        }
        self.events.append(finding)
        log.warning(
            "non-finite numerics at step %d: %d grad / %d param elements "
            "non-finite%s, worst layer %s -- the divergence started within "
            "the last sampling window",
            step, nf_g, nf_p, "" if not loss_bad else " (loss non-finite)",
            worst)
        return finding


class LossSpikeWatchdog:
    """Flags a loss that jumps ``sigma`` standard deviations above its
    exponential moving average (EMA of the loss + EMA of its squared
    deviation, bias-corrected).  The first ``warmup`` samples only train
    the EMAs -- early training legitimately moves fast."""

    def __init__(self, sigma=6.0, beta=0.9, warmup=5):
        self.sigma = float(sigma)
        self.beta = float(beta)
        self.warmup = int(warmup)
        self.events = []
        self._mean = 0.0
        self._var = 0.0
        self._n = 0

    def observe(self, step, loss):
        """Feed one sampled loss; returns a finding dict on a spike,
        else None.  Non-finite losses are NonFiniteWatchdog's business
        and only reset nothing here (the EMAs ignore them)."""
        if loss is None or not math.isfinite(loss):
            return None
        finding = None
        if self._n >= self.warmup:
            bc = 1.0 - self.beta ** self._n      # bias correction
            mean = self._mean / bc
            sd = math.sqrt(max(self._var / bc, 0.0))
            # absolute + relative floor: a perfectly flat loss stream
            # must not flag numeric dust as a "spike"
            sd = max(sd, 1e-8, 1e-3 * abs(mean))
            threshold = mean + self.sigma * sd
            if loss > threshold:
                finding = {
                    "watchdog": "loss_spike", "step": step,
                    "loss": float(loss), "ema": mean, "sd": sd,
                    "sigma": self.sigma,
                    "reason": "loss %.6g > EMA %.6g + %g sigma (%.6g)"
                              % (loss, mean, self.sigma, threshold),
                }
                self.events.append(finding)
                log.warning(
                    "loss spike at step %d: %.6g vs EMA %.6g (+%.1f sigma "
                    "threshold %.6g)", step, loss, mean, self.sigma,
                    threshold)
        # the spiked value still feeds the EMAs: a persistent new level
        # re-normalizes instead of firing forever
        self._mean = self.beta * self._mean + (1 - self.beta) * loss
        # _mean now aggregates n+1 samples -- correct with beta**(n+1):
        # a stale beta**n here seeds phantom variance on a flat stream,
        # masking real spikes for dozens of samples after warmup
        bc = 1.0 - self.beta ** (self._n + 1)
        dev = loss - self._mean / bc
        self._var = self.beta * self._var + (1 - self.beta) * dev * dev
        self._n += 1
        return finding
