"""Silent-failure watchdogs: recompiles and device-memory growth.

Two things go wrong on an accelerator without any exception being
raised: the jitted step silently recompiles every iteration (a shape or
static-arg leak -- each "step" is now a multi-second XLA compile), and
device memory creeps up until an OOM hundreds of steps later.  Both are
invisible in loss curves; both are cheap to detect on the host.

``RecompileWatchdog`` counts backend compiles per step window via
``jax.monitoring``'s duration listener (every real XLA compile emits
``/jax/core/compile/backend_compile_duration``); where that API is
unavailable it falls back to polling the jit cache size of explicitly
``watch()``-ed functions.  Any compile after the warmup steps logs a
WARNING with the offending step number.

``MemoryWatchdog`` tracks per-device ``bytes_in_use`` and flags a
monotonic increase sustained across N consecutive observations.
"""

import logging
import threading

log = logging.getLogger("bigdl_tpu.observability")

#: duration events that indicate a real backend (XLA) compile
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_counter_lock = threading.Lock()
_compile_count = 0
_listener_state = None  # None = not tried, True = active, False = unavailable


def _on_duration(name, duration_secs=None, **kwargs):
    global _compile_count
    if name == _COMPILE_EVENT:
        with _counter_lock:
            _compile_count += 1


def _ensure_listener():
    """Register the (process-global, permanent) compile listener once."""
    global _listener_state
    if _listener_state is not None:
        return _listener_state
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_duration)
        _listener_state = True
    except Exception:  # pragma: no cover - jax without monitoring
        _listener_state = False
    return _listener_state


def backend_compile_count():
    """Process-wide count of backend compiles seen by the listener."""
    _ensure_listener()
    with _counter_lock:
        return _compile_count


class RecompileWatchdog:
    """Flags backend compiles that happen after warmup.

    Drive it with ``step_begin(step)`` / ``step_end(step)`` around the
    window where NO compile is expected (dispatch + loss sync in the
    driver loop; validation/checkpoint compiles stay outside the window
    and are never false-flagged).  The first ``warmup_steps`` completed
    steps are exempt -- that is where the train step legitimately
    compiles.
    """

    def __init__(self, warmup_steps=1):
        self.warmup_steps = warmup_steps
        self.events = []          # [{"step", "compiles"}] -- one per firing
        self._watched = []        # jitted fns for the cache-size fallback
        self._begin = None
        self._steps_seen = 0
        self._use_monitoring = _ensure_listener()

    def watch(self, fn):
        """Register a jitted function whose cache size becomes the
        compile signal.  Preferred over the process-global monitoring
        counter: cache growth is PER-FUNCTION, so a concurrent thread
        compiling something else (e.g. a serving request with a new
        shape) can never be misattributed to the training step."""
        if hasattr(fn, "_cache_size"):
            self._watched.append(fn)
        return fn

    def _signal(self):
        if self._watched:
            return sum(f._cache_size() for f in self._watched)
        if self._use_monitoring:
            return backend_compile_count()
        return 0

    def step_begin(self, step):
        self._begin = self._signal()

    def step_end(self, step):
        """Close the step window; returns the number of compiles seen
        inside it (0 when clean), WARNING-logging post-warmup compiles."""
        if self._begin is None:
            return 0
        delta = self._signal() - self._begin
        self._begin = None
        self._steps_seen += 1
        if delta > 0 and self._steps_seen > self.warmup_steps:
            self.events.append({"step": step, "compiles": delta})
            log.warning(
                "recompile detected at step %d (%d backend compile%s inside "
                "the step window): a shape or static argument is changing "
                "per step -- every such step pays a full XLA compile",
                step, delta, "s" if delta > 1 else "")
        return delta


class MemoryWatchdog:
    """Flags monotonic device-memory growth sustained over ``window``
    consecutive observations (a leak signature: steady-state training
    should plateau after the first steps)."""

    def __init__(self, window=25):
        self.window = window
        self.events = []          # [{"step", "device", "bytes_in_use"}]
        self._last = {}
        self._streak = {}

    def observe(self, step, bytes_in_use_by_device):
        """Feed ``{device_label: bytes_in_use}`` for one step; returns
        the devices flagged this call (usually empty)."""
        flagged = []
        for dev, used in (bytes_in_use_by_device or {}).items():
            prev = self._last.get(dev)
            self._last[dev] = used
            if prev is not None and used > prev:
                self._streak[dev] = self._streak.get(dev, 0) + 1
            else:
                self._streak[dev] = 0
            if self._streak[dev] >= self.window:
                self._streak[dev] = 0      # re-arm: fire again after N more
                self.events.append(
                    {"step": step, "device": dev, "bytes_in_use": used})
                flagged.append(dev)
                log.warning(
                    "device %s memory grew monotonically for %d consecutive "
                    "steps (now %.1f MiB in use) at step %d -- possible "
                    "leak (host-retained device arrays, growing cache, or "
                    "per-step constants)",
                    dev, self.window, used / 2**20, step)
        return flagged
