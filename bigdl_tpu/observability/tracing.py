"""Per-request distributed trace context for the serving stack.

A ``TraceContext`` is minted once per request at the fleet edge
(``ServingFleet._request``) and flows DOWN the serving stack: routing
attempts, hedges and retries become child spans, the context crosses
the ``serving/worker.py`` socket protocol as a versioned ``trace``
field (W3C-traceparent encoding inside, so a future cross-host
transport can interop), and lands in ``ServingEngine`` /
``GenerateScheduler`` where batch ticks record span links back to
every request riding them.  Span records are durable JSONL lines
(``traces.jsonl``, written by ``StepTelemetry.record_trace``) plus a
chrome-trace mirror when a ``SpanTracer`` is attached;
``tools/trace_report.py`` stitches the records back into per-request
critical paths by trace_id.

Sampling is head-based: the root mints ``sampled`` from a
``HeadSampler`` and every child inherits the bit.  The root-side
buffer (``RequestTrace``) defers the final keep/drop decision to
request completion, so errors, shed requests and p99-tail latencies
can FORCE an unsampled trace onto disk -- the interesting tails are
never lost.  Only the fleet-local spans of a late-forced trace exist
(the wire carries the context only when ``sampled`` is already true);
that is the documented trade for keeping the unsampled path free of
remote work.

No jax import, stdlib only: tools spec-load this file by path.
"""

import os
import random
import threading
import time

#: version of the wire dict carrying the context across the socket
#: protocol; unknown higher versions still parse the traceparent field
WIRE_VERSION = 1

#: env knob for the default head-sample rate (fraction of requests)
TRACE_SAMPLE_ENV = "BIGDL_TRACE_SAMPLE"
_DEFAULT_RATE = 0.01

# one process-wide RNG, seeded once from the OS: minting ids must not
# cost a urandom syscall per request (the no-op-path microbench guards
# the whole mint at microseconds)
_rng = random.Random()
_rng.seed(int.from_bytes(os.urandom(16), "big"))
_rng_lock = threading.Lock()


def _hex_id(bits):
    with _rng_lock:
        v = _rng.getrandbits(bits)
    # zero ids are reserved/invalid in W3C trace-context; re-roll
    while not v:        # pragma: no cover - 2^-bits probability
        with _rng_lock:
            v = _rng.getrandbits(bits)
    return format(v, "0%dx" % (bits // 4))


def default_sample_rate():
    """The head-sample rate from ``BIGDL_TRACE_SAMPLE`` (default 1%)."""
    raw = os.environ.get(TRACE_SAMPLE_ENV)
    if raw is None:
        return _DEFAULT_RATE
    try:
        return float(raw)
    except ValueError:
        return _DEFAULT_RATE


def tracing_manifest(rate=None):
    """The tracing-config block bench records stamp into ``extra`` so
    ``tools/perf_gate.py`` can refuse numbers measured with
    always-sample tracing enabled."""
    r = default_sample_rate() if rate is None else float(rate)
    return {"sample_rate": r, "always_sample": r >= 1.0}


class TraceContext:
    """trace_id / span_id / parent_id / sampled -- one span's identity.

    ``trace_id`` (32 hex chars) names the whole request; ``span_id``
    (16 hex chars) names this span; ``parent_id`` links to the span
    that minted this one via ``child()``.  The string encoding is the
    W3C traceparent form ``00-<trace_id>-<span_id>-<flags>``.
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id, span_id, parent_id=None, sampled=True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = bool(sampled)

    def __repr__(self):
        return (f"TraceContext({self.trace_id}, {self.span_id}, "
                f"parent={self.parent_id}, sampled={self.sampled})")

    @classmethod
    def mint(cls, sampled=True):
        """A fresh root context (new trace_id, no parent)."""
        return cls(_hex_id(128), _hex_id(64), None, sampled)

    def child(self):
        """A child context: same trace, new span, parented here."""
        return TraceContext(self.trace_id, _hex_id(64), self.span_id,
                            self.sampled)

    # ----- encodings -------------------------------------------------- #
    def to_traceparent(self):
        return "00-%s-%s-%02x" % (self.trace_id, self.span_id,
                                  1 if self.sampled else 0)

    @classmethod
    def from_traceparent(cls, value):
        """Parse a traceparent string; None for anything malformed
        (a peer speaking garbage must not take the request down)."""
        if not isinstance(value, str):
            return None
        parts = value.strip().split("-")
        if len(parts) != 4:
            return None
        _ver, trace_id, span_id, flags = parts
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        try:
            int(trace_id, 16), int(span_id, 16)
            sampled = bool(int(flags, 16) & 1)
        except ValueError:
            return None
        return cls(trace_id.lower(), span_id.lower(), None, sampled)

    def to_wire(self):
        """The versioned dict that rides the socket protocol's request
        pickle as an optional ``trace`` field (traceless peers simply
        never read it)."""
        return {"v": WIRE_VERSION, "traceparent": self.to_traceparent()}

    @classmethod
    def from_wire(cls, obj):
        """Parse the wire dict; tolerant of None, garbage, and FUTURE
        versions (a newer peer's extra fields are ignored, the
        traceparent core still parses)."""
        if not isinstance(obj, dict):
            return None
        return cls.from_traceparent(obj.get("traceparent"))


class HeadSampler:
    """Head-based keep/drop decision, made once at the trace root."""

    def __init__(self, rate=None):
        self.rate = default_sample_rate() if rate is None else float(rate)

    def sample(self):
        if self.rate >= 1.0:
            return True
        if self.rate <= 0.0:
            return False
        with _rng_lock:
            return _rng.random() < self.rate


class RequestTrace:
    """Root-side span buffer with a deferred keep/drop decision.

    The fleet buffers every span of a request here (cheap tuples, no
    I/O) and calls ``flush`` exactly once at completion: records hit
    ``traces.jsonl`` only when the head sampler said yes OR something
    interesting forced the trace (error, shed, p99 tail).  Buffering
    instead of streaming is what makes always-sample-on-error possible
    without paying write costs for the 99% of unsampled-ok requests.
    """

    __slots__ = ("ctx", "records", "forced")

    def __init__(self, ctx):
        self.ctx = ctx
        self.records = []
        self.forced = False

    def add(self, name, ctx, t_wall, dur_s, status="ok", **fields):
        self.records.append((name, ctx, t_wall, dur_s, status, fields))
        # any error/shed span forces the whole trace: a request that
        # RETRIED to success still keeps its dead attempt's evidence
        if status == "shed" or status.startswith("error:"):
            self.forced = True

    def force(self):
        """Override the head sampler: this trace must survive."""
        self.forced = True

    @property
    def keep(self):
        return self.ctx.sampled or self.forced

    def flush(self, telemetry):
        if telemetry is None or not self.records or not self.keep:
            return False
        emit = getattr(telemetry, "record_trace", None)
        if emit is None:
            return False
        for name, ctx, t_wall, dur_s, status, fields in self.records:
            emit(name, ctx, t_wall, dur_s, status=status, **fields)
        self.records = []
        return True
