"""Interop layer: BigDL protobuf model format, Caffe, TensorFlow GraphDef.

Reference: utils/serializer/ (bigdl.proto), utils/caffe/, utils/tf/
(SURVEY.md section 2.6).
"""

from bigdl_tpu.interop import bigdl_pb2  # noqa: F401
