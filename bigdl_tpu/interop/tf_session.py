"""Train an imported TensorFlow graph (Session training).

Reference: utils/tf/Session.scala:105 BigDLSessionImpl.train(outputs,
dataSet, optMethod, criterion, endWhen): construct the model from the
GraphDef with VARIABLES TRAINABLE, then drive the normal optimizer over an
in-memory dataset (the queue-fed variant replaces TFRecord queue ops with
the host input pipeline -- here that is the DataSet pipeline already).
"""

from typing import List, Optional

from bigdl_tpu.interop.tensorflow import load_tf, read_graph


class TFSession:
    """reference: BigDLSessionImpl (utils/tf/Session.scala)."""

    def __init__(self, path, binary=None):
        self.path = path
        self._gdef = read_graph(path, binary)

    def placeholders(self) -> List[str]:
        return [n.name for n in self._gdef.node
                if n.op in ("Placeholder", "PlaceholderV2")]

    def build(self, outputs, inputs: Optional[List[str]] = None,
              input_specs=None):
        """-> trainable Graph between the placeholders and ``outputs``
        (variables become parameters initialised from their Assign values).
        """
        inputs = inputs if inputs is not None else self.placeholders()
        if not inputs:
            raise ValueError(
                "no Placeholder inputs found; Session training needs "
                "placeholder-fed graphs (the reference requires the same: "
                "Session.scala 'only support Placeholder as input')")
        return load_tf(self.path, inputs=inputs, outputs=outputs,
                       input_specs=input_specs, trainable=True)

    def train(self, outputs, dataset, optim_method, criterion, end_when,
              inputs: Optional[List[str]] = None, input_specs=None):
        """Train the graph's variables; returns the trained model
        (Session.scala:105 train overload #1)."""
        from bigdl_tpu.optim.local_optimizer import LocalOptimizer

        model = self.build(outputs, inputs, input_specs)
        opt = LocalOptimizer(model, dataset, criterion, optim_method)
        opt.set_end_when(end_when)
        opt.optimize()
        return model
