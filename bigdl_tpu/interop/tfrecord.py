"""TFRecord file I/O + tf.Example parsing.

Reference: utils/tf/TFRecordInputFormat.scala / TFRecordOutputFormat.scala
(Hadoop input/output formats over the TFRecord framing) and
utils/tf/TFRecordIterator.java.  Framing per record:

    uint64 LE  length
    uint32 LE  masked crc32c(length bytes)
    byte[length] payload (usually a serialized tf.Example)
    uint32 LE  masked crc32c(payload)

The Example protobuf is parsed with a minimal hand-rolled proto reader
(wire format only: field 1 = features map<string, Feature>, Feature oneof
bytes_list/float_list/int64_list) so no tensorflow dependency is needed --
the schema restates the public tensorflow/core/example/example.proto.
"""

import struct

import numpy as np

from bigdl_tpu.visualization.tensorboard import _masked_crc


_NATIVE = None
_NATIVE_TRIED = False


def _native_reader():
    """The C++ reader (native/record_reader.cpp) when buildable; the
    framing + crc work is pure host IO, so it lives native like the
    reference's loader layer (SURVEY.md 2.8)."""
    global _NATIVE, _NATIVE_TRIED
    if _NATIVE_TRIED:
        return _NATIVE
    _NATIVE_TRIED = True
    try:
        import ctypes

        from bigdl_tpu.dataset.native_loader import build_native_lib

        lib = build_native_lib("record_reader")
        lib.rr_open.restype = ctypes.c_void_p
        lib.rr_open.argtypes = [ctypes.c_char_p]
        lib.rr_next.restype = ctypes.c_longlong
        lib.rr_next.argtypes = [ctypes.c_void_p]
        lib.rr_data.restype = ctypes.POINTER(ctypes.c_ubyte)
        lib.rr_data.argtypes = [ctypes.c_void_p]
        lib.rr_close.argtypes = [ctypes.c_void_p]
        _NATIVE = lib
    except Exception:
        _NATIVE = None
    return _NATIVE


class TFRecordReader:
    """Iterate payload bytes from a TFRecord file (crc-checked).

    Uses the native C++ reader when available (``use_native=None`` =
    auto); the pure-python path is the behavioural reference either way.
    """

    def __init__(self, path, check_crc=True, use_native=None):
        self.path = path
        self.check_crc = check_crc
        self.use_native = use_native

    def __iter__(self):
        native = self.use_native
        if native is None:
            native = self.check_crc and _native_reader() is not None
        if native:
            yield from self._iter_native()
            return
        yield from self._iter_python()

    def _iter_native(self):
        import ctypes

        lib = _native_reader()
        if lib is None:
            raise RuntimeError("native record reader unavailable")
        h = lib.rr_open(self.path.encode())
        if not h:
            raise FileNotFoundError(self.path)
        try:
            while True:
                n = lib.rr_next(h)
                if n == -1:
                    return
                if n < 0:
                    raise ValueError(f"{self.path}: corrupt record crc")
                yield ctypes.string_at(lib.rr_data(h), n)
        finally:
            lib.rr_close(h)

    def _iter_python(self):
        with open(self.path, "rb") as f:
            while True:
                head = f.read(8)
                if len(head) < 8:
                    return
                (length,) = struct.unpack("<Q", head)
                (len_crc,) = struct.unpack("<I", f.read(4))
                if self.check_crc and _masked_crc(head) != len_crc:
                    raise ValueError(
                        f"{self.path}: corrupt length crc at offset "
                        f"{f.tell() - 12}")
                payload = f.read(length)
                if len(payload) < length:
                    raise ValueError(f"{self.path}: truncated record")
                (data_crc,) = struct.unpack("<I", f.read(4))
                if self.check_crc and _masked_crc(payload) != data_crc:
                    raise ValueError(
                        f"{self.path}: corrupt payload crc")
                yield payload


class TFRecordWriter:
    """Write payload bytes with TFRecord framing."""

    def __init__(self, path):
        self._f = open(path, "wb")

    def write(self, payload: bytes):
        head = struct.pack("<Q", len(payload))
        self._f.write(head)
        self._f.write(struct.pack("<I", _masked_crc(head)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


# --------------------------------------------------------------------------- #
# minimal proto wire reader/writer for tf.Example
# --------------------------------------------------------------------------- #


def _read_varint(buf, pos):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _write_varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _fields(buf):
    """Yield (field_number, wire_type, value_bytes_or_int)."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:          # varint
            val, pos = _read_varint(buf, pos)
        elif wt == 1:        # 64-bit
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:        # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:        # 32-bit
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def _parse_feature(buf):
    """Feature: oneof {1: BytesList, 2: FloatList, 3: Int64List}."""
    for field, _, val in _fields(buf):
        items = []
        if field == 1:       # BytesList: repeated bytes value = 1
            for f2, _, v2 in _fields(val):
                if f2 == 1:
                    items.append(bytes(v2))
            return items
        if field == 2:       # FloatList: repeated float value = 1 (packed)
            for f2, wt2, v2 in _fields(val):
                if f2 == 1:
                    if wt2 == 2:
                        items.extend(np.frombuffer(v2, "<f4").tolist())
                    else:
                        items.append(struct.unpack("<f", v2)[0])
            return np.asarray(items, np.float32)
        if field == 3:       # Int64List: repeated int64 value = 1 (packed)
            for f2, wt2, v2 in _fields(val):
                if f2 == 1:
                    if wt2 == 2:
                        p = 0
                        while p < len(v2):
                            n, p = _read_varint(v2, p)
                            items.append(n - (1 << 64) if n >= 1 << 63
                                         else n)
                    else:
                        items.append(v2 - (1 << 64) if v2 >= 1 << 63
                                     else v2)
            return np.asarray(items, np.int64)
    return []


def parse_example(payload: bytes):
    """Serialized tf.Example -> dict name -> list[bytes] | float32 array |
    int64 array (the ParseExample analogue, utils/tf/loaders usage)."""
    out = {}
    for field, _, val in _fields(payload):
        if field != 1:       # Example.features
            continue
        for f2, _, feat_entry in _fields(val):
            if f2 != 1:      # Features.feature map entry
                continue
            name, feature = None, None
            for f3, _, v3 in _fields(feat_entry):
                if f3 == 1:
                    name = v3.decode()
                elif f3 == 2:
                    feature = _parse_feature(v3)
            if name is not None:
                out[name] = feature
    return out


def _encode_feature(value):
    if isinstance(value, (bytes, bytearray)):
        value = [bytes(value)]
    if isinstance(value, (list, tuple)) and value \
            and isinstance(value[0], (bytes, bytearray)):
        inner = b"".join(
            _write_varint((1 << 3) | 2) + _write_varint(len(v)) + bytes(v)
            for v in value)
        body = _write_varint((1 << 3) | 2) + _write_varint(len(inner)) + inner
        return body                      # Feature.bytes_list = 1
    arr = np.asarray(value)
    if np.issubdtype(arr.dtype, np.integer):
        inner = b"".join(_write_varint(int(v) & ((1 << 64) - 1))
                         for v in arr.ravel())
        packed = _write_varint((1 << 3) | 2) + _write_varint(len(inner)) \
            + inner
        return _write_varint((3 << 3) | 2) + _write_varint(len(packed)) \
            + packed                     # Feature.int64_list = 3
    data = arr.astype("<f4").tobytes()
    packed = _write_varint((1 << 3) | 2) + _write_varint(len(data)) + data
    return _write_varint((2 << 3) | 2) + _write_varint(len(packed)) \
        + packed                         # Feature.float_list = 2


def build_example(features: dict) -> bytes:
    """dict -> serialized tf.Example (inverse of parse_example)."""
    entries = b""
    for name, value in features.items():
        nb = name.encode()
        feat = _encode_feature(value)
        entry = (_write_varint((1 << 3) | 2) + _write_varint(len(nb)) + nb
                 + _write_varint((2 << 3) | 2) + _write_varint(len(feat))
                 + feat)
        entries += (_write_varint((1 << 3) | 2)
                    + _write_varint(len(entry)) + entry)
    return _write_varint((1 << 3) | 2) + _write_varint(len(entries)) \
        + entries
