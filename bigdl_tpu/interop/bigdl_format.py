"""BigDL protobuf model format: save/load `bigdl_tpu` modules wire-compatibly.

Reference: utils/serializer/ModuleSerializer.scala:34 (registry),
ModuleLoader.scala:37,219 (load / save with optional separate weight file),
schema spark/dl/src/main/resources/serialization/bigdl.proto.

Design: each supported layer has a converter pair
``to_attrs(module) -> (attrs, params)`` / ``from_attrs(attrs, params)``
registered under the reference's fully-qualified Scala class name, so
``moduleType`` and the attribute names match what the reference's
reflection-based serializer emits (constructor parameter names).  Weight
layouts are converted between our TPU-native layouts (Linear (out, in) --
same as the reference -- and conv HWIO) and the reference's
``(nGroup, out/g, in/g, kH, kW)`` conv layout.

Storage dedup: every distinct ndarray gets one ``TensorStorage`` id; the
loader caches by id (reference: BigDLTensor.id / TensorStorage.id sharing).
"""

import json
import os

import numpy as np

from bigdl_tpu.interop import bigdl_pb2 as pb

_NN = "com.intel.analytics.bigdl.nn."
_TPU = "bigdl_tpu.nn."


# --------------------------------------------------------------------------- #
# tensor <-> proto
# --------------------------------------------------------------------------- #


class _Ctx:
    """Per-file storage-id space (storage dedup)."""

    def __init__(self):
        self.next_id = 1
        self.by_obj = {}     # id(ndarray) -> storage id  (save)
        self.by_id = {}      # storage id -> ndarray      (load)
        self.keep = []       # keeps saved arrays alive so id() stays unique


def _contiguous_strides(shape):
    strides, acc = [], 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= s
    return list(reversed(strides))


def _proto_dtype(dtype):
    """numpy dtype -> (proto DataType, storage field name, cast dtype)."""
    if dtype == np.float64:
        return pb.DOUBLE, "double_data", np.float64
    if np.issubdtype(dtype, np.bool_):
        return pb.BOOL, "bool_data", np.bool_
    if dtype in (np.int64, np.uint32, np.uint64):
        return pb.INT64, "long_data", np.int64
    if np.issubdtype(dtype, np.integer):
        return pb.INT32, "int_data", np.int32
    # f32 + half/bfloat16 ride as FLOAT; exact dtype restored via the
    # generic path's leafDtypes attr
    return pb.FLOAT, "float_data", np.float32


def _encode_tensor(arr, ctx: _Ctx, msg=None):
    orig = arr
    arr = np.asarray(arr)
    if not arr.flags["C_CONTIGUOUS"]:
        # NB: unconditional ascontiguousarray would reshape 0-d to (1,)
        arr = np.ascontiguousarray(arr)
    t = msg if msg is not None else pb.BigDLTensor()
    t.datatype = _proto_dtype(arr.dtype)[0]
    t.size.extend(int(s) for s in arr.shape)
    t.stride.extend(_contiguous_strides(arr.shape))
    # reference writes 1-BASED storageOffset (TensorConverter.scala:278 uses
    # DenseTensor.storageOffset = _storageOffset + 1); 1 == start of storage
    t.offset = 1
    t.dimension = arr.ndim
    t.nElements = int(arr.size)
    t.isScalar = arr.ndim == 0
    shared = ctx.by_obj.get(id(orig))
    if shared is not None:
        # storage dedup: shared ndarray -> one payload, later tensors
        # reference it by id only (reference: TensorStorage.id sharing)
        t.id = ctx.next_id
        ctx.next_id += 1
        t.storage.datatype = t.datatype
        t.storage.id = shared
        return t
    t.id = ctx.next_id
    ctx.next_id += 1
    t.storage.datatype = t.datatype
    t.storage.id = t.id
    ctx.by_obj[id(orig)] = t.id
    ctx.keep.append(orig)
    _, field, cast = _proto_dtype(arr.dtype)
    flat = arr.astype(cast).ravel()
    getattr(t.storage, field).extend(flat.tolist())
    return t


def _decode_tensor(t, ctx: _Ctx):
    if t.storage.float_data:
        data = np.asarray(t.storage.float_data, np.float32)
    elif t.storage.double_data:
        data = np.asarray(t.storage.double_data, np.float64)
    elif t.storage.int_data:
        data = np.asarray(t.storage.int_data, np.int32)
    elif t.storage.long_data:
        data = np.asarray(t.storage.long_data, np.int64)
    elif len(t.storage.bool_data):
        data = np.asarray(t.storage.bool_data, np.bool_)
    elif t.storage.id in ctx.by_id:
        data = ctx.by_id[t.storage.id]
    elif t.nElements > 0:
        raise ValueError(
            f"tensor storage {t.storage.id} has no payload -- was this "
            f"model saved with a separate weight file?  Pass weight_path=")
    else:
        data = np.zeros(0, np.float32)
    if t.storage.id:
        ctx.by_id[t.storage.id] = data
    shape = tuple(t.size)
    n = int(np.prod(shape)) if shape else 1
    # proto offset is 1-based (see _encode_tensor); files written by the
    # round-1 exporter used 0 -- treat offsets < 1 as start-of-storage
    off = max(int(t.offset) - 1, 0)
    strides = tuple(int(s) for s in t.stride)
    if strides and list(strides) != _contiguous_strides(shape):
        # non-contiguous view saved by real BigDL: reconstruct elementwise
        # from size/stride/offset, then copy to a contiguous array
        last = off + sum(s * (d - 1) for s, d in zip(strides, shape))
        if not shape or min(shape) == 0:
            return np.zeros(shape, data.dtype)
        if last >= data.size or off >= data.size:
            raise ValueError(
                f"tensor view out of bounds: offset {t.offset}, strides "
                f"{strides}, size {shape} over storage of {data.size}")
        itemsize = data.dtype.itemsize
        view = np.lib.stride_tricks.as_strided(
            data[off:], shape=shape,
            strides=tuple(s * itemsize for s in strides))
        return np.ascontiguousarray(view)
    if data.size < off + n:
        raise ValueError(
            f"tensor storage truncated: need {off + n} elements "
            f"(offset {t.offset} + {n}), storage has {data.size}")
    return data[off:off + n].reshape(shape)


# --------------------------------------------------------------------------- #
# attr helpers
# --------------------------------------------------------------------------- #


def _set_attr(attrs, key, value, ctx):
    a = attrs[key]
    if isinstance(value, bool):
        a.dataType = pb.BOOL
        a.boolValue = value
    elif isinstance(value, (int, np.integer)):
        a.dataType = pb.INT32
        a.int32Value = int(value)
    elif isinstance(value, (float, np.floating)):
        a.dataType = pb.DOUBLE
        a.doubleValue = float(value)
    elif isinstance(value, str):
        a.dataType = pb.STRING
        a.stringValue = value
    elif isinstance(value, np.ndarray):
        a.dataType = pb.TENSOR
        _encode_tensor(value, ctx, a.tensorValue)
    elif isinstance(value, (list, tuple)) and all(
            isinstance(v, (int, np.integer)) for v in value):
        a.dataType = pb.ARRAY_VALUE
        a.arrayValue.datatype = pb.INT32
        a.arrayValue.size = len(value)
        a.arrayValue.i32.extend(int(v) for v in value)
    elif _is_regularizer(value):
        _encode_value(a, value, ctx)
    else:
        raise TypeError(f"unsupported attr {key}: {type(value)}")


def _get_attr(mod_pb, key, default=None, ctx=None):
    if key not in mod_pb.attr:
        return default
    a = mod_pb.attr[key]
    which = a.WhichOneof("value")
    if which is None:
        return default
    v = getattr(a, which)
    if which == "tensorValue":
        return _decode_tensor(v, ctx or _Ctx())
    if which == "arrayValue":
        return list(v.i32) or list(v.i64) or list(v.flt) or list(v.dbl)
    if which == "regularizerValue":
        return _decode_value(a, ctx or _Ctx())
    return v


# --------------------------------------------------------------------------- #
# layer converters
# --------------------------------------------------------------------------- #

_SAVERS = {}    # our class name -> (module_type, to_attrs)
_LOADERS = {}   # module_type   -> from_pb


def _register(our_name, module_type, to_attrs, from_attrs):
    _SAVERS[our_name] = (module_type, to_attrs)
    _LOADERS[module_type] = from_attrs


def _conv_weight_to_bigdl(m, w):
    """HWIO (kh, kw, in/g, out) -> (nGroup, out/g, in/g, kH, kW)."""
    kh, kw = m.kernel
    g = m.n_group
    cin_g = m.n_input_plane // g
    out_g = m.n_output_plane // g
    return (w.reshape(kh, kw, cin_g, g, out_g)
            .transpose(3, 4, 2, 0, 1))


def _conv_weight_from_bigdl(w, kh, kw, cin_g, g, out_g):
    return (w.reshape(g, out_g, cin_g, kh, kw)
            .transpose(3, 4, 2, 0, 1).reshape(kh, kw, cin_g, g * out_g))


def _reg_attrs(m):
    """wRegularizer/bRegularizer attr entries when present (reference attr
    names from the Scala serializer)."""
    out = {}
    if getattr(m, "w_regularizer", None) is not None:
        out["wRegularizer"] = m.w_regularizer
    if getattr(m, "b_regularizer", None) is not None:
        out["bRegularizer"] = m.b_regularizer
    return out


def _install_regs(m, attrs):
    m.set_regularizer(attrs("wRegularizer", None), attrs("bRegularizer", None))
    return m


def _save_linear(m, p):
    return ({"inputSize": m.input_size, "outputSize": m.output_size,
             "withBias": m.with_bias, **_reg_attrs(m)},
            [np.asarray(p["weight"])]
            + ([np.asarray(p["bias"])] if m.with_bias else []))


def _load_linear(attrs, params, ctx):
    import bigdl_tpu.nn as nn
    m = nn.Linear(attrs("inputSize"), attrs("outputSize"),
                  with_bias=attrs("withBias", True))
    _install_regs(m, attrs)
    pt = {"weight": params[0]}
    if attrs("withBias", True) and len(params) > 1:
        pt["bias"] = params[1]
    return m, pt


def _save_conv(m, p):
    attrs = {"nInputPlane": m.n_input_plane, "nOutputPlane": m.n_output_plane,
             "kernelW": m.kernel[1], "kernelH": m.kernel[0],
             "strideW": m.stride[1], "strideH": m.stride[0],
             "padW": m.pad[1], "padH": m.pad[0], "nGroup": m.n_group,
             "withBias": m.with_bias, **_reg_attrs(m)}
    params = [_conv_weight_to_bigdl(m, np.asarray(p["weight"]))]
    if m.with_bias:
        params.append(np.asarray(p["bias"]))
    return attrs, params


def _load_conv(attrs, params, ctx):
    import bigdl_tpu.nn as nn
    g = attrs("nGroup", 1)
    cin, cout = attrs("nInputPlane"), attrs("nOutputPlane")
    kh, kw = attrs("kernelH"), attrs("kernelW")
    m = nn.SpatialConvolution(
        cin, cout, kw, kh, attrs("strideW", 1), attrs("strideH", 1),
        attrs("padW", 0), attrs("padH", 0), n_group=g,
        with_bias=attrs("withBias", True))
    _install_regs(m, attrs)
    w = _conv_weight_from_bigdl(params[0], kh, kw, cin // g, g, cout // g)
    pt = {"weight": w}
    if attrs("withBias", True) and len(params) > 1:
        pt["bias"] = params[1]
    return m, pt


def _save_pool(m, p):
    return ({"kW": m.kernel[1], "kH": m.kernel[0],
             "dW": m.stride[1], "dH": m.stride[0],
             "padW": m.pad[1], "padH": m.pad[0],
             "ceilMode": bool(getattr(m, "ceil_mode", False))}, [])


def _make_pool_loader(cls_name):
    def load(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        m = getattr(nn, cls_name)(
            attrs("kW"), attrs("kH"), attrs("dW", 1), attrs("dH", 1),
            attrs("padW", 0), attrs("padH", 0))
        if attrs("ceilMode", False):
            m.ceil()
        return m, {}
    return load


def _save_bn(m, p):
    attrs = {"nOutput": m.n_output, "eps": m.eps, "momentum": m.momentum,
             "affine": m.affine}
    params = ([np.asarray(p["weight"]), np.asarray(p["bias"])]
              if m.affine else [])
    return attrs, params


def _make_bn_loader(cls_name):
    def load(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        m = getattr(nn, cls_name)(attrs("nOutput"), attrs("eps", 1e-5),
                                  attrs("momentum", 0.1),
                                  affine=attrs("affine", True))
        pt = {}
        if attrs("affine", True) and len(params) >= 2:
            pt = {"weight": params[0], "bias": params[1]}
        return m, pt
    return load


def _save_lookup(m, p):
    return ({"nIndex": m.n_index, "nOutput": m.n_output},
            [np.asarray(p["weight"])])


def _load_lookup(attrs, params, ctx):
    import bigdl_tpu.nn as nn
    return nn.LookupTable(attrs("nIndex"), attrs("nOutput")), \
        {"weight": params[0]}


def _noarg(cls_name):
    def save(m, p):
        return {}, []

    def load(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        return getattr(nn, cls_name)(), {}
    return save, load


def _register_all():
    for name in ["ReLU", "Tanh", "Sigmoid", "LogSoftMax", "SoftMax",
                 "ReLU6", "SoftSign", "Abs", "Exp",
                 "Square", "Sqrt", "Identity", "FlattenTable", "GELU",
                 "SiLU"]:
        save, load = _noarg(name)
        _register(name, _NN + name, save, load)

    # parameterised activations keep their args on the wire
    # (reference: nn/ELU.scala alpha, nn/SoftPlus.scala beta)
    def save_elu(m, p):
        return {"alpha": float(m.alpha)}, []

    def load_elu(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        return nn.ELU(attrs("alpha", 1.0)), {}
    _register("ELU", _NN + "ELU", save_elu, load_elu)

    def save_softplus(m, p):
        return {"beta": float(m.beta)}, []

    def load_softplus(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        return nn.SoftPlus(attrs("beta", 1.0)), {}
    _register("SoftPlus", _NN + "SoftPlus", save_softplus, load_softplus)

    _register("Linear", _NN + "Linear", _save_linear, _load_linear)
    _register("SpatialConvolution", _NN + "SpatialConvolution",
              _save_conv, _load_conv)

    # int8 quantized layers (reference: nn/quantized/QuantSerializer.scala:
    # weights stored quantized with per-channel scales, never re-quantized
    # on load).  weight_q rides as INT32 int_data; the loader restores int8.
    def save_qlinear(m, p):
        params = [np.asarray(p["weight_q"], np.int32),
                  np.asarray(p["scale"], np.float32)]
        if m.with_bias:
            params.append(np.asarray(p["bias"], np.float32))
        # weight layout (out, in) matches the reference Linear convention
        return ({"inputSize": int(np.asarray(p["weight_q"]).shape[1]),
                 "outputSize": m.output_size, "withBias": m.with_bias},
                params)

    def load_qlinear(attrs, params, ctx):
        from bigdl_tpu.nn.quantized import QuantizedLinear
        wb = attrs("withBias", True)
        m = QuantizedLinear(
            output_size=attrs("outputSize"), with_bias=wb,
            weight_q=np.asarray(params[0], np.int8), scale=params[1],
            bias=params[2] if wb and len(params) > 2 else None)
        return m, {}
    _register("QuantizedLinear",
              "com.intel.analytics.bigdl.nn.quantized.Linear",
              save_qlinear, load_qlinear)

    def save_qconv(m, p):
        c = m.conv
        attrs = {"nInputPlane": c.n_input_plane,
                 "nOutputPlane": c.n_output_plane,
                 "kernelW": c.kernel[1], "kernelH": c.kernel[0],
                 "strideW": c.stride[1], "strideH": c.stride[0],
                 "padW": c.pad[1], "padH": c.pad[0], "nGroup": c.n_group,
                 "dilationW": c.dilation[1], "dilationH": c.dilation[0],
                 "withBias": c.with_bias, "dataFormat": c.data_format}
        # wire layout = the reference's grouped (g, out/g, in/g, kH, kW),
        # same as the float conv converter
        wq = _conv_weight_to_bigdl(c, np.asarray(p["weight_q"], np.int32))
        params = [wq, np.asarray(p["scale"], np.float32)]
        if c.with_bias:
            params.append(np.asarray(p["bias"], np.float32))
        return attrs, params

    def load_qconv(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.quantized import QuantizedSpatialConvolution
        wb = attrs("withBias", True)
        g = attrs("nGroup", 1)
        cin, cout = attrs("nInputPlane"), attrs("nOutputPlane")
        kh, kw = attrs("kernelH"), attrs("kernelW")
        conv = nn.SpatialConvolution(
            cin, cout, kw, kh,
            attrs("strideW", 1), attrs("strideH", 1),
            attrs("padW", 0), attrs("padH", 0),
            n_group=g, dilation_w=attrs("dilationW", 1),
            dilation_h=attrs("dilationH", 1), with_bias=wb,
            data_format=attrs("dataFormat", "NHWC"))
        wq = _conv_weight_from_bigdl(np.asarray(params[0]), kh, kw,
                                     cin // g, g, cout // g)
        m = QuantizedSpatialConvolution(
            conv, weight_q=np.asarray(wq, np.int8), scale=params[1],
            bias=params[2] if wb and len(params) > 2 else None)
        return m, {}
    _register("QuantizedSpatialConvolution",
              "com.intel.analytics.bigdl.nn.quantized.SpatialConvolution",
              save_qconv, load_qconv)
    _register("SpatialMaxPooling", _NN + "SpatialMaxPooling", _save_pool,
              _make_pool_loader("SpatialMaxPooling"))
    _register("SpatialAveragePooling", _NN + "SpatialAveragePooling",
              _save_pool, _make_pool_loader("SpatialAveragePooling"))
    _register("BatchNormalization", _NN + "BatchNormalization", _save_bn,
              _make_bn_loader("BatchNormalization"))
    _register("SpatialBatchNormalization", _NN + "SpatialBatchNormalization",
              _save_bn, _make_bn_loader("SpatialBatchNormalization"))
    _register("LookupTable", _NN + "LookupTable", _save_lookup, _load_lookup)

    def save_dropout(m, p):
        return {"initP": m.p}, []

    def load_dropout(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        return nn.Dropout(attrs("initP", 0.5)), {}
    _register("Dropout", _NN + "Dropout", save_dropout, load_dropout)

    def save_lrn(m, p):
        return {"size": m.size, "alpha": m.alpha, "beta": m.beta, "k": m.k}, []

    def load_lrn(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        return nn.SpatialCrossMapLRN(attrs("size", 5), attrs("alpha", 1.0),
                                     attrs("beta", 0.75), attrs("k", 1.0)), {}
    _register("SpatialCrossMapLRN", _NN + "SpatialCrossMapLRN",
              save_lrn, load_lrn)

    def save_reshape(m, p):
        return {"size": list(m.size)}, []

    def load_reshape(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        return nn.Reshape(tuple(attrs("size"))), {}
    _register("Reshape", _NN + "Reshape", save_reshape, load_reshape)

    def save_flatten(m, p):
        return {}, []

    def load_flatten(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        return nn.Flatten(), {}
    _register("Flatten", _TPU + "Flatten", save_flatten, load_flatten)

    def save_cadd(m, p):
        return {}, []

    def load_cadd(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        return nn.CAddTable(), {}
    _register("CAddTable", _NN + "CAddTable", save_cadd, load_cadd)

    def save_join(m, p):
        return {"dimension": m.dimension + 1}, []   # reference is 1-based

    def load_join(attrs, params, ctx):
        import bigdl_tpu.nn as nn
        return nn.JoinTable(attrs("dimension", 1) - 1), {}
    _register("JoinTable", _NN + "JoinTable", save_join, load_join)


_register_all()


# --------------------------------------------------------------------------- #
# generic reflection path: round-trips ANY module via recorded init args
# (reference analogue: ModuleSerializable's constructor-mirror reflection,
#  utils/serializer/ModuleSerializable.scala -- here the constructor call is
#  recorded at instance creation, see nn/module.py _record_init)
# --------------------------------------------------------------------------- #

_GEN = "bigdl_tpu.nn."
_GEN_CRIT = "bigdl_tpu.criterion."


def _is_regularizer(v):
    from bigdl_tpu.optim.regularizer import Regularizer
    return isinstance(v, Regularizer)


def _is_init_method(v):
    from bigdl_tpu.nn.initialization import InitializationMethod
    return isinstance(v, InitializationMethod)


def _is_dtype_like(v):
    if isinstance(v, np.dtype):
        return True
    if isinstance(v, type) and issubclass(v, np.generic):
        return True
    return type(v).__name__ == "_ScalarMeta"   # jnp.float32 & friends


def _encode_value(a, value, ctx):
    """python constructor-arg value -> AttrValue (generic path)."""
    from bigdl_tpu.nn.module import Criterion, Module

    if value is None:
        a.dataType = pb.STRING
        a.subType = "none"
    elif isinstance(value, (bool, np.bool_)):
        a.dataType = pb.BOOL
        a.boolValue = bool(value)
    elif isinstance(value, (int, np.integer)):
        if abs(int(value)) > 2**31 - 1:
            a.dataType = pb.INT64
            a.int64Value = int(value)
        else:
            a.dataType = pb.INT32
            a.int32Value = int(value)
    elif isinstance(value, (float, np.floating)):
        a.dataType = pb.DOUBLE
        a.doubleValue = float(value)
    elif isinstance(value, str):
        a.dataType = pb.STRING
        a.stringValue = value
    elif isinstance(value, Module):
        a.dataType = pb.MODULE
        _module_to_pb(value, {}, {}, ctx, arch_only=True,
                      msg=a.bigDLModuleValue)
    elif isinstance(value, Criterion):
        a.dataType = pb.MODULE
        a.subType = "criterion"
        _crit_to_pb(value, ctx, a.bigDLModuleValue)
    elif _is_regularizer(value):
        # wire: Regularizer message with regularData=[l1, l2]
        # (reference: serializer converters/DataConverter regularizer path)
        a.dataType = pb.REGULARIZER
        rv = a.regularizerValue
        l1 = float(getattr(value, "l1", 0.0))
        l2 = float(getattr(value, "l2", 0.0))
        if type(value).__name__ == "L1Regularizer":
            rv.regularizerType = pb.L1Regularizer
        elif type(value).__name__ == "L2Regularizer":
            rv.regularizerType = pb.L2Regularizer
        else:
            rv.regularizerType = pb.L1L2Regularizer
        rv.regularData.extend([l1, l2])
    elif _is_init_method(value):
        # initializer objects (MsraFiller, Xavier, ...) carry only
        # primitive ctor state; encode as name + kwargs JSON.  They only
        # matter for re-randomising a loaded architecture -- the saved
        # weights are installed regardless -- but round-tripping them
        # keeps e.g. ResNet(stem_s2d=True) saveable (its stem records
        # weight_init=MsraFiller(False))
        import json as _json
        a.dataType = pb.STRING
        a.subType = "initmethod"
        a.stringValue = _json.dumps(
            {"cls": type(value).__name__, "kw": value.__dict__})
    elif _is_dtype_like(value):
        a.dataType = pb.STRING
        a.subType = "dtype"
        a.stringValue = np.dtype(value).name
    elif isinstance(value, np.ndarray) or type(value).__module__.startswith(
            ("jax", "jaxlib")):
        arr = np.asarray(value)
        a.dataType = pb.TENSOR
        a.subType = str(arr.dtype)
        _encode_tensor(arr, ctx, a.tensorValue)
    elif isinstance(value, (tuple, list)):
        a.dataType = pb.ARRAY_VALUE
        a.subType = "list" if isinstance(value, list) else "tuple"
        av = a.arrayValue
        av.size = len(value)
        if not value:
            av.datatype = pb.INT32
        elif all(isinstance(v, (bool, np.bool_)) for v in value):
            av.datatype = pb.BOOL
            av.boolean.extend(bool(v) for v in value)
        elif all(isinstance(v, (int, np.integer)) for v in value):
            av.datatype = pb.INT32
            av.i32.extend(int(v) for v in value)
        elif all(isinstance(v, (int, float, np.integer, np.floating))
                 for v in value):
            av.datatype = pb.DOUBLE
            av.dbl.extend(float(v) for v in value)
        elif all(isinstance(v, str) for v in value):
            av.datatype = pb.STRING
            av.str.extend(value)
        elif all(isinstance(v, Module) for v in value):
            av.datatype = pb.MODULE
            for v in value:
                _module_to_pb(v, {}, {}, ctx, arch_only=True,
                              msg=av.bigDLModule.add())
        elif all(isinstance(v, Criterion) for v in value):
            av.datatype = pb.MODULE
            a.subType += ":criterion"
            for v in value:
                _crit_to_pb(v, ctx, av.bigDLModule.add())
        elif all(isinstance(v, (tuple, list)) and all(
                isinstance(x, (int, np.integer)) for x in v) for v in value):
            av.datatype = pb.SHAPE
            for v in value:
                s = av.shape.add()
                s.shapeType = pb.Shape.SINGLE
                s.ssize = len(v)
                s.shapeValue.extend(int(x) for x in v)
        else:
            raise TypeError(
                f"unsupported constructor-arg sequence for serialization: "
                f"{value!r}")
    else:
        raise TypeError(
            f"unsupported constructor-arg type for serialization: "
            f"{type(value).__name__} ({value!r}); register an explicit "
            f"converter for this layer")


def _decode_value(a, ctx):
    import jax.numpy as jnp

    if a.subType == "none":
        return None
    if a.subType == "dtype":
        return jnp.dtype(a.stringValue)
    if a.subType == "initmethod":
        import json as _json

        from bigdl_tpu.nn import initialization
        spec = _json.loads(a.stringValue)
        obj = getattr(initialization, spec["cls"])(**spec["kw"])
        return obj
    which = a.WhichOneof("value")
    if which is None:
        return None
    if which == "bigDLModuleValue":
        if a.subType == "criterion":
            return _crit_from_pb(a.bigDLModuleValue, ctx)
        return _module_from_pb(a.bigDLModuleValue, ctx, (), [])
    if which == "regularizerValue":
        from bigdl_tpu.optim.regularizer import (L1L2Regularizer,
                                                 L1Regularizer, L2Regularizer)
        rv = a.regularizerValue
        data = list(rv.regularData)
        l1 = data[0] if data else 0.0
        l2 = data[1] if len(data) > 1 else 0.0
        if rv.regularizerType == pb.L1Regularizer:
            return L1Regularizer(l1)
        if rv.regularizerType == pb.L2Regularizer:
            return L2Regularizer(l2)
        return L1L2Regularizer(l1, l2)
    if which == "tensorValue":
        arr = _decode_tensor(a.tensorValue, ctx)
        if a.subType:
            arr = arr.astype(jnp.dtype(a.subType))
        return jnp.asarray(arr)
    if which == "arrayValue":
        av = a.arrayValue
        if av.datatype == pb.BOOL:
            out = [bool(v) for v in av.boolean]
        elif av.datatype == pb.INT32:
            out = [int(v) for v in av.i32]
        elif av.datatype == pb.DOUBLE:
            out = [float(v) for v in av.dbl]
        elif av.datatype == pb.STRING:
            out = list(av.str)
        elif av.datatype == pb.MODULE:
            if a.subType.endswith(":criterion"):
                out = [_crit_from_pb(m, ctx) for m in av.bigDLModule]
            else:
                out = [_module_from_pb(m, ctx, (), []) for m in av.bigDLModule]
        elif av.datatype == pb.SHAPE:
            out = [tuple(int(x) for x in s.shapeValue) for s in av.shape]
        else:
            raise TypeError(f"unsupported array datatype {av.datatype}")
        return out if a.subType.startswith("list") else tuple(out)
    v = getattr(a, which)
    return v


def _crit_to_pb(crit, ctx, msg):
    msg.moduleType = _GEN_CRIT + type(crit).__name__
    args, kwargs = getattr(crit, "_init_args", ((), {}))
    _encode_value(msg.attr["nArgs"], len(args), ctx)
    for i, v in enumerate(args):
        _encode_value(msg.attr[f"arg{i}"], v, ctx)
    for k, v in kwargs.items():
        _encode_value(msg.attr["kw:" + k], v, ctx)
    return msg


def _crit_from_pb(msg, ctx):
    import bigdl_tpu.nn as nn

    name = msg.moduleType.rsplit(".", 1)[-1]
    cls = getattr(nn, name, None)
    if cls is None:
        raise NotImplementedError(f"unknown criterion {msg.moduleType}")
    nargs = _decode_value(msg.attr["nArgs"], ctx)
    args = [_decode_value(msg.attr[f"arg{i}"], ctx) for i in range(nargs)]
    kwargs = {k[3:]: _decode_value(v, ctx)
              for k, v in msg.attr.items() if k.startswith("kw:")}
    return cls(*args, **kwargs)


def _generic_to_pb(module, params, state, ctx, arch_only=False, msg=None):
    import jax

    msg = msg if msg is not None else pb.BigDLModule()
    msg.name = module.name or type(module).__name__
    msg.version = "0.8.0"
    msg.train = bool(getattr(module, "train_mode", True))
    msg.moduleType = _GEN + type(module).__name__
    args, kwargs = getattr(module, "_init_args", ((), {}))
    _encode_value(msg.attr["nArgs"], len(args), ctx)
    for i, v in enumerate(args):
        _encode_value(msg.attr[f"arg{i}"], v, ctx)
    for k, v in kwargs.items():
        _encode_value(msg.attr["kw:" + k], v, ctx)

    from bigdl_tpu.nn.module import Container
    if isinstance(module, Container):
        # children added via .add() post-construction; constructor-built
        # children (wrappers) are re-created by the constructor on load
        n_ctor = len(_ctor_children(module))
        _encode_value(msg.attr["nCtorChildren"], n_ctor, ctx)
        for child in module.modules[n_ctor:]:
            _module_to_pb(child, {}, {}, ctx, arch_only=True,
                          msg=msg.subModules.add())

    if not arch_only:
        p_leaves = jax.tree_util.tree_leaves(params)
        s_leaves = jax.tree_util.tree_leaves(state)
        if p_leaves or s_leaves:
            msg.hasParameters = True
            _encode_value(msg.attr["nParamLeaves"], len(p_leaves), ctx)
            dtypes = []
            for leaf in p_leaves + s_leaves:
                arr = np.asarray(leaf)
                dtypes.append(str(arr.dtype))
                _encode_tensor(arr, ctx, msg.parameters.add())
            _encode_value(msg.attr["leafDtypes"], dtypes, ctx)
    return msg


def _ctor_children(module):
    """Children the constructor itself creates: re-running cls(*init_args)
    on load reproduces them, so only .add()-ed children serialize as
    subModules.  Detected by re-invoking the constructor (pure by the
    module contract: __init__ only stores config)."""
    cls = type(module)
    args, kwargs = getattr(module, "_init_args", ((), {}))
    try:
        probe = cls(*args, **kwargs)
        return probe.modules
    except Exception:
        return []


def _generic_from_pb(msg, ctx, path, installs):
    import bigdl_tpu.nn as nn

    name = msg.moduleType.rsplit(".", 1)[-1]
    cls = getattr(nn, name, None)
    if cls is None:
        raise NotImplementedError(f"unknown module type {msg.moduleType}")
    nargs = _decode_value(msg.attr["nArgs"], ctx)
    args = [_decode_value(msg.attr[f"arg{i}"], ctx) for i in range(nargs)]
    kwargs = {k[3:]: _decode_value(v, ctx)
              for k, v in msg.attr.items() if k.startswith("kw:")}
    m = cls(*args, **kwargs)
    if msg.name:
        m.name = msg.name
    if "nCtorChildren" in msg.attr:
        n_ctor = _decode_value(msg.attr["nCtorChildren"], ctx)
        if len(m.modules) != n_ctor:
            raise ValueError(
                f"{type(m).__name__}: constructor produced "
                f"{len(m.modules)} children but the file was saved with "
                f"{n_ctor} -- save-side probe and load disagree")
    if msg.subModules:
        for sub in msg.subModules:
            m.add(_module_from_pb(sub, ctx, (), []))
    if msg.hasParameters:
        n_p = _decode_value(msg.attr["nParamLeaves"], ctx)
        dtypes = _decode_value(msg.attr["leafDtypes"], ctx) or []
        leaves = [_decode_tensor(t, ctx) for t in msg.parameters]
        leaves = [l.astype(np.dtype(d)) if d else l
                  for l, d in zip(leaves, dtypes)]
        installs.append(("subtree", path, leaves[:n_p], leaves[n_p:]))
    return m


# --------------------------------------------------------------------------- #
# Graph (static DAG): topology via subModules + preModules edge names
# (reference: Graph serialization with preModules/nextModules fields)
# --------------------------------------------------------------------------- #


def _graph_to_pb(module, params, state, ctx, arch_only=False, msg=None):
    msg = msg if msg is not None else pb.BigDLModule()
    msg.name = module.name
    msg.version = "0.8.0"
    msg.train = bool(module.train_mode)
    msg.moduleType = _NN + "StaticGraph"
    names = {id(n): f"node{i}" for i, n in enumerate(module._topo)}
    for i, node in enumerate(module._topo):
        if node.module is None:
            sub = msg.subModules.add()
            sub.moduleType = _NN + "Input"
        else:
            sub = _module_to_pb(node.module, params.get(str(i), {}),
                                state.get(str(i), {}), ctx,
                                arch_only=arch_only,
                                msg=msg.subModules.add())
            _encode_value(sub.attr["origName"], node.module.name, ctx)
        sub.name = names[id(node)]
        sub.preModules.extend(names[id(p)] for p in node.inputs)
    _encode_value(msg.attr["inputNames"],
                  [names[id(n)] for n in module.input_nodes], ctx)
    _encode_value(msg.attr["outputNames"],
                  [names[id(n)] for n in module.output_nodes], ctx)
    return msg


def _graph_from_pb(msg, ctx, path, installs):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.graph import Input, Node

    nodes = {}
    for i, sub in enumerate(msg.subModules):
        if sub.moduleType.rsplit(".", 1)[-1] == "Input":
            node = Input()
        else:
            m = _module_from_pb(sub, ctx, path + (str(i),), installs)
            orig = _decode_value(sub.attr["origName"], ctx) \
                if "origName" in sub.attr else None
            if orig:
                m.name = orig
            node = Node(m, [nodes[p] for p in sub.preModules])
        nodes[sub.name] = node
    inputs = [nodes[n] for n in _decode_value(msg.attr["inputNames"], ctx)]
    outputs = [nodes[n] for n in _decode_value(msg.attr["outputNames"], ctx)]
    g = nn.Graph(inputs, outputs)
    if msg.name:
        g.name = msg.name
    return g


# --------------------------------------------------------------------------- #
# module tree <-> BigDLModule
# --------------------------------------------------------------------------- #


def _module_to_pb(module, params, state, ctx: _Ctx, arch_only=False,
                  msg=None):
    """params/state are THIS module's subtrees (root owns the full tree).

    Dispatch: Graph -> topology converter; Sequential/Concat -> wire-compat
    recursion; registered classes -> wire-compat converters (reference FQCN
    moduleType, readable by real BigDL); everything else -> generic
    reflection path.  arch_only (constructor-arg modules) always uses the
    generic/graph path since wire-compat converters need built params.
    """
    import bigdl_tpu.nn as nn

    params = params if isinstance(params, dict) else {}
    state = state if isinstance(state, dict) else {}

    if isinstance(module, nn.Graph):
        return _graph_to_pb(module, params, state, ctx,
                            arch_only=arch_only, msg=msg)
    cls = type(module).__name__
    if not isinstance(module, (nn.Sequential, nn.Concat)):
        if arch_only or cls not in _SAVERS:
            return _generic_to_pb(module, params, state, ctx,
                                  arch_only=arch_only, msg=msg)

    msg = msg if msg is not None else pb.BigDLModule()
    msg.name = module.name or cls
    msg.version = "0.8.0"
    msg.train = bool(getattr(module, "train_mode", True))

    if isinstance(module, (nn.Sequential, nn.Concat)):
        msg.moduleType = _NN + cls
        if isinstance(module, nn.Concat):
            _set_attr(msg.attr, "dimension", module.dimension + 1, ctx)
        for i, child in enumerate(module.modules):
            _module_to_pb(
                child, params.get(str(i), {}), state.get(str(i), {}), ctx,
                arch_only=arch_only, msg=msg.subModules.add())
    else:
        module_type, to_attrs = _SAVERS[cls]
        msg.moduleType = module_type
        attrs, plist = to_attrs(module, params)
        for k, v in attrs.items():
            _set_attr(msg.attr, k, v, ctx)
        if plist:
            msg.hasParameters = True
            for arr in plist:
                _encode_tensor(arr, ctx, msg.parameters.add())
        # BN running stats ride as attrs (reference: BatchNormalization's
        # own serializer stores runningMean/runningVar attrs,
        # BatchNormalization.scala:430-436)
        if "running_mean" in state:
            _set_attr(msg.attr, "runningMean",
                      np.asarray(state["running_mean"]), ctx)
            _set_attr(msg.attr, "runningVar",
                      np.asarray(state["running_var"]), ctx)
    return msg


def _module_from_pb(msg, ctx: _Ctx, path, installs):
    """-> module; appends (path, key, array, is_state) weight installs."""
    import bigdl_tpu.nn as nn

    mt = msg.moduleType
    short = mt.rsplit(".", 1)[-1]
    if short == "StaticGraph":
        return _graph_from_pb(msg, ctx, path, installs)
    # registered loaders win over the generic prefix: a few wire-compat
    # types (e.g. Flatten) live under the bigdl_tpu.nn. moduleType too
    if mt.startswith(_GEN) and mt not in _LOADERS:
        return _generic_from_pb(msg, ctx, path, installs)
    if short in ("Sequential", "Concat"):
        if short == "Concat":
            node = nn.Concat(_get_attr(msg, "dimension", 1, ctx) - 1)
        else:
            node = nn.Sequential()
        node.name = msg.name or node.name
        for i, sub in enumerate(msg.subModules):
            node.add(_module_from_pb(sub, ctx, path + (str(i),), installs))
        return node
    if mt not in _LOADERS:
        raise NotImplementedError(f"no loader for module type {mt}")

    params = [_decode_tensor(t, ctx) for t in msg.parameters]
    if not params and msg.HasField("weight"):
        params.append(_decode_tensor(msg.weight, ctx))
        if msg.HasField("bias"):
            params.append(_decode_tensor(msg.bias, ctx))

    def attrs(key, default=None):
        return _get_attr(msg, key, default, ctx)

    m, ptree = _LOADERS[mt](attrs, params, ctx)
    if msg.name:
        m.name = msg.name
    for k, v in (ptree or {}).items():
        installs.append((path, k, np.asarray(v, np.float32), False))
    rm = _get_attr(msg, "runningMean", None, ctx)
    if rm is not None:
        installs.append((path, "running_mean",
                         np.asarray(rm, np.float32), True))
        installs.append((path, "running_var",
                         np.asarray(_get_attr(msg, "runningVar", None, ctx),
                                    np.float32), True))
    return m


def _install(module, installs):
    """Overwrite built params/state leaves with deserialized values."""
    import jax
    import jax.numpy as jnp
    for entry in installs:
        if entry[0] == "subtree":
            _, path, p_leaves, s_leaves = entry
            _install_subtree(module, path, p_leaves, s_leaves)
            continue
        path, key, value, is_state = entry
        node = module._state if is_state else module._params
        for p in path:
            node = node[p]
        if key not in node:
            raise KeyError(
                f"deserialized weight {'/'.join(path)}/{key} has no slot in "
                f"the built module")
        if tuple(node[key].shape) != tuple(value.shape):
            raise ValueError(
                f"shape mismatch at {'/'.join(path)}/{key}: file "
                f"{value.shape} vs module {tuple(node[key].shape)}")
        node[key] = jnp.asarray(value)


def _install_subtree(module, path, p_leaves, s_leaves):
    """Replace the flattened leaves of the params/state subtree at ``path``
    (generic path: leaf ORDER is the contract -- same class + init args +
    build spec => same treedef on both sides)."""
    import jax
    import jax.numpy as jnp

    for attr, leaves in (("_params", p_leaves), ("_state", s_leaves)):
        tree = getattr(module, attr)
        parents, node = [], tree
        for k in path:
            parents.append(node)
            node = node[k]
        flat, treedef = jax.tree_util.tree_flatten(node)
        if len(flat) != len(leaves):
            raise ValueError(
                f"{attr} subtree at {'/'.join(path) or '<root>'} has "
                f"{len(flat)} leaves; file has {len(leaves)} -- was the "
                f"module built with a different input spec?")
        new = []
        for old, val in zip(flat, leaves):
            if tuple(np.shape(old)) != tuple(np.shape(val)):
                raise ValueError(
                    f"shape mismatch in {attr} at "
                    f"{'/'.join(path) or '<root>'}: file {np.shape(val)} "
                    f"vs module {tuple(np.shape(old))}")
            new.append(jnp.asarray(val))
        rebuilt = jax.tree_util.tree_unflatten(treedef, new)
        if parents:
            parents[-1][path[-1]] = rebuilt
        else:
            setattr(module, attr, rebuilt)


_STORAGE_FIELDS = (("float_data", np.float32), ("double_data", np.float64),
                   ("int_data", np.int32), ("long_data", np.int64))


def _take_storage(st):
    """-> array moved out of whichever payload field is populated, or None
    (int_data matters for int8 quantized weights riding as INT32)."""
    for field, dt in _STORAGE_FIELDS:
        data = getattr(st, field)
        if data:
            arr = np.asarray(data, dt)
            for f, _ in _STORAGE_FIELDS:
                st.ClearField(f)
            return arr
    return None


def _strip_storages(msg, store):
    """Move storage payloads out of the proto into ``store`` (npz dict)."""
    for t in list(msg.parameters):
        arr = _take_storage(t.storage)
        if arr is not None:
            store[str(t.storage.id)] = arr
    for a in msg.attr.values():
        if a.WhichOneof("value") == "tensorValue":
            arr = _take_storage(a.tensorValue.storage)
            if arr is not None:
                store[str(a.tensorValue.storage.id)] = arr
    for sub in msg.subModules:
        _strip_storages(sub, store)


def _storage_empty(st):
    """Pure check -- unlike _take_storage it must NOT clear payloads."""
    return not any(getattr(st, f) for f, _ in _STORAGE_FIELDS)


def _put_storage(st, arr):
    field = {np.dtype(np.float64): "double_data",
             np.dtype(np.int32): "int_data",
             np.dtype(np.int64): "long_data"}.get(arr.dtype, "float_data")
    if field == "float_data":
        arr = arr.astype(np.float32)
    getattr(st, field).extend(arr.tolist())


def _restore_storages(msg, store):
    for t in list(msg.parameters):
        key = str(t.storage.id)
        if key in store and _storage_empty(t.storage):
            _put_storage(t.storage, store[key])
    for a in msg.attr.values():
        if a.WhichOneof("value") == "tensorValue":
            key = str(a.tensorValue.storage.id)
            if key in store and _storage_empty(a.tensorValue.storage):
                _put_storage(a.tensorValue.storage, store[key])
    for sub in msg.subModules:
        _restore_storages(sub, store)


def _spec_to_json(spec):
    if isinstance(spec, (tuple, list)):
        return [_spec_to_json(s) for s in spec]
    if hasattr(spec, "shape") and hasattr(spec, "dtype"):
        return {"shape": [int(s) for s in spec.shape],
                "dtype": str(np.dtype(spec.dtype))}
    raise TypeError(f"unsupported build spec node {type(spec).__name__}")


def _spec_from_json(j):
    import jax
    if isinstance(j, list):
        return tuple(_spec_from_json(s) for s in j)
    return jax.ShapeDtypeStruct(tuple(j["shape"]), np.dtype(j["dtype"]))


def save_bigdl(module, path, overwrite=True, weight_path=None):
    """ModulePersister.saveToFile equivalent (protobuf BigDLModule file).

    ``weight_path``: big-model support — tensor storages go to a separate
    npz keyed by storage id and the proto keeps only metadata (reference:
    ModuleLoader.scala:219 saveToFile(definitionPath, weightPath)).

    Unbuilt modules save architecture-only; built modules additionally
    record their build spec so ``load_bigdl`` can rebuild without an
    ``input_spec``.
    """
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(path)
    ctx = _Ctx()
    msg = _module_to_pb(module, module._params or {}, module._state or {},
                        ctx, arch_only=not module.is_built())
    build_spec = getattr(module, "_build_spec", None)  # round-1 pickle
    if module.is_built() and build_spec is not None:   # objects lack it
        try:
            _set_attr(msg.attr, "buildSpec",
                      json.dumps(_spec_to_json(build_spec)), ctx)
        except TypeError:
            pass     # exotic spec: caller must pass input_spec at load
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    if weight_path is not None:
        store = {}
        _strip_storages(msg, store)
        if not weight_path.endswith(".npz"):
            weight_path += ".npz"   # np.savez appends it anyway
        np.savez(weight_path, **store)
    with open(path, "wb") as f:
        f.write(msg.SerializeToString())


def load_bigdl(path, input_spec=None, weight_path=None):
    """ModuleLoader.loadFromFile equivalent.

    Returns the module; when ``input_spec`` (a jax.ShapeDtypeStruct or an
    example array) is given the module is built immediately and the stored
    weights installed; otherwise they install at the module's first build
    (triggered by ``forward``).
    """
    msg = pb.BigDLModule()
    with open(path, "rb") as f:
        msg.ParseFromString(f.read())
    if weight_path is not None:
        if not weight_path.endswith(".npz") and not os.path.exists(weight_path):
            weight_path += ".npz"
        store = dict(np.load(weight_path))
        _restore_storages(msg, store)
    ctx = _Ctx()
    installs = []
    module = _module_from_pb(msg, ctx, (), installs)
    if not msg.train:
        module.evaluate()

    orig_build = module.build

    def build_and_install(spec, rng=None):
        out = orig_build(spec, rng=rng)
        _install(module, installs)
        return out
    module.build = build_and_install

    if input_spec is None and "buildSpec" in msg.attr:
        input_spec = _spec_from_json(
            json.loads(_get_attr(msg, "buildSpec", ctx=ctx)))
    if input_spec is not None:
        from bigdl_tpu.utils.shape import spec_of
        module.build(spec_of(input_spec))
    return module
