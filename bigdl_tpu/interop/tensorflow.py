"""TensorFlow GraphDef import/export.

Reference: utils/tf/TensorflowLoader.scala:43 (load(pb, inputs, outputs):
parse GraphDef, pattern-match subgraphs via the per-op loaders in
utils/tf/loaders/, buildBigDLModel at :358) and utils/tf/TensorflowSaver.scala
(export).

TPU-native notes: TF is natively NHWC with HWIO conv kernels — identical to
our layouts, so conv weights install verbatim; only MatMul weights transpose
((in, out) -> our (out, in)).  Pattern folding: BiasAdd over Conv2D/MatMul
becomes the module bias (the reference does the same via subgraph patterns,
e.g. loaders/Conv2D.scala).
"""

import numpy as np

from bigdl_tpu.interop import tensorflow_pb2 as tfpb
from google.protobuf import text_format

_DT_NP = {
    tfpb.DT_FLOAT: np.float32, tfpb.DT_DOUBLE: np.float64,
    tfpb.DT_INT32: np.int32, tfpb.DT_INT64: np.int64,
    tfpb.DT_BOOL: np.bool_, tfpb.DT_INT8: np.int8,
    tfpb.DT_UINT8: np.uint8, tfpb.DT_INT16: np.int16,
}


def read_graph(path, binary=None):
    """Parse a GraphDef from .pb (binary) or .pbtxt (text)."""
    g = tfpb.GraphDef()
    if binary is None:
        binary = not (path.endswith(".pbtxt") or path.endswith(".pbtxt.txt"))
    if binary:
        with open(path, "rb") as f:
            g.ParseFromString(f.read())
    else:
        with open(path) as f:
            text_format.Parse(f.read(), g, allow_unknown_field=True)
    return g


def _tensor_to_np(t):
    shape = tuple(int(d.size) for d in t.tensor_shape.dim)
    if t.string_val:
        n = int(np.prod(shape)) if shape else 1
        vals = list(t.string_val)
        if len(vals) == 1 and n > 1:
            vals = vals * n                         # splat encoding
        arr = np.empty(len(vals), object)           # bytes elements
        arr[:] = vals
        return arr.reshape(shape)
    dtype = _DT_NP.get(t.dtype, np.float32)
    n = int(np.prod(shape)) if shape else 1
    if t.tensor_content:
        arr = np.frombuffer(t.tensor_content, dtype=dtype)
    elif t.float_val:
        arr = np.asarray(t.float_val, dtype)
    elif t.double_val:
        arr = np.asarray(t.double_val, dtype)
    elif t.int_val:
        arr = np.asarray(t.int_val, dtype)
    elif t.int64_val:
        arr = np.asarray(t.int64_val, dtype)
    elif t.bool_val:
        arr = np.asarray(t.bool_val, dtype)
    else:
        arr = np.zeros(n, dtype)
    if arr.size == 1 and n > 1:
        arr = np.full(n, arr.ravel()[0], dtype)   # splat encoding
    return arr.reshape(shape)


def _clean(name):
    name = name.lstrip("^")
    return name.split(":")[0]


def _input_key(name):
    """ctx.input_nodes key for a user-named input: slot 0 collapses to the
    bare node name; a non-zero slot (e.g. ``reader:1``, the value output
    of ReaderReadV2) keeps its suffix so multi-output sockets stay
    distinct."""
    name = name.lstrip("^")
    base, _, slot = name.partition(":")
    return base if slot in ("", "0") else f"{base}:{slot}"


class _GraphCtx:
    def __init__(self, nodes):
        self.nodes = nodes          # name -> NodeDef
        self.memo = {}              # name -> ("const", np) | ("node", Node)
        self.module_blobs = []      # (module, install_fn) pairs
        self.input_nodes = {}       # placeholder name -> Input node
        self.consumers = {}         # name -> number of consuming nodes
        self.frames = {}            # while-frame name -> (node, var map)
        for n in nodes.values():
            for i in n.input:
                key = _clean(i)
                self.consumers[key] = self.consumers.get(key, 0) + 1


def _const_of(ctx, name):
    kind, val = _convert(ctx, name)
    if kind != "const":
        raise NotImplementedError(
            f"expected constant input {name}, got graph node")
    return val


def _node_of(ctx, name):
    kind, val = _convert(ctx, name)
    if kind != "node":
        raise NotImplementedError(
            f"{name} resolves to a constant where an activation is expected")
    return val


def _tf_conv_module(k_shape, strides, dilations, with_same_pad):
    """TF-exact conv: lax's string padding reproduces TF SAME including
    its input-size-dependent asymmetric pads (no symmetric approximation)."""
    from bigdl_tpu.nn.module import Module
    import jax.numpy as jnp
    from jax import lax

    kh, kw, cin, cout = k_shape
    sh, sw = strides
    dh, dw = dilations

    class TfConv2D(Module):
        n_input_plane, n_output_plane = cin, cout

        def setup(self, rng, input_spec):
            return {"weight": jnp.zeros((kh, kw, cin, cout), jnp.float32),
                    "bias": jnp.zeros((cout,), jnp.float32)}, ()

        def apply(self, params, state, input, *, training=False, rng=None):
            y = lax.conv_general_dilated(
                input, params["weight"].astype(input.dtype),
                window_strides=(sh, sw),
                padding="SAME" if with_same_pad else "VALID",
                rhs_dilation=(dh, dw),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            return y + params["bias"].astype(y.dtype), state

    return TfConv2D()


def _data_format(ndef):
    return ndef.attr["data_format"].s.decode() or "NHWC"


def _nchw_wrap(build, rank=4):
    """Run a channels-last-native conversion on channels-first data:
    permute in, build the NHWC/NDHWC subgraph, permute back (XLA folds
    the transposes into layouts; reference loaders support both formats
    natively, e.g. Conv2D.scala).  ``rank``: 4 for NCHW, 5 for NCDHW."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.graph import Node

    perm_in = (0,) + tuple(range(2, rank)) + (1,)
    perm_out = (0, rank - 1) + tuple(range(1, rank - 1))

    def wrapped(x_node):
        pre = Node(nn.Permute(perm_in), [x_node])
        out = build(pre)
        return Node(nn.Permute(perm_out), [out])
    return wrapped


def _pool_module(ndef, kind):
    """TF-exact pooling: reduce_window with lax string padding (SAME
    matches TF's asymmetric pads; avg excludes padded cells like TF)."""
    from bigdl_tpu.nn.module import Module
    import jax.numpy as jnp
    from jax import lax

    ks = list(ndef.attr["ksize"].list.i)
    st = list(ndef.attr["strides"].list.i)
    hw = (2, 3) if _data_format(ndef) == "NCHW" else (1, 2)
    kh, kw = int(ks[hw[0]]), int(ks[hw[1]])
    sh, sw = int(st[hw[0]]), int(st[hw[1]])
    pad = ndef.attr["padding"].s.decode()

    class TfPool(Module):
        def apply(self, params, state, input, *, training=False, rng=None):
            dims, strides = (1, kh, kw, 1), (1, sh, sw, 1)
            if kind == "max":
                return lax.reduce_window(
                    input, -jnp.inf, lax.max, dims, strides, pad), state
            ones = jnp.ones_like(input)
            total = lax.reduce_window(input, 0.0, lax.add, dims, strides,
                                      pad)
            count = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                      pad)
            return total / count, state

    return TfPool()


def _convert(ctx, name):
    raw = name.lstrip("^")
    base, _, slot_s = raw.partition(":")
    slot = int(slot_s) if slot_s else 0
    if (base, slot) in ctx.memo:
        return ctx.memo[(base, slot)]
    if base not in ctx.nodes:
        raise KeyError(f"node {base} not in graph")
    ndef = ctx.nodes[base]
    result = _convert_node(ctx, ndef)
    if result[0] == "multi":
        # multi-output op (Split/Unpack/...): memoise every slot
        for i, r in enumerate(result[1]):
            ctx.memo[(base, i)] = r
        return ctx.memo[(base, slot)]
    ctx.memo[(base, 0)] = result
    if slot != 0:
        raise NotImplementedError(
            f"{base}:{slot} -- output slot {slot} of single-output op "
            f"{ndef.op}")
    return result


def _convert_node(ctx, ndef):
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn import ops as nnops
    from bigdl_tpu.nn.graph import Node
    from bigdl_tpu.nn.module import Module

    op = ndef.op
    ins = [i for i in ndef.input if not i.startswith("^")]

    if op == "Const":
        return "const", _tensor_to_np(ndef.attr["value"].tensor)
    if op in ("Identity", "StopGradient", "CheckNumerics", "PreventGradient"):
        return _convert(ctx, ins[0])
    if op in ("Placeholder", "PlaceholderV2"):
        from bigdl_tpu.nn.graph import Input
        node = ctx.input_nodes.get(ndef.name)
        if node is None:
            node = Input()
            ctx.input_nodes[ndef.name] = node
        return "node", node

    if op == "MatMul":
        x = _node_of(ctx, ins[0])
        if ndef.attr["transpose_a"].b:

            class _TransposeA(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return jnp.swapaxes(input, -1, -2), state
            x = Node(_TransposeA(), [x])
        w_kind, w_val = _convert(ctx, ins[1])
        tb = bool(ndef.attr["transpose_b"].b)
        if w_kind == "node":
            # weight is a live graph value (e.g. a trainable session
            # variable): emit the matmul as a two-input op
            class _MatMul(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    a, b = input
                    return a @ (b.T if tb else b), state
            return "node", Node(_MatMul(), [x, w_val])
        w = w_val.T if tb else w_val      # (in, out)
        mod = nn.Linear(w.shape[0], w.shape[1], with_bias=True)
        node = Node(mod, [x])

        def install(params, w=w):
            params["weight"] = jnp.asarray(w.T)     # ours is (out, in)
            params["bias"] = jnp.zeros((w.shape[1],), jnp.float32)
        ctx.module_blobs.append((mod, install))
        return "node", node

    if op == "Conv2D":
        nchw = _data_format(ndef) == "NCHW"
        hw = (2, 3) if nchw else (1, 2)
        x = _node_of(ctx, ins[0])
        st = list(ndef.attr["strides"].list.i)
        dil = list(ndef.attr["dilations"].list.i) or [1, 1, 1, 1]
        pad = ndef.attr["padding"].s.decode()
        k_kind, k_val = _convert(ctx, ins[1])
        sh, sw = int(st[hw[0]]), int(st[hw[1]])
        dh, dw = int(dil[hw[0]]), int(dil[hw[1]])
        if k_kind == "node":

            class _ConvOp(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    from jax import lax
                    a, k = input
                    y = lax.conv_general_dilated(
                        a, k.astype(a.dtype), (sh, sw), pad,
                        rhs_dilation=(dh, dw),
                        dimension_numbers=("NHWC", "HWIO", "NHWC"))
                    return y, state

            build = lambda xn: Node(_ConvOp(), [xn, k_val])
            if nchw:
                build = _nchw_wrap(build)
            return "node", build(x)
        k = k_val                          # HWIO
        mod = _tf_conv_module(k.shape, (sh, sw), (dh, dw), pad == "SAME")
        build = lambda xn: Node(mod, [xn])
        if nchw:
            build = _nchw_wrap(build)
        node = build(x)

        def install(params, k=k):
            params["weight"] = jnp.asarray(k)       # HWIO verbatim
        ctx.module_blobs.append((mod, install))
        return "node", node

    if op in ("NoOp", "Assert"):
        # ordering/validation-only nodes: nothing to compute (the reference
        # maps these to ControlDependency/Assert pass-throughs)
        return "const", np.zeros((), np.float32)
    if op == "BiasAddV1":
        op = "BiasAdd"
    if op == "BiasAdd" or (op in ("Add", "AddV2") and len(ins) == 2):
        a_kind, a_val = _convert(ctx, ins[0])
        b_kind, b_val = _convert(ctx, ins[1])
        if (op == "BiasAdd" and _data_format(ndef) == "NCHW"
                and b_kind == "const" and b_val.ndim == 1):
            # bias broadcasts over the channel axis (1), not the last;
            # the value's rank (4-D NCHW vs 5-D NCDHW) is only known at
            # apply time
            bias_cf = b_val
            if a_kind == "const":
                return "const", a_val + bias_cf.reshape(
                    (-1,) + (1,) * (a_val.ndim - 2))

            class _BiasAddCF(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    shape = (-1,) + (1,) * (input.ndim - 2)
                    return input + jnp.asarray(bias_cf).reshape(shape), \
                        state
            return "node", Node(_BiasAddCF(), [a_val])
        if a_kind == "node" and b_kind == "const":
            # fold into the producing conv/linear bias when 1-D and the
            # producer's raw output feeds ONLY this BiasAdd
            prod = a_val
            sole = ctx.consumers.get(_clean(ins[0]), 0) <= 1
            if (b_val.ndim == 1 and sole and prod.module is not None
                    and (isinstance(prod.module, nn.Linear)
                         or type(prod.module).__name__ in ("TfConv2D",
                                                           "TfConv3D"))
                    and not getattr(prod.module, "_tf_bias_set", False)):
                mod = prod.module
                mod._tf_bias_set = True

                def install(params, b=b_val):
                    params["bias"] = jnp.asarray(b)
                ctx.module_blobs.append((mod, install))
                return "node", prod
            class _AddConst(Module):
                def __init__(self, c):
                    super().__init__()
                    self.c = c

                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return input + jnp.asarray(self.c), state

            node = Node(_AddConst(b_val), [prod])
            ctx.module_blobs.append((node.module, None))
            return "node", node
        if a_kind == "node" and b_kind == "node":
            node = Node(nn.CAddTable(), [a_val, b_val])
            return "node", node
        if a_kind == "const" and b_kind == "node":
            class _AddConstL(Module):
                def __init__(self, c):
                    super().__init__()
                    self.c = c

                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return input + jnp.asarray(self.c), state
            return "node", Node(_AddConstL(a_val), [b_val])
        return "const", a_val + b_val

    if op in ("Sub", "Mul", "RealDiv", "Maximum", "Minimum"):
        a_kind, a_val = _convert(ctx, ins[0])
        b_kind, b_val = _convert(ctx, ins[1])
        table = {"Sub": nn.CSubTable, "Mul": nn.CMulTable,
                 "RealDiv": nn.CDivTable, "Maximum": nn.CMaxTable,
                 "Minimum": nn.CMinTable}
        npop = {"Sub": np.subtract, "Mul": np.multiply,
                "RealDiv": np.divide, "Maximum": np.maximum,
                "Minimum": np.minimum}
        if a_kind == "const" and b_kind == "const":
            return "const", npop[op](a_val, b_val)
        if a_kind == "node" and b_kind == "node":
            return "node", Node(table[op](), [a_val, b_val])
        const = b_val if b_kind == "const" else a_val
        x = a_val if a_kind == "node" else b_val
        if op == "Mul":
            return "node", Node(nn.MulConstant(float(const)
                                               if const.ndim == 0
                                               else const), [x])

        class _Affine(Module):
            def __init__(self, c, op_name, const_first):
                super().__init__()
                self.c, self.op_name, self.const_first = c, op_name, \
                    const_first

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                c = jnp.asarray(self.c)
                f = {"Sub": jnp.subtract, "RealDiv": jnp.divide,
                     "Maximum": jnp.maximum, "Minimum": jnp.minimum}[
                         self.op_name]
                return (f(c, input) if self.const_first
                        else f(input, c)), state

        return "node", Node(_Affine(const, op, a_kind == "const"), [x])

    if op in ("Relu", "Relu6", "Tanh", "Sigmoid", "Softmax", "Elu",
              "Softplus", "Softsign", "LogSoftmax", "Rsqrt", "Sqrt", "Exp",
              "Log", "Abs", "Neg", "Square", "Floor"):
        x = _node_of(ctx, ins[0])
        m = {"Relu": nn.ReLU, "Relu6": nn.ReLU6, "Tanh": nn.Tanh,
             "Sigmoid": nn.Sigmoid, "Softmax": nn.SoftMax, "Elu": nn.ELU,
             "Softplus": nn.SoftPlus, "Softsign": nn.SoftSign,
             "LogSoftmax": nn.LogSoftMax, "Sqrt": nn.Sqrt, "Exp": nn.Exp,
             "Abs": nn.Abs, "Negative": nn.Negative, "Neg": nn.Negative,
             "Square": nn.Square, "Floor": nnops.Floor, "Log": nn.Log}
        if op == "Rsqrt":
            class _Rsqrt(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return 1.0 / jnp.sqrt(input), state
            return "node", Node(_Rsqrt(), [x])
        return "node", Node(m[op](), [x])

    if op in ("MaxPool", "AvgPool"):
        kind_s = "max" if op == "MaxPool" else "avg"
        build = lambda xn: Node(_pool_module(ndef, kind_s), [xn])
        if _data_format(ndef) == "NCHW":
            build = _nchw_wrap(build)
        return "node", build(_node_of(ctx, ins[0]))

    if op == "Reshape":
        x = _node_of(ctx, ins[0])
        shape = [int(v) for v in _const_of(ctx, ins[1]).ravel()]
        if shape and shape[0] == -1:
            return "node", Node(nn.Reshape(tuple(shape[1:])), [x])
        return "node", Node(nn.Reshape(tuple(shape), batch_mode=False), [x])

    if op == "Squeeze":
        x = _node_of(ctx, ins[0])
        dims = tuple(int(i) for i in ndef.attr["squeeze_dims"].list.i)
        return "node", Node(nn.Squeeze(dims or None), [x])

    if op == "Mean":
        x = _node_of(ctx, ins[0])
        axes = tuple(int(v) for v in _const_of(ctx, ins[1]).ravel())
        keep = bool(ndef.attr["keep_dims"].b)
        if axes == (1, 2) and not keep:
            return "node", Node(nn.GlobalAveragePooling2D(), [x])
        return "node", Node(nnops.ReduceMean(axes, keep_dims=keep), [x])

    if op in ("ConcatV2", "Concat"):
        if op == "ConcatV2":
            parts, axis = ins[:-1], int(_const_of(ctx, ins[-1]).ravel()[0])
        else:
            axis, parts = int(_const_of(ctx, ins[0]).ravel()[0]), ins[1:]
        nodes = [_node_of(ctx, p) for p in parts]
        return "node", Node(nn.JoinTable(axis), nodes)

    if op == "Pad":
        x = _node_of(ctx, ins[0])
        pads = _const_of(ctx, ins[1]).astype(int)

        class _Pad(Module):
            def __init__(self, cfg):
                super().__init__()
                self.cfg = [tuple(r) for r in cfg]

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                return jnp.pad(input, self.cfg), state

        return "node", Node(_Pad(pads), [x])

    if op == "LRN":
        x = _node_of(ctx, ins[0])
        r = int(ndef.attr["depth_radius"].i or 5)
        bias = float(ndef.attr["bias"].f or 1.0)
        alpha = float(ndef.attr["alpha"].f or 1.0)
        beta = float(ndef.attr["beta"].f or 0.5)
        size = 2 * r + 1
        # TF: (bias + alpha*sum)^beta; ours (caffe): (k + alpha/size*sum)^beta
        return "node", Node(
            nn.SpatialCrossMapLRN(size, alpha * size, beta, bias), [x])

    if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
        x = _node_of(ctx, ins[0])
        scale = _const_of(ctx, ins[1])
        offset = _const_of(ctx, ins[2])
        mean = _const_of(ctx, ins[3])
        var = _const_of(ctx, ins[4])
        eps = float(ndef.attr["epsilon"].f or 1e-3)
        mod = nn.SpatialBatchNormalization(scale.shape[0], eps)
        build = lambda xn: Node(mod, [xn])
        if _data_format(ndef) == "NCHW":
            build = _nchw_wrap(build)
        node = build(x)

        def install(params, s=scale, o=offset):
            params["weight"] = jnp.asarray(s)
            params["bias"] = jnp.asarray(o)

        def install_state(state, m=mean, v=var):
            state["running_mean"] = jnp.asarray(m)
            state["running_var"] = jnp.asarray(v)
        ctx.module_blobs.append((mod, install))
        ctx.module_blobs.append((mod, ("state", install_state)))
        # slots 1-4 (batch_mean, batch_var, reserve_1, reserve_2) exist for
        # grad-op wiring; our FusedBatchNormGrad recomputes batch stats in
        # training mode, so the const running stats suffice as values
        return "multi", [("node", node), ("const", mean), ("const", var),
                         ("const", mean), ("const", var)]

    if op == "Cast":
        return _convert(ctx, ins[0])

    # ------------------------------------------------------------------ #
    # round-3 breadth (reference: utils/tf/loaders/ has 161 per-op files;
    # the inference-relevant set is covered here)
    # ------------------------------------------------------------------ #

    if op == "Transpose":
        kind, val = _convert(ctx, ins[0])
        perm = tuple(int(v) for v in _const_of(ctx, ins[1]).ravel())
        if kind == "const":
            return "const", np.transpose(val, perm)
        return "node", Node(nn.Permute(perm), [val])

    if op == "ExpandDims":
        kind, val = _convert(ctx, ins[0])
        axis = int(_const_of(ctx, ins[1]).ravel()[0])
        if kind == "const":
            return "const", np.expand_dims(val, axis)
        return "node", Node(nn.Unsqueeze(axis), [val])

    if op == "Fill":
        dims = tuple(int(v) for v in _const_of(ctx, ins[0]).ravel())
        return "const", np.full(dims, _const_of(ctx, ins[1]).ravel()[0])

    if op == "Range":
        args = [_const_of(ctx, i).ravel()[0] for i in ins]
        return "const", np.arange(*args)

    if op in ("ZerosLike", "OnesLike"):
        kind, val = _convert(ctx, ins[0])
        f = np.zeros_like if op == "ZerosLike" else np.ones_like
        if kind == "const":
            return "const", f(val)

        class _Like(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                return (jnp.zeros_like(input) if op == "ZerosLike"
                        else jnp.ones_like(input)), state
        return "node", Node(_Like(), [val])

    if op == "AddN":
        kinds = [_convert(ctx, i) for i in ins]
        if all(k == "const" for k, _ in kinds):
            return "const", sum(v for _, v in kinds[1:]) + kinds[0][1]
        nodes = [_node_of(ctx, i) for i in ins]
        out = nodes[0]
        for other in nodes[1:]:
            out = Node(nn.CAddTable(), [out, other])
        return "node", out

    if op == "Pow":
        a_kind, a_val = _convert(ctx, ins[0])
        b_kind, b_val = _convert(ctx, ins[1])
        if a_kind == "const" and b_kind == "const":
            return "const", np.power(a_val, b_val)
        if b_kind == "const":
            return "node", Node(nn.Power(float(b_val.ravel()[0])), [a_val])
        return "node", Node(nnops.Pow(), [_node_of(ctx, ins[0]),
                                          _node_of(ctx, ins[1])])

    if op in ("Sum", "Prod", "Max", "Min", "All", "Any"):
        x_kind, x_val = _convert(ctx, ins[0])
        axes = tuple(int(v) for v in _const_of(ctx, ins[1]).ravel())
        keep = bool(ndef.attr["keep_dims"].b)
        if x_kind == "const":
            f = {"Sum": np.sum, "Prod": np.prod, "Max": np.max,
                 "Min": np.min, "All": np.all, "Any": np.any}[op]
            return "const", f(x_val, axis=axes, keepdims=keep)
        mods = {"Sum": nnops.ReduceSum, "Prod": nnops.ReduceProd,
                "Max": nnops.ReduceMax, "Min": nnops.ReduceMin,
                "All": nnops.All, "Any": nnops.Any}
        return "node", Node(mods[op](axes, keep_dims=keep), [x_val])

    if op in ("Greater", "GreaterEqual", "Less", "LessEqual", "Equal",
              "NotEqual", "LogicalAnd", "LogicalOr"):
        a_kind, a_val = _convert(ctx, ins[0])
        b_kind, b_val = _convert(ctx, ins[1])
        npf = {"Greater": np.greater, "GreaterEqual": np.greater_equal,
               "Less": np.less, "LessEqual": np.less_equal,
               "Equal": np.equal, "NotEqual": np.not_equal,
               "LogicalAnd": np.logical_and, "LogicalOr": np.logical_or}
        if a_kind == "const" and b_kind == "const":
            return "const", npf[op](a_val, b_val)
        mods = {"Greater": nnops.Greater, "GreaterEqual": nnops.GreaterEqual,
                "Less": nnops.Less, "LessEqual": nnops.LessEqual,
                "Equal": nnops.Equal, "NotEqual": nnops.NotEqual,
                "LogicalAnd": nnops.LogicalAnd, "LogicalOr": nnops.LogicalOr}
        if a_kind == "node" and b_kind == "node":
            return "node", Node(mods[op](), [a_val, b_val])

        const = b_val if b_kind == "const" else a_val
        x = a_val if a_kind == "node" else b_val
        const_first = a_kind == "const"

        class _CmpConst(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                jf = {"Greater": jnp.greater,
                      "GreaterEqual": jnp.greater_equal,
                      "Less": jnp.less, "LessEqual": jnp.less_equal,
                      "Equal": jnp.equal, "NotEqual": jnp.not_equal,
                      "LogicalAnd": jnp.logical_and,
                      "LogicalOr": jnp.logical_or}[op]
                c = jnp.asarray(const)
                return (jf(c, input) if const_first else jf(input, c)), state
        return "node", Node(_CmpConst(), [x])

    if op == "LogicalNot":
        return "node", Node(nnops.LogicalNot(), [_node_of(ctx, ins[0])])

    if op == "Select" or op == "SelectV2":
        c = _node_of(ctx, ins[0])
        a_kind, a_val = _convert(ctx, ins[1])
        b_kind, b_val = _convert(ctx, ins[2])

        class _Where(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                cond = input[0]
                t = input[1] if a_kind == "node" else jnp.asarray(a_val)
                f = input[-1] if b_kind == "node" else jnp.asarray(b_val)
                return jnp.where(cond, t, f), state

        parents = [c] + [v for k, v in ((a_kind, a_val), (b_kind, b_val))
                         if k == "node"]
        return "node", Node(_Where(), parents)

    if op == "OneHot":
        kind, val = _convert(ctx, ins[0])
        depth = int(_const_of(ctx, ins[1]).ravel()[0])
        on = float(_const_of(ctx, ins[2]).ravel()[0])
        off = float(_const_of(ctx, ins[3]).ravel()[0])
        if kind == "const":
            eye = np.where(np.arange(depth) == val[..., None], on, off)
            return "const", eye.astype(np.float32)
        return "node", Node(nnops.OneHot(depth, on, off), [val])

    if op in ("Pack", "Stack"):
        axis = int(ndef.attr["axis"].i)
        kinds = [_convert(ctx, i) for i in ins]
        if all(k == "const" for k, _ in kinds):
            return "const", np.stack([v for _, v in kinds], axis)
        nodes = [_node_of(ctx, i) for i in ins]

        class _Stack(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                xs = input if isinstance(input, tuple) else (input,)
                return jnp.stack(xs, axis), state
        return "node", Node(_Stack(), nodes)

    if op in ("Unpack", "Unstack"):
        axis = int(ndef.attr["axis"].i)
        num = int(ndef.attr["num"].i)
        kind, val = _convert(ctx, ins[0])
        if kind == "const":
            return "multi", [("const", np.squeeze(a, axis)) for a in
                             np.split(val, num, axis)]

        class _Pick(Module):
            def __init__(self, k):
                super().__init__()
                self.k = k

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                return jnp.squeeze(
                    jnp.take(input, jnp.asarray([self.k]), axis=axis),
                    axis), state
        return "multi", [("node", Node(_Pick(k), [val]))
                         for k in range(num)]

    if op in ("Split", "SplitV"):
        if op == "Split":
            axis = int(_const_of(ctx, ins[0]).ravel()[0])
            x = _node_of(ctx, ins[1])
            num = int(ndef.attr["num_split"].i)
            sizes = None
        else:
            x = _node_of(ctx, ins[0])
            sizes = [int(v) for v in _const_of(ctx, ins[1]).ravel()]
            axis = int(_const_of(ctx, ins[2]).ravel()[0])
            num = len(sizes)

        def make_slice(k):
            class _Slice(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    if sizes is None:
                        parts = jnp.split(input, num, axis)
                    else:
                        idx = np.cumsum([0] + sizes)
                        parts = [lax_dynamic_slice_axis(input, idx[i],
                                                        sizes[i], axis)
                                 for i in range(num)]
                    return parts[k], state
            return _Slice()

        def lax_dynamic_slice_axis(xv, start, size, ax):
            sl = [slice(None)] * xv.ndim
            sl[ax] = slice(start, start + size)
            return xv[tuple(sl)]

        return "multi", [("node", Node(make_slice(k), [x]))
                         for k in range(num)]

    if op == "Slice":
        kind, val = _convert(ctx, ins[0])
        begin = [int(v) for v in _const_of(ctx, ins[1]).ravel()]
        size = [int(v) for v in _const_of(ctx, ins[2]).ravel()]
        if kind == "const":
            sl = tuple(slice(b, None if s == -1 else b + s)
                       for b, s in zip(begin, size))
            return "const", val[sl]
        return "node", Node(nnops.Slice(begin, size), [val])

    if op == "StridedSlice":
        kind, val = _convert(ctx, ins[0])
        begin = [int(v) for v in _const_of(ctx, ins[1]).ravel()]
        end = [int(v) for v in _const_of(ctx, ins[2]).ravel()]
        strides = [int(v) for v in _const_of(ctx, ins[3]).ravel()]
        bm = int(ndef.attr["begin_mask"].i)
        em = int(ndef.attr["end_mask"].i)
        sm = int(ndef.attr["shrink_axis_mask"].i)
        nm = int(ndef.attr["new_axis_mask"].i)
        elm = int(ndef.attr["ellipsis_mask"].i)
        # numpy/jnp advanced indexing natively expresses every mask:
        # Ellipsis for ellipsis_mask, None for new_axis_mask, an integer
        # index for shrink_axis_mask (reference: loaders/StridedSlice.scala
        # builds the same spec for its slice op)
        sls = []
        for i in range(len(begin)):
            if (elm >> i) & 1:
                sls.append(Ellipsis)
            elif (nm >> i) & 1:
                sls.append(None)
            elif (sm >> i) & 1:
                sls.append(begin[i])
            else:
                b = None if (bm >> i) & 1 else begin[i]
                e = None if (em >> i) & 1 else end[i]
                sls.append(slice(b, e, strides[i]))
        sls = tuple(sls)
        if kind == "const":
            return "const", val[sls]

        class _StridedSlice(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                return input[sls], state
        return "node", Node(_StridedSlice(), [val])

    if op == "Tile":
        kind, val = _convert(ctx, ins[0])
        mult = tuple(int(v) for v in _const_of(ctx, ins[1]).ravel())
        if kind == "const":
            return "const", np.tile(val, mult)
        return "node", Node(nnops.Tile(mult), [val])

    if op in ("Gather", "GatherV2"):
        kind, val = _convert(ctx, ins[0])
        i_kind, idx = _convert(ctx, ins[1])
        axis = 0
        if op == "GatherV2" and len(ins) > 2:
            axis = int(_const_of(ctx, ins[2]).ravel()[0])
        if kind == "const" and i_kind == "const":
            return "const", np.take(val, idx.astype(np.int64), axis)
        if kind == "const" and i_kind == "node":
            table = val

            class _Lookup(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return jnp.take(jnp.asarray(table),
                                    input.astype(jnp.int32), axis), state
            return "node", Node(_Lookup(), [idx])
        return "node", Node(nnops.Gather(axis), [val, _node_of(ctx, ins[1])])

    if op == "DepthwiseConv2dNative":
        nchw = _data_format(ndef) == "NCHW"
        hw = (2, 3) if nchw else (1, 2)
        x = _node_of(ctx, ins[0])
        k = _const_of(ctx, ins[1])        # (kh, kw, cin, mult)
        st_raw = list(ndef.attr["strides"].list.i) or [1, 1, 1, 1]
        st = [1, int(st_raw[hw[0]]), int(st_raw[hw[1]]), 1]
        pad = ndef.attr["padding"].s.decode()
        kh, kw, cin, mult = k.shape

        class _DwConv(Module):
            def setup(self, rng, input_spec):
                return {"weight": jnp.zeros((kh, kw, cin, mult),
                                            jnp.float32)}, ()

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                from jax import lax
                w = params["weight"].astype(input.dtype)
                # depthwise = grouped conv with cin groups; HWIO with
                # O = cin*mult, I = 1.  TF output channel c*mult + m ==
                # row-major merge of the trailing (cin, mult) dims
                w = w.reshape(kh, kw, 1, cin * mult)
                y = lax.conv_general_dilated(
                    input, w, (int(st[1]), int(st[2])), pad,
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    feature_group_count=cin)
                return y, state

        mod = _DwConv()
        build = lambda xn: Node(mod, [xn])
        if nchw:
            build = _nchw_wrap(build)
        node = build(x)

        def install(params, k=k):
            params["weight"] = jnp.asarray(k)
        ctx.module_blobs.append((mod, install))
        return "node", node

    if op == "Conv2DBackpropInput":
        # deconvolution used as a forward op (e.g. FCN upsampling)
        out_shape = [int(v) for v in _const_of(ctx, ins[0]).ravel()]
        k = _const_of(ctx, ins[1])        # (kh, kw, cout, cin) HWOI for bwd
        x = _node_of(ctx, ins[2])
        st = list(ndef.attr["strides"].list.i)
        pad = ndef.attr["padding"].s.decode()
        kh, kw, cout, cin = k.shape

        class _Deconv(Module):
            def setup(self, rng, input_spec):
                return {"weight": jnp.zeros((kh, kw, cout, cin),
                                            jnp.float32)}, ()

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                from jax import lax
                w = params["weight"].astype(input.dtype)
                # TF filter (kh, kw, cout, cin) IS the forward-conv HWIO
                # kernel of the conv being transposed (I=cout, O=cin);
                # transpose_kernel=True swaps the roles back
                y = lax.conv_transpose(
                    input, w, (int(st[1]), int(st[2])), pad,
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    transpose_kernel=True)
                return y[:, :out_shape[1], :out_shape[2], :], state

        mod = _Deconv()
        node = Node(mod, [x])

        def install(params, k=k):
            params["weight"] = jnp.asarray(k)
        ctx.module_blobs.append((mod, install))
        return "node", node

    if op in ("VariableV2", "Variable", "VarHandleOp"):
        # un-frozen graph: the variable's value is the Const assigned to it
        # (ref-style Assign or TF2 resource-style AssignVariableOp)
        for n in ctx.nodes.values():
            if n.op in ("Assign", "AssignVariableOp") \
                    and _clean(n.input[0]) == ndef.name:
                if getattr(ctx, "trainable", False):
                    init_kind, init_val = _convert(ctx, n.input[1])
                    if init_kind != "const":
                        raise NotImplementedError(
                            f"{ndef.name}: non-constant initializer in "
                            f"trainable session mode")

                    class _TfVariable(Module):
                        """A graph variable as a trainable parameter
                        (reference: Session.scala constructModel trains the
                        imported graph's variables)."""

                        def setup(self, rng, input_spec):
                            return {"value": jnp.asarray(
                                np.asarray(init_val, np.float32))}, ()

                        def apply(self, params, state, input, *,
                                  training=False, rng=None):
                            return params["value"], state

                    var = _TfVariable()
                    var.name = ndef.name.replace("/", "_")
                    return "node", Node(var, [])
                return _convert(ctx, n.input[1])
        raise NotImplementedError(
            f"{op} {ndef.name} has no Assign initializer in-graph")
    if op in ("Assign", "AssignVariableOp"):
        return _convert(ctx, ins[1])
    if op == "ReadVariableOp":
        return _convert(ctx, ins[0])

    if op == "Exit":
        return _convert_while_frame(ctx, ndef)
    if op == "Enter":
        # reached directly only for frame-invariant values
        return _convert(ctx, ins[0])

    if op == "Switch":
        raise NotImplementedError(
            f"Switch {ndef.name} consumed outside a Merge/Exit -- tf.cond "
            f"diamonds are lowered at their Merge (see _convert_cond_merge)")

    if op == "Merge":
        return _convert_cond_merge(ctx, ndef)

    if op == "Shape":
        raise NotImplementedError(
            "dynamic Shape op (import the inference subgraph only)")

    extra = _convert_extra_op(ctx, ndef, op, ins)
    if extra is not None:
        return extra
    raise NotImplementedError(f"TF op {op} has no converter")


def _convert_extra_op(ctx, ndef, op, ins):
    """Wide op coverage: elementwise math, comparisons and explicit-gradient
    ops (reference: utils/tf/loaders/ -- one loader class per op, 161 total;
    the *Grad ops appear in TF training graphs, which Session training
    imports -- Session.scala:105).  Returns None for unknown ops."""
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn import ops as nnops
    from bigdl_tpu.nn.graph import Node
    from bigdl_tpu.nn.module import Module

    def unary_node(mod):
        """Emit a unary op, folding constant operands through the module's
        own apply (frozen graphs do shape math with these)."""
        kind, val = _convert(ctx, ins[0])
        if kind == "const":
            out, _ = mod.apply({}, (), jnp.asarray(val))
            return "const", np.asarray(out)
        return "node", Node(mod, [val])

    def bin_node(fn, in_a, in_b):
        """Emit a binary op with any mix of node/const operands."""
        a_kind, a_val = _convert(ctx, in_a)
        b_kind, b_val = _convert(ctx, in_b)
        if a_kind == "const" and b_kind == "const":
            return "const", np.asarray(fn(jnp.asarray(a_val),
                                          jnp.asarray(b_val)))

        class _Bin2(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                if a_kind == "node" and b_kind == "node":
                    a, b = input
                elif a_kind == "const":
                    a, b = jnp.asarray(a_val), input
                else:
                    a, b = input, jnp.asarray(b_val)
                return fn(a, b), state
        parents = [v for k, v in ((a_kind, a_val), (b_kind, b_val))
                   if k == "node"]
        return "node", Node(_Bin2(), parents)

    unary = {
        "Ceil": nnops.Ceil, "Round": nnops.Round, "Rint": nnops.Rint,
        "Sign": nnops.Sign, "Expm1": nnops.Expm1, "Erf": nnops.Erf,
        "Erfc": nnops.Erfc, "Lgamma": nnops.Lgamma,
        "Digamma": nnops.Digamma, "Inv": nnops.Inv,
        "Reciprocal": nnops.Inv, "IsFinite": nnops.IsFinite,
        "IsInf": nnops.IsInf, "IsNan": nnops.IsNan, "Rank": nnops.Rank,
        "L2Loss": nnops.L2Loss,
    }
    if op in unary:
        return unary_node(unary[op]())
    if op == "Log1p":
        class _Log1p(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                return jnp.log1p(input), state
        return unary_node(_Log1p())

    binary = {
        "Div": jnp.divide, "FloorDiv": jnp.floor_divide, "Mod": jnp.fmod,
        "FloorMod": jnp.mod, "TruncateMod": jnp.fmod,
        "TruncateDiv": lambda a, b: jnp.trunc(a / b).astype(a.dtype),
        "SquaredDifference": lambda a, b: jnp.square(a - b),
        # explicit-gradient ops out of tf.gradients graphs
        "ReluGrad": lambda g, x: g * (x > 0).astype(g.dtype),
        "Relu6Grad": lambda g, x: g * ((x > 0) & (x < 6)).astype(g.dtype),
        "SigmoidGrad": lambda y, g: g * y * (1.0 - y),
        "TanhGrad": lambda y, g: g * (1.0 - jnp.square(y)),
        "SqrtGrad": lambda y, g: g * 0.5 / y,
        "RsqrtGrad": lambda y, g: -0.5 * g * y * y * y,
        "SoftplusGrad": lambda g, x: g * jax.nn.sigmoid(x),
        "SoftsignGrad": lambda g, x: g / jnp.square(1.0 + jnp.abs(x)),
        "EluGrad": lambda g, y: g * jnp.where(y > 0, 1.0, y + 1.0),
        "InvGrad": lambda y, g: -g * y * y,
        "ReciprocalGrad": lambda y, g: -g * y * y,
    }
    if op in binary:
        return bin_node(binary[op], ins[0], ins[1])

    if op == "ApproximateEqual":
        tol = (float(ndef.attr["tolerance"].f)
               if "tolerance" in ndef.attr else 1e-5)
        return bin_node(lambda x, y: jnp.abs(x - y) < tol, ins[0], ins[1])

    if op in ("BatchMatMul", "BatchMatMulV2"):
        adj_x = bool(ndef.attr["adj_x"].b)
        adj_y = bool(ndef.attr["adj_y"].b)

        def bmm(x, y):
            if adj_x:
                x = jnp.swapaxes(x, -1, -2)
            if adj_y:
                y = jnp.swapaxes(y, -1, -2)
            return jnp.matmul(x, y)
        return bin_node(bmm, ins[0], ins[1])

    if op == "ArgMax":
        axis = int(_const_of(ctx, ins[1]).ravel()[0])
        return "node", Node(nnops.ArgMax(axis), [_node_of(ctx, ins[0])])

    if op in ("TopK", "TopKV2"):
        if op == "TopK":
            k = int(ndef.attr["k"].i)
        else:
            k = int(_const_of(ctx, ins[1]).ravel()[0])
        x = _node_of(ctx, ins[0])

        def pick(j):
            class _TopKPart(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return jax.lax.top_k(input, k)[j], state
            return _TopKPart()
        return "multi", [("node", Node(pick(0), [x])),
                         ("node", Node(pick(1), [x]))]

    if op in ("InTopK", "InTopKV2"):
        if op == "InTopK":
            k = int(ndef.attr["k"].i)
        else:
            k = int(_const_of(ctx, ins[2]).ravel()[0])
        return bin_node(
            lambda p, t: nnops.InTopK(k).apply({}, (), (p, t))[0],
            ins[0], ins[1])

    if op == "SoftmaxCrossEntropyWithLogits":
        logits = _node_of(ctx, ins[0])
        labels = _node_of(ctx, ins[1])

        class _SoftmaxXent(Module):
            """-> (loss (N,), backprop (N, C)) like the TF op."""

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                lg, lb = input
                lsm = jax.nn.log_softmax(lg, axis=-1)
                return -jnp.sum(lb * lsm, axis=-1), state

        class _SoftmaxXentGrad(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                lg, lb = input
                return jax.nn.softmax(lg, axis=-1) - lb, state
        return "multi", [
            ("node", Node(_SoftmaxXent(), [logits, labels])),
            ("node", Node(_SoftmaxXentGrad(), [logits, labels]))]

    if op == "BiasAddGrad":
        g = _node_of(ctx, ins[0])

        class _BiasAddGrad(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                return jnp.sum(input, axis=tuple(range(input.ndim - 1))), \
                    state
        return "node", Node(_BiasAddGrad(), [g])

    if op == "SegmentSum":
        data = _node_of(ctx, ins[0])
        seg_kind, seg_val = _convert(ctx, ins[1])
        if seg_kind == "const":
            num = int(np.max(seg_val)) + 1

            class _SegSumC(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return jax.ops.segment_sum(
                        input, jnp.asarray(seg_val, jnp.int32),
                        num_segments=num), state
            return "node", Node(_SegSumC(), [data])
        return "node", Node(nnops.SegmentSum(), [data, seg_val])

    if op == "Conv3D":
        fmt = ndef.attr["data_format"].s.decode() or "NDHWC"
        ncdhw = fmt == "NCDHW"
        sl = slice(2, 5) if ncdhw else slice(1, 4)
        strides = tuple(ndef.attr["strides"].list.i)[sl] or (1, 1, 1)
        dil = tuple(ndef.attr["dilations"].list.i)[sl] or (1, 1, 1)
        padding = ndef.attr["padding"].s.decode() or "VALID"
        w_kind, w_val = _convert(ctx, ins[1])
        x = _node_of(ctx, ins[0])
        if w_kind == "node":
            st3, dil3, pad3 = strides, dil, padding

            class _Conv3DOp(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    from jax import lax
                    a, k = input
                    y = lax.conv_general_dilated(
                        a, k.astype(a.dtype), st3, pad3,
                        rhs_dilation=dil3,
                        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
                    return y, state

            build = lambda xn: Node(_Conv3DOp(), [xn, w_val])
            if ncdhw:
                build = _nchw_wrap(build, rank=5)
            return "node", build(x)
        w = np.asarray(w_val, np.float32)      # (kd, kh, kw, cin, cout)
        w_shape = w.shape                       # class captures shape only

        class TfConv3D(Module):
            """TF-exact 3-D conv: filter/bias as PARAMETERS (trainable,
            BiasAdd-foldable) like the 2-D TfConv2D; lax string padding
            reproduces TF SAME."""

            def setup(self, rng, input_spec):
                return {"weight": jnp.zeros(w_shape, jnp.float32),
                        "bias": jnp.zeros((w_shape[-1],), jnp.float32)}, ()

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                from jax import lax
                y = lax.conv_general_dilated(
                    input, params["weight"].astype(input.dtype),
                    window_strides=strides, padding=padding,
                    rhs_dilation=dil,
                    dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
                return y + params["bias"].astype(y.dtype), state

        mod = TfConv3D()
        build = lambda xn: Node(mod, [xn])
        if ncdhw:
            # NB: the permute wrapper blocks the BiasAdd fold into the
            # conv bias; the channels-first BiasAdd module handles it
            build = _nchw_wrap(build, rank=5)
        node = build(x)

        def install(params, w=w):
            params["weight"] = jnp.asarray(w)
        ctx.module_blobs.append((mod, install))
        return "node", node

    if op == "RandomShuffle":
        x = _node_of(ctx, ins[0])
        seed = int(ndef.attr["seed"].i)

        class _RandomShuffle(Module):
            """Shuffle along axis 0 (reference: loaders/RandomShuffle.scala)."""

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                key = rng if rng is not None else jax.random.key(seed)
                return jax.random.permutation(key, input, axis=0), state
        return "node", Node(_RandomShuffle(), [x])

    if op == "RandomUniform":
        shape = tuple(int(v) for v in _const_of(ctx, ins[0]).ravel())
        seed = int(ndef.attr["seed"].i)

        class _RandomUniform(Module):
            """Deterministic under the framework rng (reference:
            loaders/RandomUniform.scala seeds the BigDL RNG)."""

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                key = rng if rng is not None else jax.random.key(seed)
                return jax.random.uniform(key, shape), state
        return "node", Node(_RandomUniform(), [])

    if op == "ResizeBilinear":
        size = tuple(int(v) for v in _const_of(ctx, ins[1]).ravel())
        align = bool(ndef.attr["align_corners"].b)
        half_pixel = bool(ndef.attr["half_pixel_centers"].b)
        x = _node_of(ctx, ins[0])

        class _ResizeBilinear(Module):
            """TF1 legacy grid (src = dst*scale), align_corners
            (src = dst*(in-1)/(out-1)), or half-pixel centers, per the
            attrs."""

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                out_shape = (input.shape[0],) + size + (input.shape[-1],)
                if half_pixel:
                    return jax.image.resize(input, out_shape,
                                            "bilinear"), state
                return _tf1_resize_bilinear(input, size,
                                            align_corners=align), state
        return "node", Node(_ResizeBilinear(), [x])

    return _convert_grad_data_op(ctx, ndef, op, ins)


def _tf1_resize_bilinear(input, size, align_corners=False):
    """TF1 resize grids: legacy (src = dst * in/out) or align_corners
    (src = dst * (in-1)/(out-1))."""
    import jax.numpy as jnp

    in_h, in_w = input.shape[1], input.shape[2]
    out = input
    for axis, (n_in, n_out) in ((1, (in_h, size[0])),
                                (2, (in_w, size[1]))):
        if align_corners:
            scale = (n_in - 1) / (n_out - 1) if n_out > 1 else 0.0
            src = jnp.arange(n_out) * scale
        else:
            src = jnp.arange(n_out) * (n_in / n_out)
        lo = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, n_in - 1)
        hi = jnp.clip(lo + 1, 0, n_in - 1)
        w = (src - lo).astype(input.dtype)
        shape = [1] * out.ndim
        shape[axis] = n_out
        w = w.reshape(shape)
        out = (jnp.take(out, lo, axis=axis) * (1 - w)
               + jnp.take(out, hi, axis=axis) * w)
    return out


def _convert_grad_data_op(ctx, ndef, op, ins):
    """Reference-loader parity tail (round-4): pooling/conv/BN backward ops
    as the vjp of the matching forward (autodiff replaces the reference's
    hand-written backward loaders, e.g. loaders/MaxPoolGrad.scala),
    morphological Dilation2D (+grads), queue/reader plumbing (Identity
    semantics per loaders/QueueDequeueV2.scala -- data enters/leaves the
    graph there), tf.Example parsing and image decoding (host-side const
    evaluation; runtime decoding belongs to the data pipeline).  Returns
    None for unknown ops."""
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.graph import Input, Node
    from bigdl_tpu.nn.module import Module

    def _parents(*names):
        """Mixed const/node operands: returns (getters, node_parents);
        getters[i](input) yields operand i inside Module.apply (input is
        the bare value for one parent, the tuple for several)."""
        kinds = [_convert(ctx, i) for i in names]
        parents = [v for k, v in kinds if k == "node"]
        getters, pos = [], 0
        for k, v in kinds:
            if k == "node":
                getters.append(lambda inp, i=pos, n=len(parents):
                               inp[i] if n > 1 else inp)
                pos += 1
            else:
                getters.append(lambda inp, c=v: jnp.asarray(c))
        return getters, parents

    # ---- queue / reader plumbing (reference: Identity loaders) -------- #
    if op in ("QueueDequeueV2", "QueueDequeueManyV2", "ReaderReadV2"):
        # data ENTERS the graph here: each output slot becomes an Input
        # socket the caller feeds (the reference cuts its training graphs
        # at the dequeue the same way, Session.scala)
        n_out = (2 if op == "ReaderReadV2"
                 else len(ndef.attr["component_types"].list.type) or 1)
        outs = []
        for i in range(n_out):
            key = ndef.name if i == 0 else f"{ndef.name}:{i}"
            node = ctx.input_nodes.get(key)
            if node is None:
                node = Input()
                ctx.input_nodes[key] = node
            outs.append(("node", node))
        return ("multi", outs) if n_out > 1 else outs[0]
    if op in ("QueueEnqueueV2", "QueueEnqueueManyV2"):
        # pass the enqueued components through (ins[0] is the queue handle)
        data = ins[1:] if len(ins) > 1 else ins
        if len(data) == 1:
            return _convert(ctx, data[0])
        return "multi", [_convert(ctx, i) for i in data]

    # ---- host-side data ops (const evaluation) ------------------------ #
    if op in ("DecodeJpeg", "DecodePng", "DecodeBmp", "DecodeGif"):
        kind, val = _convert(ctx, ins[0])
        if kind != "const":
            raise NotImplementedError(
                f"{op} on a runtime tensor: decode images host-side in the "
                "data pipeline (bigdl_tpu.transform.vision / "
                "dataset.image_folder), where the reference's runtime "
                "decoders also live")
        import io

        from PIL import Image

        img = Image.open(io.BytesIO(val.ravel()[0]))
        if op == "DecodeGif":          # (num_frames, h, w, 3) like TF
            frames = []
            try:
                while True:
                    frames.append(np.asarray(img.convert("RGB"), np.uint8))
                    img.seek(img.tell() + 1)
            except EOFError:
                pass
            return "const", np.stack(frames)
        channels = int(ndef.attr["channels"].i)
        if channels == 1:
            img = img.convert("L")
        elif channels == 3:
            img = img.convert("RGB")
        elif channels == 4:
            img = img.convert("RGBA")
        arr = np.asarray(img, np.uint8)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return "const", arr

    if op == "DecodeRaw":
        kind, val = _convert(ctx, ins[0])
        if kind != "const":
            raise NotImplementedError(
                "DecodeRaw on a runtime tensor: decode bytes host-side in "
                "the data pipeline")
        out_np = _DT_NP.get(ndef.attr["out_type"].type, np.float32)
        # TF's op default is little_endian=true; an absent attr (e.g.
        # strip_default_attrs) must not flip the byte order
        little = (bool(ndef.attr["little_endian"].b)
                  if "little_endian" in ndef.attr else True)
        dt = np.dtype(out_np).newbyteorder("<" if little else ">")
        rows = [np.frombuffer(b, dt).astype(out_np) for b in val.ravel()]
        return "const", np.stack(rows).reshape(val.shape + (-1,))

    if op == "Substr":
        kind, val = _convert(ctx, ins[0])
        if kind != "const":
            raise NotImplementedError("Substr on a runtime tensor")
        pos = _const_of(ctx, ins[1]).astype(np.int64)
        length = _const_of(ctx, ins[2]).astype(np.int64)
        # TF broadcasts pos/len against the input shape
        pos = np.broadcast_to(pos, val.shape)
        length = np.broadcast_to(length, val.shape)
        flat = val.ravel()
        p, l = pos.ravel(), length.ravel()
        out = np.empty(flat.shape, object)
        for i, b in enumerate(flat):
            out[i] = bytes(b)[int(p[i]):int(p[i]) + int(l[i])]
        return "const", out.reshape(val.shape)

    if op in ("ParseExample", "ParseSingleExample"):
        from bigdl_tpu.interop.tfrecord import parse_example
        kind, ser = _convert(ctx, ins[0])
        if kind != "const":
            raise NotImplementedError(
                f"{op} on a runtime tensor: parse tf.Example records "
                "host-side via bigdl_tpu.interop.tfrecord (TFRecordReader "
                "+ parse_example) and feed the parsed tensors as inputs")
        if op == "ParseExample":
            nsparse = int(ndef.attr["Nsparse"].i)
            ndense = int(ndef.attr["Ndense"].i)
            if nsparse:
                raise NotImplementedError("ParseExample sparse features")
            keys = [bytes(_const_of(ctx, ins[2 + j]).ravel()[0])
                    for j in range(ndense)]
            shapes = [tuple(int(d.size) for d in sh.dim)
                      for sh in ndef.attr["dense_shapes"].list.shape]
            records = [parse_example(bytes(b))
                       for b in np.atleast_1d(ser).ravel()]
            outs = []
            for j, k in enumerate(keys):
                vals = [np.asarray(ex[k.decode()]).reshape(shapes[j])
                        for ex in records]
                outs.append(("const", np.stack(vals)))
            return ("multi", outs) if len(outs) > 1 else outs[0]
        keys = [bytes(s) for s in ndef.attr["dense_keys"].list.s]
        shapes = [tuple(int(d.size) for d in sh.dim)
                  for sh in ndef.attr["dense_shapes"].list.shape]
        ex = parse_example(bytes(np.asarray(ser).ravel()[0]))
        outs = [("const", np.asarray(ex[k.decode()]).reshape(shapes[j]))
                for j, k in enumerate(keys)]
        return ("multi", outs) if len(outs) > 1 else outs[0]

    if op == "BroadcastGradientArgs":
        s0 = [int(v) for v in _const_of(ctx, ins[0]).ravel()]
        s1 = [int(v) for v in _const_of(ctx, ins[1]).ravel()]
        n = max(len(s0), len(s1))
        p0 = [1] * (n - len(s0)) + s0
        p1 = [1] * (n - len(s1)) + s1
        r0 = [i for i in range(n) if p0[i] == 1 and p1[i] != 1]
        r1 = [i for i in range(n) if p1[i] == 1 and p0[i] != 1]
        return "multi", [("const", np.asarray(r0, np.int32)),
                         ("const", np.asarray(r1, np.int32))]

    # ---- backward ops = vjp of the matching forward ------------------- #
    if op in ("MaxPoolGrad", "AvgPoolGrad"):
        ks = list(ndef.attr["ksize"].list.i)
        st = list(ndef.attr["strides"].list.i)
        nchw = _data_format(ndef) == "NCHW"
        hw = (2, 3) if nchw else (1, 2)
        pad = ndef.attr["padding"].s.decode()
        kh, kw = int(ks[hw[0]]), int(ks[hw[1]])
        sh, sw = int(st[hw[0]]), int(st[hw[1]])
        dims = (1, 1, kh, kw) if nchw else (1, kh, kw, 1)
        strides = (1, 1, sh, sw) if nchw else (1, sh, sw, 1)
        if op == "MaxPoolGrad":
            getters, parents = _parents(ins[0], ins[2])

            class _MaxPoolGrad(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    from jax import lax
                    xx, gg = getters[0](input), getters[1](input)
                    f = lambda a: lax.reduce_window(
                        a, -jnp.inf, lax.max, dims, strides, pad)
                    _, vjp = jax.vjp(f, xx)
                    return vjp(gg.astype(xx.dtype))[0], state
            return "node", Node(_MaxPoolGrad(), parents)
        shape = tuple(int(v) for v in _const_of(ctx, ins[0]).ravel())
        getters, parents = _parents(ins[1])

        class _AvgPoolGrad(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                from jax import lax
                gg = getters[0](input)

                def f(a):
                    tot = lax.reduce_window(a, 0.0, lax.add, dims, strides,
                                            pad)
                    cnt = lax.reduce_window(jnp.ones_like(a), 0.0, lax.add,
                                            dims, strides, pad)
                    return tot / cnt
                # avg pooling is linear: vjp at zeros is exact
                _, vjp = jax.vjp(f, jnp.zeros(shape, gg.dtype))
                return vjp(gg)[0], state
        return "node", Node(_AvgPoolGrad(), parents)

    if op == "Conv2DBackpropFilter":
        nchw = _data_format(ndef) == "NCHW"
        hw = (2, 3) if nchw else (1, 2)
        st = list(ndef.attr["strides"].list.i)
        dil = list(ndef.attr["dilations"].list.i) or [1, 1, 1, 1]
        pad = ndef.attr["padding"].s.decode()
        sh, sw = int(st[hw[0]]), int(st[hw[1]])
        dh, dw = int(dil[hw[0]]), int(dil[hw[1]])
        dn = (("NCHW", "HWIO", "NCHW") if nchw
              else ("NHWC", "HWIO", "NHWC"))
        fshape = tuple(int(v) for v in _const_of(ctx, ins[1]).ravel())
        getters, parents = _parents(ins[0], ins[2])

        class _ConvBpF(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                from jax import lax
                xx, gg = getters[0](input), getters[1](input)
                f = lambda w: lax.conv_general_dilated(
                    xx, w, (sh, sw), pad, rhs_dilation=(dh, dw),
                    dimension_numbers=dn)
                # conv is linear in the filter: vjp at zeros is exact
                _, vjp = jax.vjp(f, jnp.zeros(fshape, xx.dtype))
                return vjp(gg.astype(xx.dtype))[0], state
        return "node", Node(_ConvBpF(), parents)

    if op in ("Conv3DBackpropInput", "Conv3DBackpropInputV2",
              "Conv3DBackpropFilter", "Conv3DBackpropFilterV2"):
        fmt = ndef.attr["data_format"].s.decode() or "NDHWC"
        ncdhw = fmt == "NCDHW"
        st = list(ndef.attr["strides"].list.i)
        sl = slice(2, 5) if ncdhw else slice(1, 4)
        sd, sh, sw = (int(v) for v in st[sl])
        pad = ndef.attr["padding"].s.decode()
        dn = ("NDHWC", "DHWIO", "NDHWC")
        to_last = (0, 2, 3, 4, 1)        # NCDHW activation -> NDHWC
        to_first = (0, 4, 1, 2, 3)

        def conv3d(a, w):
            from jax import lax
            return lax.conv_general_dilated(a, w, (sd, sh, sw), pad,
                                            dimension_numbers=dn)

        wrt_input = "Input" in op
        # V2 passes the reconstructed tensor's SIZES as a const vector;
        # V1 passes the original tensor itself (used for its shape only)
        size_in = ins[0] if wrt_input else ins[1]
        k_kind, k_val = _convert(ctx, size_in)
        static_shape = None
        if k_kind == "const" and np.asarray(k_val).ndim == 1:
            static_shape = tuple(int(v) for v in np.asarray(k_val).ravel())
            if ncdhw and wrt_input:      # sizes arrive in NCDHW order
                static_shape = tuple(static_shape[i] for i in to_last)
            other = ins[1] if wrt_input else ins[0]
            getters, parents = _parents(other, ins[2])
            g_shape = None
        else:
            getters, parents = _parents(ins[0], ins[1], ins[2])
            g_shape = getters[0] if wrt_input else getters[1]
            getters = ([getters[1], getters[2]] if wrt_input
                       else [getters[0], getters[2]])

        class _Conv3DBp(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                other, gg = getters[0](input), getters[1](input)
                if ncdhw:                # activations arrive NCDHW
                    gg = jnp.transpose(gg, to_last)
                    if not wrt_input:    # `other` is the input activation
                        other = jnp.transpose(other, to_last)
                if static_shape is not None:
                    shape = static_shape
                else:
                    shape = g_shape(input).shape
                    if ncdhw and wrt_input:
                        shape = tuple(shape[i] for i in to_last)
                zeros = jnp.zeros(shape, gg.dtype)
                if wrt_input:
                    f = lambda a: conv3d(a, other.astype(gg.dtype))
                else:
                    f = lambda w: conv3d(other.astype(gg.dtype), w)
                _, vjp = jax.vjp(f, zeros)
                out = vjp(gg)[0]
                if ncdhw and wrt_input:  # input-grad back to NCDHW
                    out = jnp.transpose(out, to_first)
                return out, state
        return "node", Node(_Conv3DBp(), parents)

    if op in ("DepthwiseConv2dNativeBackpropInput",
              "DepthwiseConv2dNativeBackpropFilter"):
        nchw = _data_format(ndef) == "NCHW"
        hw = (2, 3) if nchw else (1, 2)
        st = list(ndef.attr["strides"].list.i)
        pad = ndef.attr["padding"].s.decode()
        sh, sw = int(st[hw[0]]), int(st[hw[1]])
        wrt_input = op.endswith("Input")
        shape = tuple(int(v) for v in
                      _const_of(ctx, ins[0] if wrt_input else ins[1])
                      .ravel())
        getters, parents = _parents(ins[1] if wrt_input else ins[0],
                                    ins[2])

        def dwconv(a, w):
            from jax import lax
            kh, kw, cin, mult = w.shape
            wr = w.reshape(kh, kw, 1, cin * mult)
            if nchw:
                a = jnp.transpose(a, (0, 2, 3, 1))
            y = lax.conv_general_dilated(
                a, wr, (sh, sw), pad,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=cin)
            return jnp.transpose(y, (0, 3, 1, 2)) if nchw else y

        class _DwBp(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                other, gg = getters[0](input), getters[1](input)
                zeros = jnp.zeros(shape, gg.dtype)
                if wrt_input:
                    f = lambda a: dwconv(a, other.astype(gg.dtype))
                else:
                    f = lambda w: dwconv(other.astype(gg.dtype), w)
                _, vjp = jax.vjp(f, zeros)
                return vjp(gg)[0], state
        return "node", Node(_DwBp(), parents)

    if op in ("FusedBatchNormGrad", "FusedBatchNormGradV2",
              "FusedBatchNormGradV3"):
        eps = float(ndef.attr["epsilon"].f or 1e-3)
        is_training = (bool(ndef.attr["is_training"].b)
                       if "is_training" in ndef.attr else True)
        nchw = _data_format(ndef) == "NCHW"
        axes = (0, 2, 3) if nchw else (0, 1, 2)
        cshape = ((1, -1, 1, 1) if nchw else (1, 1, 1, -1))
        getters, parents = _parents(ins[0], ins[1], ins[2], ins[3],
                                    ins[4])

        class _FBNGrad(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                gg, xx, scale = (getters[0](input), getters[1](input),
                                 getters[2](input))
                mean, var = getters[3](input), getters[4](input)

                def f(a, s, o):
                    if is_training:
                        m = a.mean(axes, keepdims=True)
                        v = ((a - m) ** 2).mean(axes, keepdims=True)
                    else:
                        m = mean.reshape(cshape)
                        v = var.reshape(cshape)
                    xhat = (a - m) / jnp.sqrt(v + eps)
                    return xhat * s.reshape(cshape) + o.reshape(cshape)

                _, vjp = jax.vjp(f, xx, scale.astype(xx.dtype),
                                 jnp.zeros_like(scale, xx.dtype))
                dx, ds, do = vjp(gg.astype(xx.dtype))
                return [dx, ds, do], state

        main = Node(_FBNGrad(), parents)
        outs = [("node", Node(nn.SelectTable(i), [main]))
                for i in range(3)]
        # reserve-space outputs (slots 3, 4) exist for op chaining only
        outs += [("const", np.zeros((), np.float32))] * 2
        return "multi", outs

    if op == "LRNGrad":
        r = int(ndef.attr["depth_radius"].i or 5)
        bias = float(ndef.attr["bias"].f or 1.0)
        alpha = float(ndef.attr["alpha"].f or 1.0)
        beta = float(ndef.attr["beta"].f or 0.5)
        getters, parents = _parents(ins[0], ins[1])

        class _LRNGrad(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                from jax import lax
                gg, xx = getters[0](input), getters[1](input)

                def f(a):
                    sq = lax.reduce_window(
                        a * a, 0.0, lax.add, (1, 1, 1, 2 * r + 1),
                        (1, 1, 1, 1),
                        [(0, 0), (0, 0), (0, 0), (r, r)])
                    return a / jnp.power(bias + alpha * sq, beta)

                _, vjp = jax.vjp(f, xx)
                return vjp(gg.astype(xx.dtype))[0], state
        return "node", Node(_LRNGrad(), parents)

    if op == "ResizeBilinearGrad":
        align = bool(ndef.attr["align_corners"].b)
        half_pixel = bool(ndef.attr["half_pixel_centers"].b)
        getters, parents = _parents(ins[0], ins[1])

        class _ResizeGrad(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                gg, orig = getters[0](input), getters[1](input)
                size = (gg.shape[1], gg.shape[2])

                def f(a):
                    if half_pixel:
                        return jax.image.resize(
                            a, (a.shape[0],) + size + (a.shape[-1],),
                            "bilinear")
                    return _tf1_resize_bilinear(a, size,
                                                align_corners=align)
                _, vjp = jax.vjp(f, orig)
                return vjp(gg.astype(orig.dtype))[0], state
        return "node", Node(_ResizeGrad(), parents)

    if op in ("Dilation2D", "Dilation2DBackpropInput",
              "Dilation2DBackpropFilter"):
        st = list(ndef.attr["strides"].list.i)
        rt = list(ndef.attr["rates"].list.i)
        pad = ndef.attr["padding"].s.decode()
        sh, sw = int(st[1]), int(st[2])
        rh, rw = int(rt[1]), int(rt[2])

        def dilation_fwd(a, f):
            """Morphological (grey) dilation: max over the window of
            input + filter (TF Dilation2D semantics)."""
            kh, kw = f.shape[0], f.shape[1]
            ekh, ekw = (kh - 1) * rh + 1, (kw - 1) * rw + 1
            in_h, in_w = a.shape[1], a.shape[2]
            if pad == "SAME":
                out_h, out_w = -(-in_h // sh), -(-in_w // sw)
                ph = max((out_h - 1) * sh + ekh - in_h, 0)
                pw = max((out_w - 1) * sw + ekw - in_w, 0)
                pt, pl = ph // 2, pw // 2
                pads = ((0, 0), (pt, ph - pt), (pl, pw - pl), (0, 0))
            else:
                out_h = (in_h - ekh) // sh + 1
                out_w = (in_w - ekw) // sw + 1
                pads = ((0, 0), (0, 0), (0, 0), (0, 0))
            ap = jnp.pad(a, pads, constant_values=-jnp.inf)
            out = None
            for di in range(kh):
                for dj in range(kw):
                    win = ap[:, di * rh:di * rh + (out_h - 1) * sh + 1:sh,
                             dj * rw:dj * rw + (out_w - 1) * sw + 1:sw, :]
                    cand = win + f[di, dj]
                    out = cand if out is None else jnp.maximum(out, cand)
            return out

        has_g = op != "Dilation2D"
        getters, parents = (_parents(ins[0], ins[1], ins[2]) if has_g
                            else _parents(ins[0], ins[1]))
        wrt = 0 if op.endswith("Input") else 1

        class _Dilation(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                a, f = getters[0](input), getters[1](input)
                f = f.astype(a.dtype)
                if not has_g:
                    return dilation_fwd(a, f), state
                gg = getters[2](input).astype(a.dtype)
                _, vjp = jax.vjp(dilation_fwd, a, f)
                return vjp(gg)[wrt], state
        return "node", Node(_Dilation(), parents)

    return None


def _branch_switches(ctx, seed, stop_ok=True):
    """All ancestor Switch nodes of ``seed`` (the extent of a cond arm)."""
    out, seen, stack = [], set(), [seed]
    while stack:
        n = _clean(stack.pop())
        if n in seen or n not in ctx.nodes:
            continue
        seen.add(n)
        nd = ctx.nodes[n]
        if nd.op == "Switch":
            out.append(nd)
            continue           # the switch's data comes from OUTSIDE the arm
        # skip control deps ("^name"): ordering-only edges that would walk
        # into the predicate's own Switch (cond/switch_t / switch_f)
        stack.extend(i for i in nd.input if not i.startswith("^"))
    return out


def _convert_cond_merge(ctx, merge_ndef):
    """Lower a tf.cond diamond at its Merge into lax.cond.

    Each Merge input is an arm whose ancestor Switches all share one
    predicate; the arm bodies convert as sub-Graphs whose Inputs stand for
    the Switch data values (reference executes only the live arm via the
    Scheduler, nn/tf/ControlOps.scala:65-107; under XLA both arms trace and
    lax.cond executes one on device).
    """
    import jax.numpy as jnp

    from bigdl_tpu.nn.graph import Graph, Input, Node
    from bigdl_tpu.nn.module import Module, child_rng

    ins = [i for i in merge_ndef.input if not i.startswith("^")]
    if len(ins) != 2:
        raise NotImplementedError(
            f"Merge {merge_ndef.name} with {len(ins)} inputs")

    switches = []
    for arm in ins:
        switches.extend(_branch_switches(ctx, arm))
    if not switches:
        raise NotImplementedError(
            f"Merge {merge_ndef.name} is not fed by any Switch")
    pred_name = _clean(switches[0].input[1])
    if any(_clean(s.input[1]) != pred_name for s in switches):
        raise NotImplementedError("Merge arms mix predicates")
    sw_names = sorted({s.name for s in switches})
    data_parents = [_node_of(ctx, ctx.nodes[n].input[0]) for n in sw_names]
    pred_node = _node_of(ctx, ctx.nodes[sw_names[0]].input[1])

    def arm_graph(out_name, slot):
        sub = _GraphCtx(ctx.nodes)
        sub.module_blobs = ctx.module_blobs
        inputs = []
        for n in sw_names:
            node = Input()
            # the arm consumes its polarity slot; seed both slots so
            # Identity hops through either name resolve to the placeholder
            sub.memo[(n, 0)] = ("node", node)
            sub.memo[(n, 1)] = ("node", node)
            inputs.append(node)
        kind, val = _convert(sub, out_name)
        if kind == "const":
            c = val

            class _Const(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return jnp.asarray(c), state
            val = Node(_Const(), [inputs[0]])
        return Graph(inputs, [val], allow_unused=True)

    # TF convention: Merge input order is (false arm, true arm) is NOT
    # guaranteed -- determine each arm's polarity from the Switch slot it
    # consumes (":1" = true).  An arm that is directly a Switch output
    # carries the slot in its name.
    def arm_slot(arm_ref):
        raw = arm_ref.lstrip("^")
        base, _, slot_s = raw.partition(":")
        nd = ctx.nodes[base]
        seen = set()
        while nd.op != "Switch":
            if nd.name in seen or not nd.input:
                return None
            seen.add(nd.name)
            raw = nd.input[0].lstrip("^")
            base, _, slot_s = raw.partition(":")
            nd = ctx.nodes[base]
        return int(slot_s) if slot_s else 0

    slots = [arm_slot(a) for a in ins]
    if slots[0] == 1 or slots[1] == 0:
        true_ref, false_ref = ins[0], ins[1]
    else:
        false_ref, true_ref = ins[0], ins[1]
    true_g = arm_graph(_clean(true_ref), 1)
    false_g = arm_graph(_clean(false_ref), 0)

    class _TfCond(Module):
        def setup(self, rng, input_spec):
            # input = (pred, data...)
            spec = input_spec if isinstance(input_spec, tuple) \
                else (input_spec,)
            data_spec = spec[1:]
            arg = data_spec if len(data_spec) > 1 else data_spec[0]
            tp, ts = true_g.setup(child_rng(rng, 0), arg)
            fp, fs = false_g.setup(child_rng(rng, 1), arg)
            return {"true": tp, "false": fp}, {"true": ts, "false": fs}

        def apply(self, params, state, input, *, training=False, rng=None):
            from jax import lax
            pred = jnp.reshape(input[0], ()).astype(bool)
            data = input[1:]
            arg = data if len(data) > 1 else data[0]

            def t_fn(a):
                out, _ = true_g.apply(params["true"], state["true"], a)
                return out

            def f_fn(a):
                out, _ = false_g.apply(params["false"], state["false"], a)
                return out

            return lax.cond(pred, t_fn, f_fn, arg), state

    return "node", Node(_TfCond(), [pred_node] + data_parents)


def _frame_of(ctx, name):
    """Walk up through Identity-likes to find the Enter that names the
    frame a node belongs to."""
    seen = set()
    stack = [name]
    while stack:
        n = _clean(stack.pop())
        if n in seen or n not in ctx.nodes:
            continue
        seen.add(n)
        nd = ctx.nodes[n]
        if nd.op == "Enter":
            return nd.attr["frame_name"].s.decode()
        stack.extend(nd.input)
    return None


def _convert_while_frame(ctx, exit_ndef):
    """Reconstruct a classic tf.while_loop frame into one lax.while_loop.

    Frame wiring per loop variable i (TF control-flow v1;
    reference executes these with FrameManager, nn/FrameManager.scala:31):

        Enter_i(init_i, frame_name=F)
        Merge_i(Enter_i, NextIteration_i)
        LoopCond(pred(Merge_*))
        Switch_i(Merge_i, LoopCond)   -- :1 stays in loop, :0 exits
        body ops on Switch_i:1 ...    -> NextIteration_i
        Exit_i(Switch_i:0)

    All Exits of the frame share one _TfWhile node; each Exit picks its
    variable from the tuple output.
    """
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn.graph import Graph, Input, Node
    from bigdl_tpu.nn.module import Module, child_rng

    switch_name = _clean(exit_ndef.input[0])
    switch = ctx.nodes[switch_name]
    merge0 = ctx.nodes[_clean(switch.input[0])]
    frame = _frame_of(ctx, merge0.name)
    if not hasattr(ctx, "frames"):
        ctx.frames = {}
    if frame in ctx.frames:
        while_node, var_of_switch = ctx.frames[frame]
        import bigdl_tpu.nn as _nn
        return "node", Node(_nn.SelectTable(var_of_switch[switch_name]),
                            [while_node])

    loopcond_name = _clean(switch.input[1])
    loopcond = ctx.nodes[loopcond_name]

    # collect the frame's loop variables: Switch nodes driven by this
    # LoopCond, each fed by a Merge(Enter, NextIteration)
    switches = [n for n in ctx.nodes.values()
                if n.op == "Switch" and _clean(n.input[1]) == loopcond_name]
    switches.sort(key=lambda n: n.name)
    merges = [ctx.nodes[_clean(s.input[0])] for s in switches]
    enters, next_iters = [], []
    for m in merges:
        e = ctx.nodes[_clean(m.input[0])]
        ni = ctx.nodes[_clean(m.input[1])]
        if e.op != "Enter" or ni.op != "NextIteration":
            raise NotImplementedError(
                f"unsupported while-frame wiring at Merge {m.name}")
        enters.append(e)
        next_iters.append(ni)

    # loop-invariant Enters: constants fold in place; graph-node values
    # become CAPTURES -- extra sub-graph inputs fed from the outer graph
    invariant = {}
    for n in ctx.nodes.values():
        if n.op == "Enter" and n.attr["frame_name"].s.decode() == frame \
                and n.name not in {e.name for e in enters}:
            invariant[n.name] = _convert(ctx, n.input[0])
    cap_names = sorted(name for name, (k, _) in invariant.items()
                       if k == "node")
    cap_parents = [invariant[n][1] for n in cap_names]

    def subgraph(seed_names, out_names):
        """Convert a frame subgraph: loop-var then capture placeholders."""
        sub = _GraphCtx(ctx.nodes)
        sub.module_blobs = ctx.module_blobs      # share weight installs
        inputs = []
        for name in list(seed_names) + cap_names:
            node = Input()
            sub.memo[(name, 0)] = ("node", node)
            sub.memo[(name, 1)] = ("node", node)
            inputs.append(node)
        for name, kv in invariant.items():
            if kv[0] == "const":
                sub.memo[(name, 0)] = kv
        outs = []
        for name in out_names:
            kind, val = _convert(sub, name)
            if kind == "const":
                class _Const(Module):
                    def __init__(self, c):
                        super().__init__()
                        self.c = c

                    def apply(self, params, state, input, *,
                              training=False, rng=None):
                        return jnp.asarray(self.c), state
                val = Node(_Const(val), [inputs[0]])
            outs.append(val)
        # a loop var may be unused by the condition (or even the body)
        return Graph(inputs, outs, allow_unused=True), inputs

    merge_names = [m.name for m in merges]
    switch_names = [s.name for s in switches]
    cond_graph, _ = subgraph(merge_names, [_clean(loopcond.input[0])])
    body_graph, _ = subgraph(switch_names,
                             [_clean(ni.input[0]) for ni in next_iters])

    init_vals = [_convert(ctx, e.input[0]) for e in enters]

    n_dyn = sum(1 for k, _ in init_vals if k == "node")
    n_caps = len(cap_parents)

    class _TfWhile(Module):
        def setup(self, rng, input_spec):
            spec = input_spec if isinstance(input_spec, tuple) \
                else (input_spec,)
            cap_spec = tuple(spec[n_dyn:])
            full = []
            i = 0
            for kind, val in init_vals:
                if kind == "node":
                    full.append(spec[i])
                    i += 1
                else:
                    full.append(jax.ShapeDtypeStruct(
                        np.shape(val), np.asarray(val).dtype))
            full = tuple(full) + cap_spec
            cp, cs = cond_graph.setup(child_rng(rng, 0),
                                      full if len(full) > 1 else full[0])
            bp, bs = body_graph.setup(child_rng(rng, 1),
                                      full if len(full) > 1 else full[0])
            return {"cond": cp, "body": bp}, {"cond": cs, "body": bs}

        def apply(self, params, state, input, *, training=False, rng=None):
            dyn = list(input) if isinstance(input, tuple) else [input]
            caps = tuple(dyn[n_dyn:])
            vals, di = [], 0
            for kind, val in init_vals:
                if kind == "node":
                    vals.append(jnp.asarray(dyn[di]))
                    di += 1
                else:
                    vals.append(jnp.asarray(val))
            vals = tuple(vals)

            def args(vs):
                full = tuple(vs) + caps
                return full if len(full) > 1 else full[0]

            def cond_fn(vs):
                out, _ = cond_graph.apply(params["cond"], state["cond"],
                                          args(vs))
                return jnp.reshape(out, ()).astype(bool)

            def body_fn(vs):
                out, _ = body_graph.apply(params["body"], state["body"],
                                          args(vs))
                out = out if isinstance(out, tuple) else (out,)
                return tuple(jnp.asarray(o).astype(v.dtype)
                             for o, v in zip(out, vs))

            from jax import lax
            return lax.while_loop(cond_fn, body_fn, vals), state

    import jax

    parents = [v for k, v in init_vals if k == "node"] + cap_parents
    if not parents:
        raise NotImplementedError(
            "while frame with no graph-node initial values")
    while_node = Node(_TfWhile(), parents)
    var_of_switch = {s.name: i for i, s in enumerate(switches)}
    ctx.frames[frame] = (while_node, var_of_switch)
    import bigdl_tpu.nn as _nn
    return "node", Node(_nn.SelectTable(var_of_switch[switch_name]),
                        [while_node])


class UnsupportedTFOpsError(NotImplementedError):
    """Every conversion gap in the requested subgraph, reported at once
    (reference fails on the first missing loader, TensorflowLoader.scala;
    VERDICT r4 ask #7 wants the whole capability picture up front)."""

    def __init__(self, gaps):
        #: dict op -> (node_count, example message)
        self.gaps = gaps
        lines = [f"  {op} (x{n}): {msg}"
                 for op, (n, msg) in sorted(gaps.items())]
        super().__init__(
            f"unsupported TF ops in the requested subgraph "
            f"({len(gaps)} distinct):\n" + "\n".join(lines))


def _reachable_topo(nodes, inputs, outputs):
    """Reachable node defs between ``outputs`` and the graph's sources, in
    topological (ancestors-first) order."""
    # stop at declared inputs whether named bare or with an output slot
    # ("reader:1"): traversal below works on base names
    input_keys = {_input_key(n).partition(":")[0] for n in inputs}
    order, state = [], {}          # name -> 1 (on stack) / 2 (done)
    stack = [(_clean(o).partition(":")[0], False) for o in outputs]
    while stack:
        name, processed = stack.pop()
        if processed:
            state[name] = 2
            if name in nodes:
                order.append(nodes[name])
            continue
        if state.get(name):
            continue
        state[name] = 1
        stack.append((name, True))
        if name in input_keys or name not in nodes:
            continue
        for i in nodes[name].input:
            dep = i.lstrip("^").partition(":")[0]
            if not state.get(dep):
                stack.append((dep, False))
    return order


def capability_report(path, inputs, outputs, binary=None, trainable=False):
    """Pre-import capability scan: walk the GraphDef between ``inputs`` and
    ``outputs`` and classify EVERY reachable op before anything is built.

    -> {"supported": sorted list of op names that converted,
        "unsupported": {op: (node_count, example message)},
        "n_nodes": reachable node count}

    Nodes downstream of an unsupported op are skipped (not misattributed):
    conversion is attempted ancestors-first and failures poison their
    consumers.  ``load_tf`` uses the same scan to aggregate its error.
    """
    gdef = path if hasattr(path, "node") else read_graph(path, binary)
    nodes = {n.name: n for n in gdef.node}
    from bigdl_tpu.nn.graph import Input

    ctx = _GraphCtx(nodes)
    ctx.trainable = trainable
    for name in inputs:
        ctx.input_nodes[_input_key(name)] = Input()

    topo = _reachable_topo(nodes, inputs, outputs)
    supported, gaps, poisoned = set(), {}, set()
    for ndef in topo:
        if any(i.lstrip("^").partition(":")[0] in poisoned
               for i in ndef.input):
            poisoned.add(ndef.name)
            continue
        try:
            _convert(ctx, ndef.name)
            supported.add(ndef.op)
        except NotImplementedError as e:
            n, msg = gaps.get(ndef.op, (0, str(e)))
            gaps[ndef.op] = (n + 1, msg)
            poisoned.add(ndef.name)
        except Exception:
            # context-dependent failure (e.g. shape math on a const that
            # the fake inputs cannot satisfy): not a capability gap
            poisoned.add(ndef.name)
    return {"supported": sorted(supported), "unsupported": gaps,
            "n_nodes": len(topo)}


def load_tf(path, inputs, outputs, binary=None, input_specs=None,
            trainable=False):
    """TensorflowLoader.load equivalent: extract the inference subgraph
    between ``inputs`` (placeholder names) and ``outputs`` (node names) and
    build a bigdl_tpu Graph.  Reference: TensorflowLoader.scala:43,358.

    ``input_specs``: dict name -> (shape NHWC, dtype) to build immediately.
    ``trainable``: variables become trainable parameters initialised from
    their in-graph Assign values (the Session training mode,
    utils/tf/Session.scala:105) instead of folding to constants.
    """
    import jax
    from bigdl_tpu.nn.graph import Graph, Input

    gdef = read_graph(path, binary)
    nodes = {n.name: n for n in gdef.node}
    ctx = _GraphCtx(nodes)
    ctx.trainable = trainable
    for name in inputs:
        ctx.input_nodes[_input_key(name)] = Input()

    out_nodes = []
    try:
        for name in outputs:
            kind, val = _convert(ctx, name)
            if kind != "node":
                raise ValueError(f"output {name} folded to a constant")
            out_nodes.append(val)
    except NotImplementedError as e:
        if isinstance(e, UnsupportedTFOpsError):
            raise
        # report EVERY gap in the subgraph, not just the first hit
        report = capability_report(gdef, inputs, outputs,
                                   trainable=trainable)
        if report["unsupported"]:
            raise UnsupportedTFOpsError(report["unsupported"]) from e
        raise

    in_nodes = [ctx.input_nodes[_input_key(n)] for n in inputs]
    graph = Graph(in_nodes, out_nodes)

    if input_specs:
        import jax.numpy as jnp

        def to_spec(v):
            # accept a bare shape (dtype defaults to float32), a
            # (shape, dtype) pair, or a ready ShapeDtypeStruct/array
            if hasattr(v, "shape") and hasattr(v, "dtype"):
                return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
            if (len(v) == 2 and isinstance(v[0], (tuple, list))):
                return jax.ShapeDtypeStruct(tuple(v[0]), v[1])
            return jax.ShapeDtypeStruct(tuple(v), jnp.float32)

        specs = [to_spec(input_specs[n]) for n in inputs]
        graph.build(specs[0] if len(specs) == 1 else tuple(specs))
        _install(graph, ctx.module_blobs)
    else:
        orig_build = graph.build

        def build_and_install(spec, rng=None):
            out = orig_build(spec, rng=rng)
            _install(graph, ctx.module_blobs)
            return out
        graph.build = build_and_install
    return graph


def _install(graph, module_blobs):
    idx = {id(n.module): str(i) for i, n in enumerate(graph._topo)
           if n.module is not None}
    for mod, fn in module_blobs:
        if fn is None:
            continue
        key = idx.get(id(mod))
        if key is None:
            continue   # converted but unreachable from the outputs (e.g.
                       # only a sibling output slot of its op is consumed)
        if isinstance(fn, tuple) and fn[0] == "state":
            fn[1](graph._state[key])
        else:
            fn(graph._params[key])


# --------------------------------------------------------------------------- #
# export (TensorflowSaver analogue)
# --------------------------------------------------------------------------- #


def save_tf(model, path, input_shape, input_name="input",
            output_name="output"):
    """Export a built model to a frozen GraphDef (reference:
    utils/tf/TensorflowSaver.scala, which walks arbitrary graphs).
    Supports ``Sequential`` chains, ``Concat`` towers (-> ConcatV2) and
    ``Graph`` DAGs (JoinTable -> ConcatV2, CAddTable -> AddN,
    CMulTable/CMaxTable -> Mul/Maximum chains, BatchNormalization ->
    FusedBatchNorm with frozen statistics).
    """
    import bigdl_tpu.nn as nn

    g = tfpb.GraphDef()
    g.versions.producer = 21

    def add_const(name, arr, dtype=None):
        n = g.node.add()
        n.name = name
        n.op = "Const"
        tf_dtype = tfpb.DT_INT32 if dtype == np.int32 else tfpb.DT_FLOAT
        np_dtype = np.int32 if dtype == np.int32 else np.float32
        n.attr["dtype"].type = tf_dtype
        t = n.attr["value"].tensor
        t.dtype = tf_dtype
        for d in arr.shape:
            t.tensor_shape.dim.add().size = d
        t.tensor_content = np.ascontiguousarray(arr, np_dtype).tobytes()
        return name

    ph = g.node.add()
    ph.name = input_name
    ph.op = "Placeholder"
    ph.attr["dtype"].type = tfpb.DT_FLOAT
    for d in input_shape:
        ph.attr["shape"].shape.dim.add().size = d if d else -1

    cur = input_name
    counter = [0]

    def fresh(prefix):
        counter[0] += 1
        return f"{prefix}_{counter[0]}"

    def emit(mod, params, cur, state=None):
        state = state if isinstance(state, dict) else {}
        if isinstance(mod, nn.Sequential):
            for i, ch in enumerate(mod.modules):
                cur = emit(ch, params.get(str(i), {}), cur,
                           state.get(str(i), {}))
            return cur
        if isinstance(mod, nn.Identity):
            return cur
        if isinstance(mod, nn.Concat):
            tower_tops = [emit(t, params.get(str(i), {}), cur,
                               state.get(str(i), {}))
                          for i, t in enumerate(mod.modules)]
            return emit_concat(tower_tops, mod.dimension)
        if isinstance(mod, nn.SpatialBatchNormalization):
            scale = np.asarray(params.get(
                "weight", np.ones(mod.n_output, np.float32)))
            offset = np.asarray(params.get(
                "bias", np.zeros(mod.n_output, np.float32)))
            mean = np.asarray(state.get(
                "running_mean", np.zeros(mod.n_output, np.float32)))
            var = np.asarray(state.get(
                "running_var", np.ones(mod.n_output, np.float32)))
            n = g.node.add()
            n.name = fresh("fusedbatchnorm")
            n.op = "FusedBatchNorm"
            n.input.extend([cur, add_const(fresh("scale"), scale),
                            add_const(fresh("offset"), offset),
                            add_const(fresh("mean"), mean),
                            add_const(fresh("variance"), var)])
            n.attr["T"].type = tfpb.DT_FLOAT
            n.attr["epsilon"].f = mod.eps
            n.attr["is_training"].b = False
            n.attr["data_format"].s = b"NHWC"
            return n.name
        if isinstance(mod, nn.SpatialCrossMapLRN):
            # ours (caffe form): (k + alpha/size * sum)^beta over `size`
            # channels; TF: (bias + tf_alpha * sum)^beta over 2r+1 --
            # only ODD windows are TF-representable
            if mod.size % 2 == 0:
                raise NotImplementedError(
                    f"tf export: LRN window {mod.size} is even; TF LRN "
                    f"windows are 2*depth_radius+1 (odd only)")
            if getattr(mod, "data_format", "NHWC") != "NHWC":
                raise NotImplementedError("tf export: NCHW LRN")
            n = g.node.add()
            n.name = fresh("lrn")
            n.op = "LRN"
            n.input.append(cur)
            n.attr["T"].type = tfpb.DT_FLOAT
            n.attr["depth_radius"].i = (mod.size - 1) // 2
            n.attr["bias"].f = mod.k
            n.attr["alpha"].f = mod.alpha / mod.size
            n.attr["beta"].f = mod.beta
            return n.name
        if isinstance(mod, (nn.GlobalAveragePooling2D,
                            nn.GlobalMaxPooling2D)):
            if getattr(mod, "data_format", "NHWC") != "NHWC":
                raise NotImplementedError("tf export: NCHW global pooling")
            axes = add_const(fresh("axes"), np.asarray([1, 2], np.int32),
                             dtype=np.int32)
            n = g.node.add()
            n.name = fresh("globalpool")
            n.op = ("Mean" if isinstance(mod, nn.GlobalAveragePooling2D)
                    else "Max")
            n.input.extend([cur, axes])
            n.attr["T"].type = tfpb.DT_FLOAT
            n.attr["Tidx"].type = tfpb.DT_INT32
            n.attr["keep_dims"].b = False
            return n.name
        if isinstance(mod, nn.SpatialConvolution):
            if mod.pad != (0, 0):
                # encode as explicit Pad + VALID conv (TF SAME cannot
                # represent arbitrary symmetric pads)
                pname = fresh("pad")
                pc = add_const(pname + "/paddings", np.asarray(
                    [[0, 0], [mod.pad[0], mod.pad[0]],
                     [mod.pad[1], mod.pad[1]], [0, 0]], np.int32),
                    dtype=np.int32)
                n = g.node.add()
                n.name = pname
                n.op = "Pad"
                n.input.extend([cur, pc])
                n.attr["T"].type = tfpb.DT_FLOAT
                n.attr["Tpaddings"].type = tfpb.DT_INT32
                cur = pname
            kname = add_const(fresh("kernel"), np.asarray(params["weight"]))
            n = g.node.add()
            n.name = fresh("conv2d")
            n.op = "Conv2D"
            n.input.extend([cur, kname])
            n.attr["T"].type = tfpb.DT_FLOAT
            n.attr["strides"].list.i.extend(
                [1, mod.stride[0], mod.stride[1], 1])
            n.attr["dilations"].list.i.extend([1, 1, 1, 1])
            n.attr["padding"].s = b"VALID"
            n.attr["data_format"].s = b"NHWC"
            cur = n.name
            if mod.with_bias:
                bname = add_const(fresh("bias"), np.asarray(params["bias"]))
                nb = g.node.add()
                nb.name = fresh("biasadd")
                nb.op = "BiasAdd"
                nb.input.extend([cur, bname])
                nb.attr["T"].type = tfpb.DT_FLOAT
                nb.attr["data_format"].s = b"NHWC"
                cur = nb.name
            return cur
        if isinstance(mod, nn.Linear):
            wname = add_const(fresh("weight"),
                              np.asarray(params["weight"]).T)
            n = g.node.add()
            n.name = fresh("matmul")
            n.op = "MatMul"
            n.input.extend([cur, wname])
            n.attr["T"].type = tfpb.DT_FLOAT
            n.attr["transpose_a"].b = False
            n.attr["transpose_b"].b = False
            cur = n.name
            if mod.with_bias:
                bname = add_const(fresh("bias"), np.asarray(params["bias"]))
                nb = g.node.add()
                nb.name = fresh("biasadd")
                nb.op = "BiasAdd"
                nb.input.extend([cur, bname])
                nb.attr["T"].type = tfpb.DT_FLOAT
                cur = nb.name
            return cur
        simple = {nn.ReLU: "Relu", nn.Tanh: "Tanh", nn.Sigmoid: "Sigmoid",
                  nn.SoftMax: "Softmax", nn.LogSoftMax: "LogSoftmax",
                  nn.ReLU6: "Relu6"}
        for cls, opname in simple.items():
            if type(mod) is cls:
                n = g.node.add()
                n.name = fresh(opname.lower())
                n.op = opname
                n.input.append(cur)
                n.attr["T"].type = tfpb.DT_FLOAT
                return n.name
        if isinstance(mod, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
            n = g.node.add()
            n.name = fresh("pool")
            n.op = ("MaxPool" if isinstance(mod, nn.SpatialMaxPooling)
                    else "AvgPool")
            n.input.append(cur)
            n.attr["T"].type = tfpb.DT_FLOAT
            n.attr["ksize"].list.i.extend([1, mod.kernel[0],
                                           mod.kernel[1], 1])
            n.attr["strides"].list.i.extend([1, mod.stride[0],
                                             mod.stride[1], 1])
            if mod.pad == (0, 0):
                n.attr["padding"].s = b"VALID"
            else:
                same_ph = (mod.kernel[0] - mod.stride[0] + 1) // 2 \
                    if mod.kernel[0] > mod.stride[0] else 0
                same_pw = (mod.kernel[1] - mod.stride[1] + 1) // 2 \
                    if mod.kernel[1] > mod.stride[1] else 0
                if mod.pad != (same_ph, same_pw):
                    raise NotImplementedError(
                        f"tf export: pooling pad {mod.pad} is not "
                        f"SAME-representable (expected {(same_ph, same_pw)})")
                n.attr["padding"].s = b"SAME"
            n.attr["data_format"].s = b"NHWC"
            return n.name
        if isinstance(mod, nn.Reshape):
            cname = fresh("shape")
            cn = g.node.add()
            cn.name = cname
            cn.op = "Const"
            cn.attr["dtype"].type = tfpb.DT_INT32
            t = cn.attr["value"].tensor
            t.dtype = tfpb.DT_INT32
            shape = [-1] + [int(v) for v in mod.size]
            t.tensor_shape.dim.add().size = len(shape)
            t.tensor_content = np.asarray(shape, np.int32).tobytes()
            rn = g.node.add()
            rn.name = fresh("reshape")
            rn.op = "Reshape"
            rn.input.extend([cur, cname])
            rn.attr["T"].type = tfpb.DT_FLOAT
            rn.attr["Tshape"].type = tfpb.DT_INT32
            return rn.name
        if isinstance(mod, nn.Dropout):
            return cur                     # inference graph: identity
        raise NotImplementedError(
            f"tf export: unsupported layer {type(mod).__name__}")

    def emit_concat(bottoms, dimension):
        axis = add_const(fresh("axis"),
                         np.asarray(dimension, np.int32).reshape(()),
                         dtype=np.int32)
        n = g.node.add()
        n.name = fresh("concat")
        n.op = "ConcatV2"
        n.input.extend(list(bottoms) + [axis])
        n.attr["T"].type = tfpb.DT_FLOAT
        n.attr["Tidx"].type = tfpb.DT_INT32
        n.attr["N"].i = len(bottoms)
        return n.name

    def emit_nary(op, bottoms):
        if op == "AddN":
            n = g.node.add()
            n.name = fresh("addn")
            n.op = "AddN"
            n.input.extend(bottoms)
            n.attr["T"].type = tfpb.DT_FLOAT
            n.attr["N"].i = len(bottoms)
            return n.name
        cur = bottoms[0]
        for other in bottoms[1:]:          # Mul/Maximum are binary in TF
            n = g.node.add()
            n.name = fresh(op.lower())
            n.op = op
            n.input.extend([cur, other])
            n.attr["T"].type = tfpb.DT_FLOAT
            cur = n.name
        return cur

    def walk_graph(graph_mod, params, state, cur):
        if len(graph_mod.input_nodes) > 1:
            raise NotImplementedError("tf export: multi-input graphs")
        state = state if isinstance(state, dict) else {}
        tops = {id(n): cur for n in graph_mod.input_nodes}
        for i, node in enumerate(graph_mod._topo):
            if node.module is None:
                continue
            bottoms = [tops[id(p)] for p in node.inputs]
            m = node.module
            sub = (params or {}).get(str(i), {})
            substate = state.get(str(i), {})
            if isinstance(m, nn.JoinTable):
                tops[id(node)] = emit_concat(bottoms, m.dimension)
            elif isinstance(m, nn.CAddTable):
                tops[id(node)] = emit_nary("AddN", bottoms)
            elif isinstance(m, nn.CMulTable):
                tops[id(node)] = emit_nary("Mul", bottoms)
            elif isinstance(m, nn.CMaxTable):
                tops[id(node)] = emit_nary("Maximum", bottoms)
            elif isinstance(m, nn.Graph):
                inner = walk_graph(m, sub, substate, bottoms[0])
                if len(inner) > 1:
                    raise NotImplementedError(
                        "tf export: multi-output nested graph node")
                tops[id(node)] = inner[0]
            else:
                if len(bottoms) > 1:
                    raise NotImplementedError(
                        f"tf export: multi-input {type(m).__name__} node")
                tops[id(node)] = emit(m, sub, bottoms[0], substate)
        return [tops[id(n)] for n in graph_mod.output_nodes]

    if isinstance(model, nn.Graph):
        curs = walk_graph(model, model._params or {}, model._state or {},
                          cur)
    else:
        curs = [emit(model, model._params or {}, cur, model._state or {})]

    # one named Identity per model output: "output" for single-output
    # models, "output", "output_1", ... for multi-output graphs
    existing = {n.name for n in g.node}
    for i, cur in enumerate(curs):
        name = output_name if i == 0 else f"{output_name}_{i}"
        if name in existing:
            raise ValueError(
                f"tf export: output name {name!r} collides with an "
                f"internal node; pass a different output_name")
        out = g.node.add()
        out.name = name
        out.op = "Identity"
        out.input.append(cur)
        out.attr["T"].type = tfpb.DT_FLOAT

    with open(path, "wb") as f:
        f.write(g.SerializeToString())
    return path
