"""TensorFlow GraphDef import/export.

Reference: utils/tf/TensorflowLoader.scala:43 (load(pb, inputs, outputs):
parse GraphDef, pattern-match subgraphs via the per-op loaders in
utils/tf/loaders/, buildBigDLModel at :358) and utils/tf/TensorflowSaver.scala
(export).

TPU-native notes: TF is natively NHWC with HWIO conv kernels — identical to
our layouts, so conv weights install verbatim; only MatMul weights transpose
((in, out) -> our (out, in)).  Pattern folding: BiasAdd over Conv2D/MatMul
becomes the module bias (the reference does the same via subgraph patterns,
e.g. loaders/Conv2D.scala).
"""

import numpy as np

from bigdl_tpu.interop import tensorflow_pb2 as tfpb
from google.protobuf import text_format

_DT_NP = {
    tfpb.DT_FLOAT: np.float32, tfpb.DT_DOUBLE: np.float64,
    tfpb.DT_INT32: np.int32, tfpb.DT_INT64: np.int64,
    tfpb.DT_BOOL: np.bool_, tfpb.DT_INT8: np.int8,
    tfpb.DT_UINT8: np.uint8, tfpb.DT_INT16: np.int16,
}


def read_graph(path, binary=None):
    """Parse a GraphDef from .pb (binary) or .pbtxt (text)."""
    g = tfpb.GraphDef()
    if binary is None:
        binary = not (path.endswith(".pbtxt") or path.endswith(".pbtxt.txt"))
    if binary:
        with open(path, "rb") as f:
            g.ParseFromString(f.read())
    else:
        with open(path) as f:
            text_format.Parse(f.read(), g, allow_unknown_field=True)
    return g


def _tensor_to_np(t):
    dtype = _DT_NP.get(t.dtype, np.float32)
    shape = tuple(int(d.size) for d in t.tensor_shape.dim)
    n = int(np.prod(shape)) if shape else 1
    if t.tensor_content:
        arr = np.frombuffer(t.tensor_content, dtype=dtype)
    elif t.float_val:
        arr = np.asarray(t.float_val, dtype)
    elif t.double_val:
        arr = np.asarray(t.double_val, dtype)
    elif t.int_val:
        arr = np.asarray(t.int_val, dtype)
    elif t.int64_val:
        arr = np.asarray(t.int64_val, dtype)
    elif t.bool_val:
        arr = np.asarray(t.bool_val, dtype)
    else:
        arr = np.zeros(n, dtype)
    if arr.size == 1 and n > 1:
        arr = np.full(n, arr.ravel()[0], dtype)   # splat encoding
    return arr.reshape(shape)


def _clean(name):
    name = name.lstrip("^")
    return name.split(":")[0]


class _GraphCtx:
    def __init__(self, nodes):
        self.nodes = nodes          # name -> NodeDef
        self.memo = {}              # name -> ("const", np) | ("node", Node)
        self.module_blobs = []      # (module, install_fn) pairs
        self.input_nodes = {}       # placeholder name -> Input node
        self.consumers = {}         # name -> number of consuming nodes
        for n in nodes.values():
            for i in n.input:
                key = _clean(i)
                self.consumers[key] = self.consumers.get(key, 0) + 1


def _const_of(ctx, name):
    kind, val = _convert(ctx, name)
    if kind != "const":
        raise NotImplementedError(
            f"expected constant input {name}, got graph node")
    return val


def _node_of(ctx, name):
    kind, val = _convert(ctx, name)
    if kind != "node":
        raise NotImplementedError(
            f"{name} resolves to a constant where an activation is expected")
    return val


def _tf_conv_module(k_shape, strides, dilations, with_same_pad):
    """TF-exact conv: lax's string padding reproduces TF SAME including
    its input-size-dependent asymmetric pads (no symmetric approximation)."""
    from bigdl_tpu.nn.module import Module
    import jax.numpy as jnp
    from jax import lax

    kh, kw, cin, cout = k_shape
    sh, sw = strides
    dh, dw = dilations

    class TfConv2D(Module):
        n_input_plane, n_output_plane = cin, cout

        def setup(self, rng, input_spec):
            return {"weight": jnp.zeros((kh, kw, cin, cout), jnp.float32),
                    "bias": jnp.zeros((cout,), jnp.float32)}, ()

        def apply(self, params, state, input, *, training=False, rng=None):
            y = lax.conv_general_dilated(
                input, params["weight"].astype(input.dtype),
                window_strides=(sh, sw),
                padding="SAME" if with_same_pad else "VALID",
                rhs_dilation=(dh, dw),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            return y + params["bias"].astype(y.dtype), state

    return TfConv2D()


def _pool_module(ndef, kind):
    """TF-exact pooling: reduce_window with lax string padding (SAME
    matches TF's asymmetric pads; avg excludes padded cells like TF)."""
    from bigdl_tpu.nn.module import Module
    import jax.numpy as jnp
    from jax import lax

    ks = list(ndef.attr["ksize"].list.i)
    st = list(ndef.attr["strides"].list.i)
    kh, kw = int(ks[1]), int(ks[2])
    sh, sw = int(st[1]), int(st[2])
    pad = ndef.attr["padding"].s.decode()

    class TfPool(Module):
        def apply(self, params, state, input, *, training=False, rng=None):
            dims, strides = (1, kh, kw, 1), (1, sh, sw, 1)
            if kind == "max":
                return lax.reduce_window(
                    input, -jnp.inf, lax.max, dims, strides, pad), state
            ones = jnp.ones_like(input)
            total = lax.reduce_window(input, 0.0, lax.add, dims, strides,
                                      pad)
            count = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                      pad)
            return total / count, state

    return TfPool()


def _convert(ctx, name):
    name = _clean(name)
    if name in ctx.memo:
        return ctx.memo[name]
    if name not in ctx.nodes:
        raise KeyError(f"node {name} not in graph")
    ndef = ctx.nodes[name]
    result = _convert_node(ctx, ndef)
    ctx.memo[name] = result
    return result


def _convert_node(ctx, ndef):
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn import ops as nnops
    from bigdl_tpu.nn.graph import Node
    from bigdl_tpu.nn.module import Module

    op = ndef.op
    ins = [i for i in ndef.input if not i.startswith("^")]

    if op == "Const":
        return "const", _tensor_to_np(ndef.attr["value"].tensor)
    if op in ("Identity", "StopGradient", "CheckNumerics", "PreventGradient"):
        return _convert(ctx, ins[0])
    if op in ("Placeholder", "PlaceholderV2"):
        from bigdl_tpu.nn.graph import Input
        node = ctx.input_nodes.get(ndef.name)
        if node is None:
            node = Input()
            ctx.input_nodes[ndef.name] = node
        return "node", node

    if op == "MatMul":
        x = _node_of(ctx, ins[0])
        w = _const_of(ctx, ins[1])        # (in, out)
        if ndef.attr["transpose_a"].b:
            raise NotImplementedError("MatMul transpose_a")
        if ndef.attr["transpose_b"].b:
            w = w.T
        mod = nn.Linear(w.shape[0], w.shape[1], with_bias=True)
        node = Node(mod, [x])

        def install(params, w=w):
            params["weight"] = jnp.asarray(w.T)     # ours is (out, in)
            params["bias"] = jnp.zeros((w.shape[1],), jnp.float32)
        ctx.module_blobs.append((mod, install))
        return "node", node

    if op == "Conv2D":
        if ndef.attr["data_format"].s.decode() not in ("", "NHWC"):
            raise NotImplementedError("Conv2D data_format NCHW")
        x = _node_of(ctx, ins[0])
        k = _const_of(ctx, ins[1])        # HWIO
        st = list(ndef.attr["strides"].list.i)
        dil = list(ndef.attr["dilations"].list.i) or [1, 1, 1, 1]
        pad = ndef.attr["padding"].s.decode()
        mod = _tf_conv_module(k.shape, (int(st[1]), int(st[2])),
                              (int(dil[1]), int(dil[2])), pad == "SAME")
        node = Node(mod, [x])

        def install(params, k=k):
            params["weight"] = jnp.asarray(k)       # HWIO verbatim
        ctx.module_blobs.append((mod, install))
        return "node", node

    if op == "BiasAdd" or (op in ("Add", "AddV2") and len(ins) == 2):
        a_kind, a_val = _convert(ctx, ins[0])
        b_kind, b_val = _convert(ctx, ins[1])
        if a_kind == "node" and b_kind == "const":
            # fold into the producing conv/linear bias when 1-D and the
            # producer's raw output feeds ONLY this BiasAdd
            prod = a_val
            sole = ctx.consumers.get(_clean(ins[0]), 0) <= 1
            if (b_val.ndim == 1 and sole and prod.module is not None
                    and (isinstance(prod.module, nn.Linear)
                         or type(prod.module).__name__ == "TfConv2D")
                    and not getattr(prod.module, "_tf_bias_set", False)):
                mod = prod.module
                mod._tf_bias_set = True

                def install(params, b=b_val):
                    params["bias"] = jnp.asarray(b)
                ctx.module_blobs.append((mod, install))
                return "node", prod
            class _AddConst(Module):
                def __init__(self, c):
                    super().__init__()
                    self.c = c

                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return input + jnp.asarray(self.c), state

            node = Node(_AddConst(b_val), [prod])
            ctx.module_blobs.append((node.module, None))
            return "node", node
        if a_kind == "node" and b_kind == "node":
            node = Node(nn.CAddTable(), [a_val, b_val])
            return "node", node
        if a_kind == "const" and b_kind == "node":
            class _AddConstL(Module):
                def __init__(self, c):
                    super().__init__()
                    self.c = c

                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return input + jnp.asarray(self.c), state
            return "node", Node(_AddConstL(a_val), [b_val])
        return "const", a_val + b_val

    if op in ("Sub", "Mul", "RealDiv", "Maximum", "Minimum"):
        a_kind, a_val = _convert(ctx, ins[0])
        b_kind, b_val = _convert(ctx, ins[1])
        table = {"Sub": nn.CSubTable, "Mul": nn.CMulTable,
                 "RealDiv": nn.CDivTable, "Maximum": nn.CMaxTable,
                 "Minimum": nn.CMinTable}
        npop = {"Sub": np.subtract, "Mul": np.multiply,
                "RealDiv": np.divide, "Maximum": np.maximum,
                "Minimum": np.minimum}
        if a_kind == "const" and b_kind == "const":
            return "const", npop[op](a_val, b_val)
        if a_kind == "node" and b_kind == "node":
            return "node", Node(table[op](), [a_val, b_val])
        const = b_val if b_kind == "const" else a_val
        x = a_val if a_kind == "node" else b_val
        if op == "Mul":
            return "node", Node(nn.MulConstant(float(const)
                                               if const.ndim == 0
                                               else const), [x])

        class _Affine(Module):
            def __init__(self, c, op_name, const_first):
                super().__init__()
                self.c, self.op_name, self.const_first = c, op_name, \
                    const_first

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                c = jnp.asarray(self.c)
                f = {"Sub": jnp.subtract, "RealDiv": jnp.divide,
                     "Maximum": jnp.maximum, "Minimum": jnp.minimum}[
                         self.op_name]
                return (f(c, input) if self.const_first
                        else f(input, c)), state

        return "node", Node(_Affine(const, op, a_kind == "const"), [x])

    if op in ("Relu", "Relu6", "Tanh", "Sigmoid", "Softmax", "Elu",
              "Softplus", "Softsign", "LogSoftmax", "Rsqrt", "Sqrt", "Exp",
              "Log", "Abs", "Neg", "Square", "Floor"):
        x = _node_of(ctx, ins[0])
        m = {"Relu": nn.ReLU, "Relu6": nn.ReLU6, "Tanh": nn.Tanh,
             "Sigmoid": nn.Sigmoid, "Softmax": nn.SoftMax, "Elu": nn.ELU,
             "Softplus": nn.SoftPlus, "Softsign": nn.SoftSign,
             "LogSoftmax": nn.LogSoftMax, "Sqrt": nn.Sqrt, "Exp": nn.Exp,
             "Abs": nn.Abs, "Negative": nn.Negative, "Neg": nn.Negative,
             "Square": nn.Square, "Floor": nnops.Floor, "Log": nn.Log}
        if op == "Rsqrt":
            class _Rsqrt(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return 1.0 / jnp.sqrt(input), state
            return "node", Node(_Rsqrt(), [x])
        return "node", Node(m[op](), [x])

    if op == "MaxPool":
        if ndef.attr["data_format"].s.decode() not in ("", "NHWC"):
            raise NotImplementedError("MaxPool data_format NCHW")
        return "node", Node(_pool_module(ndef, "max"),
                            [_node_of(ctx, ins[0])])
    if op == "AvgPool":
        if ndef.attr["data_format"].s.decode() not in ("", "NHWC"):
            raise NotImplementedError("AvgPool data_format NCHW")
        return "node", Node(_pool_module(ndef, "avg"),
                            [_node_of(ctx, ins[0])])

    if op == "Reshape":
        x = _node_of(ctx, ins[0])
        shape = [int(v) for v in _const_of(ctx, ins[1]).ravel()]
        if shape and shape[0] == -1:
            return "node", Node(nn.Reshape(tuple(shape[1:])), [x])
        return "node", Node(nn.Reshape(tuple(shape), batch_mode=False), [x])

    if op == "Squeeze":
        x = _node_of(ctx, ins[0])
        dims = tuple(int(i) for i in ndef.attr["squeeze_dims"].list.i)
        return "node", Node(nn.Squeeze(dims or None), [x])

    if op == "Mean":
        x = _node_of(ctx, ins[0])
        axes = tuple(int(v) for v in _const_of(ctx, ins[1]).ravel())
        keep = bool(ndef.attr["keep_dims"].b)
        if axes == (1, 2) and not keep:
            return "node", Node(nn.GlobalAveragePooling2D(), [x])
        return "node", Node(nnops.ReduceMean(axes, keep_dims=keep), [x])

    if op in ("ConcatV2", "Concat"):
        if op == "ConcatV2":
            parts, axis = ins[:-1], int(_const_of(ctx, ins[-1]).ravel()[0])
        else:
            axis, parts = int(_const_of(ctx, ins[0]).ravel()[0]), ins[1:]
        nodes = [_node_of(ctx, p) for p in parts]
        return "node", Node(nn.JoinTable(axis), nodes)

    if op == "Pad":
        x = _node_of(ctx, ins[0])
        pads = _const_of(ctx, ins[1]).astype(int)

        class _Pad(Module):
            def __init__(self, cfg):
                super().__init__()
                self.cfg = [tuple(r) for r in cfg]

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                return jnp.pad(input, self.cfg), state

        return "node", Node(_Pad(pads), [x])

    if op == "LRN":
        x = _node_of(ctx, ins[0])
        r = int(ndef.attr["depth_radius"].i or 5)
        bias = float(ndef.attr["bias"].f or 1.0)
        alpha = float(ndef.attr["alpha"].f or 1.0)
        beta = float(ndef.attr["beta"].f or 0.5)
        size = 2 * r + 1
        # TF: (bias + alpha*sum)^beta; ours (caffe): (k + alpha/size*sum)^beta
        return "node", Node(
            nn.SpatialCrossMapLRN(size, alpha * size, beta, bias), [x])

    if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
        x = _node_of(ctx, ins[0])
        scale = _const_of(ctx, ins[1])
        offset = _const_of(ctx, ins[2])
        mean = _const_of(ctx, ins[3])
        var = _const_of(ctx, ins[4])
        eps = float(ndef.attr["epsilon"].f or 1e-3)
        mod = nn.SpatialBatchNormalization(scale.shape[0], eps)
        node = Node(mod, [x])

        def install(params, s=scale, o=offset):
            params["weight"] = jnp.asarray(s)
            params["bias"] = jnp.asarray(o)

        def install_state(state, m=mean, v=var):
            state["running_mean"] = jnp.asarray(m)
            state["running_var"] = jnp.asarray(v)
        ctx.module_blobs.append((mod, install))
        ctx.module_blobs.append((mod, ("state", install_state)))
        return "node", node

    if op == "Cast":
        return _convert(ctx, ins[0])
    if op == "Shape":
        raise NotImplementedError(
            "dynamic Shape op (import the inference subgraph only)")
    raise NotImplementedError(f"TF op {op} has no converter")


def load_tf(path, inputs, outputs, binary=None, input_specs=None):
    """TensorflowLoader.load equivalent: extract the inference subgraph
    between ``inputs`` (placeholder names) and ``outputs`` (node names) and
    build a bigdl_tpu Graph.  Reference: TensorflowLoader.scala:43,358.

    ``input_specs``: dict name -> (shape NHWC, dtype) to build immediately.
    """
    import jax
    from bigdl_tpu.nn.graph import Graph, Input

    gdef = read_graph(path, binary)
    nodes = {n.name: n for n in gdef.node}
    ctx = _GraphCtx(nodes)
    for name in inputs:
        ctx.input_nodes[_clean(name)] = Input()

    out_nodes = []
    for name in outputs:
        kind, val = _convert(ctx, name)
        if kind != "node":
            raise ValueError(f"output {name} folded to a constant")
        out_nodes.append(val)

    in_nodes = [ctx.input_nodes[_clean(n)] for n in inputs]
    graph = Graph(in_nodes, out_nodes)

    if input_specs:
        specs = [jax.ShapeDtypeStruct(tuple(input_specs[n][0]),
                                      input_specs[n][1]) for n in inputs]
        graph.build(specs[0] if len(specs) == 1 else tuple(specs))
        _install(graph, ctx.module_blobs)
    else:
        orig_build = graph.build

        def build_and_install(spec, rng=None):
            out = orig_build(spec, rng=rng)
            _install(graph, ctx.module_blobs)
            return out
        graph.build = build_and_install
    return graph


def _install(graph, module_blobs):
    idx = {id(n.module): str(i) for i, n in enumerate(graph._topo)
           if n.module is not None}
    for mod, fn in module_blobs:
        if fn is None:
            continue
        key = idx[id(mod)]
        if isinstance(fn, tuple) and fn[0] == "state":
            fn[1](graph._state[key])
        else:
            fn(graph._params[key])


# --------------------------------------------------------------------------- #
# export (TensorflowSaver analogue)
# --------------------------------------------------------------------------- #


def save_tf(model, path, input_shape, input_name="input",
            output_name="output"):
    """Export a built Sequential to a frozen GraphDef
    (reference: utils/tf/TensorflowSaver.scala).
    """
    import bigdl_tpu.nn as nn

    g = tfpb.GraphDef()
    g.versions.producer = 21

    def add_const(name, arr, dtype=None):
        n = g.node.add()
        n.name = name
        n.op = "Const"
        tf_dtype = tfpb.DT_INT32 if dtype == np.int32 else tfpb.DT_FLOAT
        np_dtype = np.int32 if dtype == np.int32 else np.float32
        n.attr["dtype"].type = tf_dtype
        t = n.attr["value"].tensor
        t.dtype = tf_dtype
        for d in arr.shape:
            t.tensor_shape.dim.add().size = d
        t.tensor_content = np.ascontiguousarray(arr, np_dtype).tobytes()
        return name

    ph = g.node.add()
    ph.name = input_name
    ph.op = "Placeholder"
    ph.attr["dtype"].type = tfpb.DT_FLOAT
    for d in input_shape:
        ph.attr["shape"].shape.dim.add().size = d if d else -1

    cur = input_name
    counter = [0]

    def fresh(prefix):
        counter[0] += 1
        return f"{prefix}_{counter[0]}"

    def emit(mod, params, cur):
        if isinstance(mod, nn.Sequential):
            for i, ch in enumerate(mod.modules):
                cur = emit(ch, params.get(str(i), {}), cur)
            return cur
        if isinstance(mod, nn.SpatialConvolution):
            if mod.pad != (0, 0):
                # encode as explicit Pad + VALID conv (TF SAME cannot
                # represent arbitrary symmetric pads)
                pname = fresh("pad")
                pc = add_const(pname + "/paddings", np.asarray(
                    [[0, 0], [mod.pad[0], mod.pad[0]],
                     [mod.pad[1], mod.pad[1]], [0, 0]], np.int32),
                    dtype=np.int32)
                n = g.node.add()
                n.name = pname
                n.op = "Pad"
                n.input.extend([cur, pc])
                n.attr["T"].type = tfpb.DT_FLOAT
                n.attr["Tpaddings"].type = tfpb.DT_INT32
                cur = pname
            kname = add_const(fresh("kernel"), np.asarray(params["weight"]))
            n = g.node.add()
            n.name = fresh("conv2d")
            n.op = "Conv2D"
            n.input.extend([cur, kname])
            n.attr["T"].type = tfpb.DT_FLOAT
            n.attr["strides"].list.i.extend(
                [1, mod.stride[0], mod.stride[1], 1])
            n.attr["dilations"].list.i.extend([1, 1, 1, 1])
            n.attr["padding"].s = b"VALID"
            n.attr["data_format"].s = b"NHWC"
            cur = n.name
            if mod.with_bias:
                bname = add_const(fresh("bias"), np.asarray(params["bias"]))
                nb = g.node.add()
                nb.name = fresh("biasadd")
                nb.op = "BiasAdd"
                nb.input.extend([cur, bname])
                nb.attr["T"].type = tfpb.DT_FLOAT
                nb.attr["data_format"].s = b"NHWC"
                cur = nb.name
            return cur
        if isinstance(mod, nn.Linear):
            wname = add_const(fresh("weight"),
                              np.asarray(params["weight"]).T)
            n = g.node.add()
            n.name = fresh("matmul")
            n.op = "MatMul"
            n.input.extend([cur, wname])
            n.attr["T"].type = tfpb.DT_FLOAT
            n.attr["transpose_a"].b = False
            n.attr["transpose_b"].b = False
            cur = n.name
            if mod.with_bias:
                bname = add_const(fresh("bias"), np.asarray(params["bias"]))
                nb = g.node.add()
                nb.name = fresh("biasadd")
                nb.op = "BiasAdd"
                nb.input.extend([cur, bname])
                nb.attr["T"].type = tfpb.DT_FLOAT
                cur = nb.name
            return cur
        simple = {nn.ReLU: "Relu", nn.Tanh: "Tanh", nn.Sigmoid: "Sigmoid",
                  nn.SoftMax: "Softmax", nn.LogSoftMax: "LogSoftmax",
                  nn.ReLU6: "Relu6"}
        for cls, opname in simple.items():
            if type(mod) is cls:
                n = g.node.add()
                n.name = fresh(opname.lower())
                n.op = opname
                n.input.append(cur)
                n.attr["T"].type = tfpb.DT_FLOAT
                return n.name
        if isinstance(mod, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
            n = g.node.add()
            n.name = fresh("pool")
            n.op = ("MaxPool" if isinstance(mod, nn.SpatialMaxPooling)
                    else "AvgPool")
            n.input.append(cur)
            n.attr["T"].type = tfpb.DT_FLOAT
            n.attr["ksize"].list.i.extend([1, mod.kernel[0],
                                           mod.kernel[1], 1])
            n.attr["strides"].list.i.extend([1, mod.stride[0],
                                             mod.stride[1], 1])
            if mod.pad == (0, 0):
                n.attr["padding"].s = b"VALID"
            else:
                same_ph = (mod.kernel[0] - mod.stride[0] + 1) // 2 \
                    if mod.kernel[0] > mod.stride[0] else 0
                same_pw = (mod.kernel[1] - mod.stride[1] + 1) // 2 \
                    if mod.kernel[1] > mod.stride[1] else 0
                if mod.pad != (same_ph, same_pw):
                    raise NotImplementedError(
                        f"tf export: pooling pad {mod.pad} is not "
                        f"SAME-representable (expected {(same_ph, same_pw)})")
                n.attr["padding"].s = b"SAME"
            n.attr["data_format"].s = b"NHWC"
            return n.name
        if isinstance(mod, nn.Reshape):
            cname = fresh("shape")
            cn = g.node.add()
            cn.name = cname
            cn.op = "Const"
            cn.attr["dtype"].type = tfpb.DT_INT32
            t = cn.attr["value"].tensor
            t.dtype = tfpb.DT_INT32
            shape = [-1] + [int(v) for v in mod.size]
            t.tensor_shape.dim.add().size = len(shape)
            t.tensor_content = np.asarray(shape, np.int32).tobytes()
            rn = g.node.add()
            rn.name = fresh("reshape")
            rn.op = "Reshape"
            rn.input.extend([cur, cname])
            rn.attr["T"].type = tfpb.DT_FLOAT
            rn.attr["Tshape"].type = tfpb.DT_INT32
            return rn.name
        if isinstance(mod, nn.Dropout):
            return cur                     # inference graph: identity
        raise NotImplementedError(
            f"tf export: unsupported layer {type(mod).__name__}")

    if not isinstance(model, nn.Sequential):
        raise NotImplementedError("tf export supports Sequential models")
    cur = emit(model, model._params or {}, cur)

    out = g.node.add()
    out.name = output_name
    out.op = "Identity"
    out.input.append(cur)
    out.attr["T"].type = tfpb.DT_FLOAT

    with open(path, "wb") as f:
        f.write(g.SerializeToString())
    return path
