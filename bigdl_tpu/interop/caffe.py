"""Caffe model import/export.

Reference: utils/caffe/CaffeLoader.scala:57,531-561 (``load`` copies weights
into an existing net; ``loadCaffe`` builds the graph from the prototxt),
utils/caffe/Converter.scala / V1LayerConverter.scala (~50 layer-type
mappings), utils/caffe/CaffePersister.scala (export).

TPU-native notes: Caffe is NCHW; our convs/pools run NHWC.  Weights are
transposed at import ((out, in/g, kH, kW) -> HWIO) and an NCHW-ordered
flatten is inserted before InnerProduct layers so fully-connected weights
copy verbatim.
"""

import warnings

import numpy as np

from bigdl_tpu.interop import caffe_pb2
from google.protobuf import text_format


class _FlattenNCHW:
    """Flatten a NHWC activation in caffe's (C,H,W) feature order so
    imported InnerProduct weights apply unchanged."""

    def __new__(cls):
        from bigdl_tpu.nn.module import Module

        class FlattenNCHW(Module):
            def apply(self, params, state, input, *, training=False,
                      rng=None):
                import jax.numpy as jnp
                if input.ndim == 4:
                    input = jnp.transpose(input, (0, 3, 1, 2))
                return input.reshape(input.shape[0], -1), state

        return FlattenNCHW()


def _read_net(path, binary):
    net = caffe_pb2.NetParameter()
    if binary:
        with open(path, "rb") as f:
            net.ParseFromString(f.read())
    else:
        with open(path) as f:
            text_format.Parse(f.read(), net, allow_unknown_field=True)
    return net


_V1_TYPE_NAMES = {
    v: k for k, v in caffe_pb2.V1LayerParameter.LayerType.items()
}

_V1_TO_NEW = {
    "CONVOLUTION": "Convolution", "INNER_PRODUCT": "InnerProduct",
    "POOLING": "Pooling", "RELU": "ReLU", "TANH": "TanH",
    "SIGMOID": "Sigmoid", "SOFTMAX": "Softmax",
    "SOFTMAX_LOSS": "SoftmaxWithLoss", "LRN": "LRN", "DROPOUT": "Dropout",
    "CONCAT": "Concat", "ELTWISE": "Eltwise", "FLATTEN": "Flatten",
    "SPLIT": "Split", "SLICE": "Slice", "POWER": "Power",
    "THRESHOLD": "Threshold", "ABSVAL": "AbsVal", "EXP": "Exp",
    "BNLL": "BNLL", "DATA": "Data", "DECONVOLUTION": "Deconvolution",
}


def _layers(net):
    """Normalized (name, type_str, bottoms, tops, layer_pb) across the new
    ``layer`` and legacy ``layers`` (V1) fields."""
    out = []
    for l in net.layer:
        out.append((l.name, l.type, list(l.bottom), list(l.top), l))
    for l in net.layers:
        tname = _V1_TYPE_NAMES.get(l.type, str(l.type))
        out.append((l.name, _V1_TO_NEW.get(tname, tname),
                    list(l.bottom), list(l.top), l))
    return out


_DATA_TYPES = {"Data", "ImageData", "HDF5Data", "MemoryData", "WindowData",
               "DummyData", "Input", "AnnotatedData"}
#: SigmoidCrossEntropyLoss is NOT here: the reference keeps its
#: inference-time activation (Converter.scala: SIGMOIDCROSSENTROPYLOSS ->
#: fromCaffeSigmoid), so it converts to a Sigmoid module below.
_LOSS_TYPES = {"SoftmaxWithLoss", "EuclideanLoss", "HingeLoss",
               "InfogainLoss", "ContrastiveLoss",
               "MultinomialLogisticLoss", "Accuracy", "Silence"}
#: n-ary / multi-output layer types wired directly in load_caffe (not via
#: the single-input _build_module path)
_STRUCTURAL_TYPES = {"Split", "Concat", "Eltwise", "Slice"}


def _hw(param, field, default=None):
    """kernel/stride/pad may be repeated, _h/_w, or absent."""
    raw = getattr(param, field)
    # conv params are repeated; pooling params are scalar
    rep = list(raw) if hasattr(raw, "__len__") else ([int(raw)] if raw else [])
    base = field[:-5] if field.endswith("_size") else field  # kernel_size -> kernel_h
    h = getattr(param, base + "_h", 0)
    w = getattr(param, base + "_w", 0)
    if h or w:
        return int(h), int(w)
    if rep:
        return (int(rep[0]), int(rep[0])) if len(rep) == 1 \
            else (int(rep[0]), int(rep[1]))
    return default


def _build_module(type_str, lpb, in_channels, customized):
    """caffe layer -> (module, out_channels) (reference: Converter.scala
    per-type ``toCaffe*`` mappings)."""
    import bigdl_tpu.nn as nn

    if type_str == "Convolution":
        p = lpb.convolution_param
        kh, kw = _hw(p, "kernel_size")
        sh, sw = _hw(p, "stride", (1, 1))
        ph, pw = _hw(p, "pad", (0, 0))
        dil = list(p.dilation)
        dh = dw = int(dil[0]) if dil else 1
        nout = int(p.num_output)
        m = nn.SpatialConvolution(
            in_channels, nout, kw, kh, sw, sh, pw, ph,
            n_group=int(p.group), dilation_w=dw, dilation_h=dh,
            with_bias=bool(p.bias_term))
        return m, nout
    if type_str == "InnerProduct":
        p = lpb.inner_product_param
        nout = int(p.num_output)
        seq = (nn.Sequential()
               .add(_FlattenNCHW())
               .add(nn.Linear(None, nout, with_bias=bool(p.bias_term))))
        return seq, nout
    if type_str == "Pooling":
        p = lpb.pooling_param
        kh, kw = _hw(p, "kernel_size", (2, 2))
        sh, sw = _hw(p, "stride", (1, 1))
        ph, pw = _hw(p, "pad", (0, 0))
        if p.global_pooling:
            cls = (nn.GlobalMaxPooling2D
                   if p.pool == caffe_pb2.PoolingParameter.MAX
                   else nn.GlobalAveragePooling2D)
            return cls(), in_channels
        cls = (nn.SpatialMaxPooling
               if p.pool == caffe_pb2.PoolingParameter.MAX
               else nn.SpatialAveragePooling)
        m = cls(kw, kh, sw, sh, pw, ph)
        if p.round_mode == caffe_pb2.PoolingParameter.CEIL:
            m.ceil()          # caffe default rounding
        return m, in_channels
    if type_str == "ReLU":
        slope = float(lpb.relu_param.negative_slope) \
            if lpb.HasField("relu_param") else 0.0
        return (nn.LeakyReLU(slope) if slope else nn.ReLU()), in_channels
    if type_str == "TanH":
        return nn.Tanh(), in_channels
    if type_str == "Sigmoid":
        return nn.Sigmoid(), in_channels
    if type_str == "AbsVal":
        return nn.Abs(), in_channels
    if type_str == "Exp":
        return nn.Exp(), in_channels
    if type_str == "ELU":
        return nn.ELU(float(lpb.elu_param.alpha)), in_channels
    if type_str == "Softmax":
        return nn.SoftMax(), in_channels
    if type_str == "LRN":
        p = lpb.lrn_param
        # caffe divides alpha by the window size; the reference maps
        # directly (CaffeLoader uses alpha as-is into SpatialCrossMapLRN)
        return nn.SpatialCrossMapLRN(int(p.local_size), float(p.alpha),
                                     float(p.beta), float(p.k)), in_channels
    if type_str == "Dropout":
        return nn.Dropout(float(lpb.dropout_param.dropout_ratio)), \
            in_channels
    if type_str == "BatchNorm":
        eps = float(lpb.batch_norm_param.eps) \
            if lpb.HasField("batch_norm_param") else 1e-5
        return nn.SpatialBatchNormalization(in_channels, eps, affine=False), \
            in_channels
    if type_str == "Scale":
        p = lpb.scale_param
        return _ChannelAffine(in_channels, bool(p.bias_term)), in_channels
    if type_str == "Concat":
        # channel concat in NCHW axis 1 == our NHWC axis 3 (handled by
        # caller: Concat is an n-ary node)
        raise AssertionError("Concat handled by caller")
    if type_str == "Flatten":
        seq = nn.Sequential().add(_FlattenNCHW())
        return seq, in_channels
    if type_str == "Power":
        p = lpb.power_param
        return nn.Power(float(p.power), float(p.scale), float(p.shift)), \
            in_channels
    if type_str == "Threshold":
        return nn.Threshold(float(lpb.threshold_param.threshold)), \
            in_channels
    if type_str == "Deconvolution":
        # reference: Converter.scala registers DECONVOLUTION through
        # fromCaffeConvolution; ours maps to the transposed conv directly
        p = lpb.convolution_param
        kh, kw = _hw(p, "kernel_size")
        sh, sw = _hw(p, "stride", (1, 1))
        ph, pw = _hw(p, "pad", (0, 0))
        nout = int(p.num_output)
        if int(p.group) not in (0, 1):
            raise NotImplementedError(
                "caffe grouped Deconvolution (group>1) has no converter; "
                "pass customized_layers to split the groups by hand")
        if any(int(d) != 1 for d in p.dilation):
            raise NotImplementedError(
                "caffe dilated Deconvolution has no converter "
                "(SpatialFullConvolution is stride/adj only)")
        m = nn.SpatialFullConvolution(
            in_channels, nout, kw, kh, sw, sh, pw, ph,
            with_bias=bool(p.bias_term))
        return m, nout
    if type_str == "PReLU":
        # per-channel learnable slope (reference: fromCaffePreLU,
        # Converter.scala:190); channel = NHWC last axis here.
        # channel_shared stores a single slope -> nn.PReLU(0) (shared)
        shared = bool(lpb.prelu_param.channel_shared) \
            if lpb.HasField("prelu_param") else False
        return nn.PReLU(0 if shared else in_channels), in_channels
    if type_str == "Log":
        return nn.Log(), in_channels
    if type_str == "BNLL":
        return nn.SoftPlus(), in_channels      # log(1 + e^x)
    if type_str == "SigmoidCrossEntropyLoss":
        return nn.Sigmoid(), in_channels
    if type_str == "Reshape":
        p = lpb.reshape_param
        if int(p.axis) != 0 or int(p.num_axes) != -1:
            raise NotImplementedError(
                "caffe partial Reshape (axis/num_axes restricting the "
                "reshaped span) has no converter; only the full-shape "
                "default (axis=0, num_axes=-1) does")
        dims = tuple(int(d) for d in p.shape.dim)
        cout = dims[1] if len(dims) > 1 and dims[1] > 0 else in_channels
        return _ReshapeNCHW(dims), cout
    if type_str == "Tile":
        p = lpb.tile_param
        axis = int(p.axis) if p.HasField("axis") else 1
        if axis < 0:
            # the activation rank is unknown here, so a negative axis
            # cannot be normalized for channel bookkeeping -- fail loudly
            # rather than mis-size downstream channel-sensitive layers
            raise NotImplementedError(
                f"caffe Tile with negative axis {axis} has no converter; "
                "rewrite the prototxt with the equivalent positive axis")
        tiles = int(p.tiles)
        cout = in_channels * tiles if axis == 1 else in_channels
        return _TileNCHW(axis, tiles), cout
    if type_str == "Bias":
        # learnable per-channel bias (reference: fromCaffeBias -> Add;
        # LayerConverter.scala:196); two-bottom runtime-bias form is the
        # Eltwise SUM path, not this layer
        if len(lpb.bottom) > 1:
            raise NotImplementedError(
                "caffe Bias with a second bottom (runtime-supplied bias) "
                "has no converter; only the learned-parameter form does")
        p = getattr(lpb, "bias_param", None)   # absent from the vendored proto
        axis = int(p.axis) if p is not None and p.HasField("axis") else 1
        if axis != 1:
            raise NotImplementedError(
                f"caffe Bias axis={axis}; only the per-channel default "
                "(axis=1) has a converter")
        return _ChannelBias(in_channels), in_channels
    if type_str in ("Recurrent", "RNN"):
        raise NotImplementedError(
            "caffe Recurrent/RNN: the reference converter emits a cell-less "
            "Recurrent() that cannot execute (Converter.scala:200-203), so "
            "there is no working semantics to match; build the recurrent "
            "stack with bigdl_tpu.nn.Recurrent + a cell and copy_weights, "
            "or pass customized_layers")
    if customized and type_str in customized:
        return customized[type_str](lpb), in_channels
    raise NotImplementedError(
        f"caffe layer type {type_str} has no converter "
        f"(pass customized_layers={{'{type_str}': fn}})")


def _ChannelAffine(n, with_bias):
    """caffe Scale layer: per-channel multiply (+ optional bias)."""
    from bigdl_tpu.nn.module import Module
    import jax.numpy as jnp

    class ChannelAffine(Module):
        def setup(self, rng, input_spec):
            params = {"weight": jnp.ones((n,), jnp.float32)}
            if with_bias:
                params["bias"] = jnp.zeros((n,), jnp.float32)
            return params, ()

        def apply(self, params, state, input, *, training=False, rng=None):
            y = input * params["weight"]
            if with_bias:
                y = y + params["bias"]
            return y, state

    return ChannelAffine()


def _ChannelBias(n):
    """caffe Bias layer: learnable per-channel additive bias
    (reference: LayerConverter.fromCaffeBias -> Add)."""
    from bigdl_tpu.nn.module import Module
    import jax.numpy as jnp

    class ChannelBias(Module):
        def setup(self, rng, input_spec):
            return {"bias": jnp.zeros((n,), jnp.float32)}, ()

        def apply(self, params, state, input, *, training=False, rng=None):
            return input + params["bias"].astype(input.dtype), state

    return ChannelBias()


def _ReshapeNCHW(dims):
    """caffe Reshape: dims are NCHW-ordered with 0 = copy input dim and
    -1 = infer (reference: LayerConverter.fromCaffeReshape ->
    InferReshape).  Activations here are NHWC, so rank-4 tensors round-trip
    through NCHW for the reshape itself."""
    from bigdl_tpu.nn.module import Module
    import jax.numpy as jnp

    class ReshapeNCHW(Module):
        def apply(self, params, state, input, *, training=False, rng=None):
            x = input
            if x.ndim == 4:
                x = jnp.transpose(x, (0, 3, 1, 2))
            shape = tuple(x.shape[i] if d == 0 else d
                          for i, d in enumerate(dims))
            y = jnp.reshape(x, shape)
            if y.ndim == 4:
                y = jnp.transpose(y, (0, 2, 3, 1))
            return y, state

    return ReshapeNCHW()


def _TileNCHW(axis, tiles):
    """caffe Tile: repeat ``tiles`` times along an NCHW ``axis``
    (reference: LayerConverter.fromCaffeTile -> Tile)."""
    from bigdl_tpu.nn.module import Module
    import jax.numpy as jnp

    class TileNCHW(Module):
        def apply(self, params, state, input, *, training=False, rng=None):
            a = axis + (input.ndim if axis < 0 else 0)
            if input.ndim == 4:
                a = {0: 0, 1: 3, 2: 1, 3: 2}.get(a, a)
            reps = [1] * input.ndim
            reps[a] = tiles
            return jnp.tile(input, reps), state

    return TileNCHW()


def load_caffe(prototxt_path, model_path=None, input_shape=None,
               customized_layers=None):
    """Build a bigdl_tpu Graph from a prototxt (+ optional .caffemodel
    weights).  Reference: CaffeLoader.loadCaffe (CaffeLoader.scala:531).

    ``input_shape``: NHWC tuple overriding the prototxt input_dim.
    Train-phase-only and loss/data layers are skipped (reference keeps the
    inference subgraph).
    """
    import jax
    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.graph import Graph, Input, Node

    net = _read_net(prototxt_path, binary=False)
    weights = {}
    if model_path is not None:
        wnet = _read_net(model_path, binary=True)
        for name, _, _, _, lpb in _layers(wnet):
            if lpb.blobs:
                weights[name] = [_blob_to_array(b) for b in lpb.blobs]

    # input spec
    if net.input_dim:
        n, c, h, w = list(net.input_dim)[:4]
        nchw_shape = (n, c, h, w)
    elif net.input_shape:
        nchw_shape = tuple(int(d) for d in net.input_shape[0].dim)
    else:
        nchw_shape = None
    if input_shape is None:
        if nchw_shape is None:
            raise ValueError("no input shape in prototxt; pass input_shape=")
        n, c, h, w = nchw_shape
        input_shape = (n, h, w, c)

    inp = Input()
    top_nodes = {}
    channels = {}
    ranks = {}             # activation rank per top (concat-axis mapping)
    if net.input:
        top_nodes[net.input[0]] = inp
        channels[net.input[0]] = input_shape[-1]
        ranks[net.input[0]] = len(input_shape)
    module_blobs = []      # (module, blob list) in construction order

    first_data = True
    for name, type_str, bottoms, tops, lpb in _layers(net):
        include = list(getattr(lpb, "include", []))
        if any(r.HasField("phase") and r.phase == caffe_pb2.TRAIN
               for r in include):
            continue
        if type_str in _LOSS_TYPES:
            continue
        if type_str in _DATA_TYPES:
            # the (first) data layer's top becomes the graph input
            if first_data and tops:
                top_nodes[tops[0]] = inp
                channels[tops[0]] = input_shape[-1]
                ranks[tops[0]] = len(input_shape)
                first_data = False
            continue
        if type_str == "Split":
            for t in tops:
                top_nodes[t] = top_nodes[bottoms[0]]
                channels[t] = channels[bottoms[0]]
                ranks[t] = ranks.get(bottoms[0], 4)
            continue
        if type_str == "Concat":
            p = lpb.concat_param
            axis = int(p.axis)
            # NCHW (0,1,2,3) -> NHWC (0,3,1,2) -- 4-D activations only;
            # 2-D (batch, features) axes map identically (mirrors the
            # exporter's _caffe_axis)
            rank = ranks.get(bottoms[0], 4)
            if axis < 0:               # caffe allows negative axes
                axis += rank
            our_axis = ({0: 0, 1: 3, 2: 1, 3: 2}.get(axis, axis)
                        if rank == 4 else axis)
            mod = nn.JoinTable(our_axis)
            parents = [top_nodes[b] for b in bottoms]
            node = Node(mod, parents)
            top_nodes[tops[0]] = node
            channels[tops[0]] = sum(channels[b] for b in bottoms)
            ranks[tops[0]] = rank
            module_blobs.append((mod, None))
            continue
        if type_str == "Eltwise":
            op = lpb.eltwise_param.operation
            mod = {caffe_pb2.EltwiseParameter.SUM: nn.CAddTable,
                   caffe_pb2.EltwiseParameter.PROD: nn.CMulTable,
                   caffe_pb2.EltwiseParameter.MAX: nn.CMaxTable}[op]()
            parents = [top_nodes[b] for b in bottoms]
            node = Node(mod, parents)
            top_nodes[tops[0]] = node
            channels[tops[0]] = channels[bottoms[0]]
            ranks[tops[0]] = ranks.get(bottoms[0], 4)
            module_blobs.append((mod, None))
            continue
        if type_str == "Slice":
            # multi-output split along an NCHW axis (reference:
            # fromCaffeSlice -> SplitTable, Converter.scala:219); one
            # Narrow node per top
            p = lpb.slice_param
            axis = int(p.axis) if p.HasField("axis") else (
                int(p.slice_dim) if p.HasField("slice_dim") else 1)
            rank = ranks.get(bottoms[0], 4)
            if axis < 0:
                axis += rank
            our_axis = ({0: 0, 1: 3, 2: 1, 3: 2}.get(axis, axis)
                        if rank == 4 else axis)
            points = [int(q) for q in p.slice_point]
            cin = channels.get(bottoms[0], input_shape[-1])
            if points:
                offsets = [0] + points
                lengths = [offsets[i + 1] - offsets[i]
                           for i in range(len(offsets) - 1)]
                # last segment runs to the end; its extent is known on the
                # channel axis (cin - last point) for channel bookkeeping
                lengths.append(cin - points[-1] if axis == 1 else -1)
            else:
                if axis != 1:
                    raise NotImplementedError(
                        f"caffe Slice without slice_point on axis {axis}: "
                        "the equal-split size is only known on the channel "
                        "axis")
                if cin % len(tops):
                    raise ValueError(
                        f"caffe Slice: {cin} channels not divisible into "
                        f"{len(tops)} tops")
                seg = cin // len(tops)
                offsets = [i * seg for i in range(len(tops))]
                lengths = [seg] * len(tops)
            for t, off, ln in zip(tops, offsets, lengths):
                mod = nn.Narrow(our_axis, off, ln)
                node = Node(mod, [top_nodes[bottoms[0]]])
                top_nodes[t] = node
                channels[t] = ln if (axis == 1 and ln > 0) else cin
                ranks[t] = rank
                module_blobs.append((mod, None))
            continue

        bottom = bottoms[0]
        cin = channels.get(bottom, input_shape[-1])
        mod, cout = _build_module(type_str, lpb, cin,
                                  customized_layers or {})
        mod.name = name        # caffe layer name (copy_weights matches on it)
        node = Node(mod, [top_nodes[bottom]])
        out_top = tops[0] if tops else name
        top_nodes[out_top] = node
        channels[out_top] = cout
        if (type_str in ("InnerProduct", "Flatten")
                or (type_str == "Pooling"
                    and lpb.pooling_param.global_pooling)):
            ranks[out_top] = 2          # these collapse to (batch, features)
        elif type_str == "Reshape":
            ranks[out_top] = len(lpb.reshape_param.shape.dim)
        else:
            ranks[out_top] = ranks.get(bottom, 4)
        module_blobs.append((mod, weights.get(name)))

    # terminal nodes = tops never consumed as bottoms
    consumed = set()
    for _, type_str, bottoms, tops, lpb in _layers(net):
        if type_str in _LOSS_TYPES or type_str in _DATA_TYPES:
            continue
        for b in bottoms:
            if b not in tops:          # in-place layers don't consume
                consumed.add(b)
    outs = [node for t, node in top_nodes.items()
            if t not in consumed and node is not inp]
    graph = Graph([inp], outs if len(outs) > 1 else outs[:1])

    spec = jax.ShapeDtypeStruct(tuple(input_shape), np.float32)
    graph.build(spec)
    if weights:
        _install_weights(graph, module_blobs)
    return graph


def _blob_to_array(b):
    data = np.asarray(b.double_data or b.data, np.float32)
    if b.shape.dim:
        return data.reshape(tuple(int(d) for d in b.shape.dim))
    # legacy 4-d (num, channels, height, width) kept in full: consumers
    # reshape to the rank they need (conv weights 4-d, biases 1-d) so a
    # num_output=1 conv blob is never mis-squeezed
    legacy = tuple(max(d, 1) for d in (b.num, b.channels, b.height, b.width))
    return data.reshape(legacy if int(np.prod(legacy)) == data.size
                        else (data.size,))


def _install_blobs(mod, params, state, blobs, strict_shapes=True):
    """Install one caffe layer's blobs into a module's param/state dicts,
    layout-converted (conv (out, in/g, kH, kW) -> HWIO, InnerProduct
    verbatim caffe column order, BN mean/var with the scale factor,
    Scale -> ChannelAffine).  The ONE conversion table -- both the import
    path and copy_weights go through it.  -> True if installed, False for
    module types with no blob convention."""
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn

    def put(tgt, key, arr, what):
        """Install with a shape check against the existing leaf -- a
        mismatched caffemodel must fail here, not later inside XLA."""
        arr = np.asarray(arr, np.float32)
        if strict_shapes and key in tgt \
                and tuple(tgt[key].shape) != arr.shape:
            raise ValueError(
                f"{what} {key} shape {arr.shape} != expected "
                f"{tuple(tgt[key].shape)} on {type(mod).__name__} "
                f"'{getattr(mod, 'name', '?')}'")
        tgt[key] = jnp.asarray(arr)

    if isinstance(mod, nn.SpatialConvolution):
        w = blobs[0].reshape(blobs[0].shape[-4:])  # (out, in/g, kh, kw)
        put(params, "weight", w.transpose(2, 3, 1, 0), "conv")
        if len(blobs) > 1 and "bias" in params:
            put(params, "bias", blobs[1].reshape(-1), "conv")
        return True
    if isinstance(mod, nn.Linear):
        put(params, "weight", blobs[0].reshape(blobs[0].shape[-2:]),
            "InnerProduct")
        if len(blobs) > 1 and "bias" in params:
            put(params, "bias", blobs[1].reshape(-1), "InnerProduct")
        return True
    if isinstance(mod, nn.Sequential) and mod.modules \
            and isinstance(mod.modules[-1], nn.Linear):
        # InnerProduct import wrapper (flatten + linear)
        sub = params[str(len(mod.modules) - 1)]
        return _install_blobs(mod.modules[-1], sub, {}, blobs,
                              strict_shapes=strict_shapes)
    if isinstance(mod, nn.SpatialBatchNormalization):
        # caffe BatchNorm blobs: mean, var, scale_factor
        scale = float(blobs[2][0]) if len(blobs) > 2 and blobs[2].size \
            else 1.0
        scale = 1.0 / scale if scale != 0 else 1.0
        put(state, "running_mean", blobs[0].reshape(-1) * scale, "BN")
        put(state, "running_var", blobs[1].reshape(-1) * scale, "BN")
        return True
    if type(mod).__name__ == "ChannelAffine":  # caffe Scale layer
        put(params, "weight", blobs[0].reshape(-1), "Scale")
        if len(blobs) > 1 and "bias" in params:
            put(params, "bias", blobs[1].reshape(-1), "Scale")
        return True
    if isinstance(mod, nn.SpatialFullConvolution):
        # caffe Deconvolution blob: (in, out, kH, kW) -> ours (kH, kW, in, out)
        w = blobs[0].reshape(blobs[0].shape[-4:])
        put(params, "weight", w.transpose(2, 3, 0, 1), "deconv")
        if len(blobs) > 1 and "bias" in params:
            put(params, "bias", blobs[1].reshape(-1), "deconv")
        return True
    if isinstance(mod, nn.PReLU):
        put(params, "weight", blobs[0].reshape(-1), "PReLU")
        return True
    if type(mod).__name__ == "ChannelBias":    # caffe Bias layer
        put(params, "bias", blobs[0].reshape(-1), "Bias")
        return True
    return False


def _install_weights(graph, module_blobs):
    """Copy caffe blobs into the built graph's params (layout-converted)."""
    mod_to_idx = {}
    for i, node in enumerate(graph._topo):
        if node.module is not None:
            mod_to_idx[id(node.module)] = str(i)

    for mod, blobs in module_blobs:
        if not blobs:
            continue
        key = mod_to_idx[id(mod)]
        if not _install_blobs(mod, graph._params[key], graph._state[key],
                              blobs):
            warnings.warn(f"blobs for unhandled module {type(mod).__name__}")


def _caffe_axis(dim, spec):
    """Ours (NHWC, possibly negative) -> caffe (NCHW) concat axis.  2-D
    activations (batch, features) map identically; only 4-D needs the
    NHWC->NCHW permutation."""
    rank = len(spec) if spec else 4
    if dim < 0:
        dim += rank
    if rank == 4:
        return {0: 0, 3: 1, 1: 2, 2: 3}.get(dim, dim)
    return dim


def save_caffe(model, prototxt_path, model_path, input_shape):
    """Export a model to prototxt + caffemodel (reference:
    utils/caffe/CaffePersister.scala, which walks arbitrary graphs).
    Supports ``Sequential`` chains, ``Concat`` tower fan-outs (the
    Inception pattern) and ``Graph`` DAGs (JoinTable -> Concat,
    CAddTable/CMulTable/CMaxTable -> Eltwise, BatchNormalization ->
    BatchNorm+Scale pair).

    ``input_shape``: NHWC; written as caffe NCHW input_dim.
    """
    import bigdl_tpu.nn as nn

    net = caffe_pb2.NetParameter()
    net.name = model.name or "bigdl_tpu"
    n, h, w, c = input_shape
    net.input.append("data")
    net.input_dim.extend([n, c, h, w])

    # spec tracking: pre_flat[0] holds the (H, W, C) of the activation that
    # the most recent Flatten collapsed, so Linear columns can be permuted
    # into caffe's (C, H, W) flatten order
    pre_flat = [None]
    cur_spec = [tuple(input_shape)]
    used_names = set()

    def unique(name):
        base = name or "layer"
        out, i = base, 1
        while out in used_names:
            out = f"{base}_{i}"
            i += 1
        used_names.add(out)
        return out

    def emit(mod, params, bottoms, substate=None):
        if isinstance(mod, nn.Identity):
            if len(bottoms) > 1:
                raise NotImplementedError(
                    "caffe export: multi-input Identity (tuple "
                    "pass-through has no caffe layer)")
            return bottoms[0]
        l = net.layer.add()
        l.name = unique(mod.name)
        l.bottom.extend(bottoms)
        top = l.name
        l.top.append(top)
        if isinstance(mod, nn.SpatialConvolution):
            l.type = "Convolution"
            p = l.convolution_param
            p.num_output = mod.n_output_plane
            p.kernel_h, p.kernel_w = mod.kernel
            p.stride_h, p.stride_w = mod.stride
            p.pad_h, p.pad_w = mod.pad
            p.group = mod.n_group
            p.bias_term = mod.with_bias
            wb = l.blobs.add()
            warr = np.asarray(params["weight"]).transpose(3, 2, 0, 1)
            wb.shape.dim.extend(warr.shape)
            wb.data.extend(warr.ravel().tolist())
            if mod.with_bias:
                bb = l.blobs.add()
                bb.shape.dim.extend(params["bias"].shape)
                bb.data.extend(np.asarray(params["bias"]).ravel().tolist())
        elif isinstance(mod, nn.Linear):
            l.type = "InnerProduct"
            p = l.inner_product_param
            p.num_output = mod.output_size
            p.bias_term = mod.with_bias
            wb = l.blobs.add()
            warr = np.asarray(params["weight"])
            if pre_flat[0] is not None:
                hh, ww, cc = pre_flat[0]
                if hh * ww * cc == warr.shape[1] and (hh > 1 or ww > 1):
                    # NHWC-flat columns -> caffe (C,H,W)-flat columns
                    perm = (np.arange(hh * ww * cc)
                            .reshape(hh, ww, cc)
                            .transpose(2, 0, 1).ravel())
                    warr = warr[:, perm]
                pre_flat[0] = None
            wb.shape.dim.extend(warr.shape)
            wb.data.extend(warr.ravel().tolist())
            if mod.with_bias:
                bb = l.blobs.add()
                bb.shape.dim.extend(params["bias"].shape)
                bb.data.extend(np.asarray(params["bias"]).ravel().tolist())
        elif isinstance(mod, (nn.SpatialMaxPooling,
                              nn.SpatialAveragePooling)):
            l.type = "Pooling"
            p = l.pooling_param
            p.pool = (caffe_pb2.PoolingParameter.MAX
                      if isinstance(mod, nn.SpatialMaxPooling)
                      else caffe_pb2.PoolingParameter.AVE)
            p.kernel_h, p.kernel_w = mod.kernel
            p.stride_h, p.stride_w = mod.stride
            p.pad_h, p.pad_w = mod.pad
            p.round_mode = (caffe_pb2.PoolingParameter.CEIL
                            if mod.ceil_mode
                            else caffe_pb2.PoolingParameter.FLOOR)
        elif isinstance(mod, nn.ReLU):
            l.type = "ReLU"
        elif isinstance(mod, nn.Tanh):
            l.type = "TanH"
        elif isinstance(mod, nn.Sigmoid):
            l.type = "Sigmoid"
        elif isinstance(mod, (nn.SoftMax, nn.LogSoftMax)):
            l.type = "Softmax"   # LogSoftMax exported as Softmax (+log note)
        elif isinstance(mod, nn.SpatialCrossMapLRN):
            l.type = "LRN"
            p = l.lrn_param
            p.local_size = mod.size
            p.alpha, p.beta, p.k = mod.alpha, mod.beta, mod.k
        elif isinstance(mod, nn.Dropout):
            l.type = "Dropout"
            l.dropout_param.dropout_ratio = mod.p
        elif type(mod).__name__ == "FlattenNCHW" or \
                isinstance(mod, nn.Flatten):
            l.type = "Flatten"
            spec = cur_spec[0]
            if spec is not None and len(spec) == 4:
                # our nn.Flatten collapses NHWC order; remember the spatial
                # shape so the following Linear's columns get permuted
                # (FlattenNCHW needs no permutation -- it is already C,H,W)
                if isinstance(mod, nn.Flatten):
                    pre_flat[0] = (spec[1], spec[2], spec[3])
        elif isinstance(mod, (nn.GlobalAveragePooling2D,
                              nn.GlobalMaxPooling2D)):
            l.type = "Pooling"
            p = l.pooling_param
            p.pool = (caffe_pb2.PoolingParameter.MAX
                      if isinstance(mod, nn.GlobalMaxPooling2D)
                      else caffe_pb2.PoolingParameter.AVE)
            p.global_pooling = True
        elif isinstance(mod, nn.JoinTable):
            l.type = "Concat"
            l.concat_param.axis = _caffe_axis(mod.dimension, cur_spec[0])
        elif isinstance(mod, (nn.CAddTable, nn.CMulTable, nn.CMaxTable)):
            l.type = "Eltwise"
            l.eltwise_param.operation = {
                nn.CAddTable: caffe_pb2.EltwiseParameter.SUM,
                nn.CMulTable: caffe_pb2.EltwiseParameter.PROD,
                nn.CMaxTable: caffe_pb2.EltwiseParameter.MAX,
            }[type(mod)]
        elif isinstance(mod, nn.SpatialBatchNormalization):
            l.type = "BatchNorm"
            l.batch_norm_param.eps = mod.eps
            st = substate or {}
            mean = np.asarray(st.get("running_mean",
                                     np.zeros(mod.n_output, np.float32)))
            var = np.asarray(st.get("running_var",
                                    np.ones(mod.n_output, np.float32)))
            for arr in (mean, var, np.ones(1, np.float32)):
                b = l.blobs.add()
                b.shape.dim.extend(arr.shape)
                b.data.extend(arr.ravel().tolist())
            if "weight" in (params or {}):   # affine part -> Scale layer
                sl = net.layer.add()
                sl.name = unique(l.name + "_scale")
                sl.type = "Scale"
                sl.bottom.append(top)
                top = sl.name
                sl.top.append(top)
                sl.scale_param.bias_term = "bias" in params
                for key in ("weight", "bias"):
                    if key in params:
                        arr = np.asarray(params[key])
                        b = sl.blobs.add()
                        b.shape.dim.extend(arr.shape)
                        b.data.extend(arr.ravel().tolist())
        else:
            raise NotImplementedError(
                f"caffe export: unsupported layer {type(mod).__name__}")
        return top

    def _advance_spec(child, sub, substate):
        import jax
        try:
            spec_in = jax.ShapeDtypeStruct(cur_spec[0], np.float32)
            out = child.output_spec(sub, substate, spec_in)
            cur_spec[0] = tuple(out.shape)
        except Exception:
            cur_spec[0] = None   # spec tracking is best-effort

    def walk(child, params, state, top, allow_multi=False):
        """Emit ``child`` fed from ``top``; returns its output top (or
        top list for a multi-output root graph)."""
        state = state if isinstance(state, dict) else {}
        if isinstance(child, nn.Sequential):
            for i, sub in enumerate(child.modules):
                top = walk(sub, (params or {}).get(str(i), {}),
                           state.get(str(i), {}), top)
            return top
        if isinstance(child, nn.Concat):
            # every tower sees the SAME input spec; snapshot and restore
            in_spec = cur_spec[0]
            tower_tops = []
            for i, t in enumerate(child.modules):
                cur_spec[0] = in_spec
                tower_tops.append(walk(t, (params or {}).get(str(i), {}),
                                       state.get(str(i), {}), top))
            tower_out_spec = cur_spec[0]   # what is actually concatenated
            l = net.layer.add()
            l.name = unique(child.name or "concat")
            l.type = "Concat"
            l.bottom.extend(tower_tops)
            l.top.append(l.name)
            l.concat_param.axis = _caffe_axis(child.dimension,
                                              tower_out_spec or in_spec)
            cur_spec[0] = in_spec
            _advance_spec(child, params, state)
            return l.name
        if isinstance(child, nn.Graph):
            if len(child.input_nodes) > 1:
                raise NotImplementedError(
                    "caffe export: multi-input graphs")
            tops, specs = {}, {}
            for inp_node in child.input_nodes:
                tops[id(inp_node)] = top
                specs[id(inp_node)] = cur_spec[0]
            for i, node in enumerate(child._topo):
                if node.module is None:
                    continue
                bottoms = [tops[id(p)] for p in node.inputs]
                mod = node.module
                sub = (params or {}).get(str(i), {})
                substate = state.get(str(i), {})
                # per-node spec tracking so Flatten+Linear inside the DAG
                # still gets its column permutation
                cur_spec[0] = specs.get(id(node.inputs[0])) \
                    if node.inputs else None
                if isinstance(mod, nn.Linear) and node.inputs:
                    # pre_flat is consumed-once (sequential idiom); a
                    # Flatten node shared by several Linear heads must
                    # re-derive it per head from the Flatten's own input
                    parent = node.inputs[0]
                    pmod = getattr(parent, "module", None)
                    gp_spec = (specs.get(id(parent.inputs[0]))
                               if parent.inputs else None)
                    if (pmod is not None and gp_spec is not None
                            and len(gp_spec) == 4
                            and (isinstance(pmod, nn.Flatten)
                                 or type(pmod).__name__ == "FlattenNCHW")):
                        pre_flat[0] = (gp_spec[1:]
                                       if isinstance(pmod, nn.Flatten)
                                       else None)
                if isinstance(mod, (nn.Sequential, nn.Concat, nn.Graph)):
                    if len(bottoms) > 1:
                        raise NotImplementedError(
                            "caffe export: container graph node with "
                            "multiple parents")
                    tops[id(node)] = walk(mod, sub, substate, bottoms[0])
                else:
                    tops[id(node)] = emit(mod, sub, bottoms, substate)
                    if len(bottoms) == 1:
                        _advance_spec(mod, sub, substate)
                    else:
                        # _advance_spec feeds one spec; n-ary ops need
                        # their own propagation rules
                        in_specs = [specs.get(id(p)) for p in node.inputs]
                        if isinstance(mod, (nn.CAddTable, nn.CMulTable,
                                            nn.CMaxTable)):
                            cur_spec[0] = in_specs[0]
                        elif (isinstance(mod, nn.JoinTable)
                                and all(in_specs)):
                            d = mod.dimension % len(in_specs[0])
                            joined = list(in_specs[0])
                            joined[d] = sum(s[d] for s in in_specs)
                            cur_spec[0] = tuple(joined)
                        else:
                            cur_spec[0] = None
                specs[id(node)] = cur_spec[0]
            outs = [tops[id(o)] for o in child.output_nodes]
            if len(outs) > 1 and not allow_multi:
                raise NotImplementedError(
                    "caffe export: multi-output nested graph node")
            cur_spec[0] = specs.get(id(child.output_nodes[0]))
            if len(outs) > 1:
                # the importer discovers outputs as unconsumed tops in
                # LAYER order; cap each output with an identity Power
                # layer so (a) an output that also feeds another node
                # stays an output and (b) the original output order is
                # the terminal layer order
                capped = []
                for out_top in outs:
                    l = net.layer.add()
                    l.name = unique(out_top + "_out")
                    l.type = "Power"
                    l.bottom.append(out_top)
                    l.top.append(l.name)
                    l.power_param.power = 1.0
                    l.power_param.scale = 1.0
                    l.power_param.shift = 0.0
                    capped.append(l.name)
                return capped
            return outs[0]
        out = emit(child, params, [top], state)
        _advance_spec(child, params, state)
        return out

    walk(model, model._params or {}, model._state or {}, "data",
         allow_multi=True)

    with open(prototxt_path, "w") as f:
        # definition only (blobs stripped)
        defn = caffe_pb2.NetParameter()
        defn.CopyFrom(net)
        for l in defn.layer:
            del l.blobs[:]
        f.write(text_format.MessageToString(defn))
    with open(model_path, "wb") as f:
        f.write(net.SerializeToString())


def load(model, prototxt_path, model_path, match_all=True):
    """Reference-named alias of :func:`copy_weights`
    (CaffeLoader.load, CaffeLoader.scala:57)."""
    return copy_weights(model, prototxt_path, model_path, match_all)


def copy_weights(model, prototxt_path, model_path, match_all=True):
    """Copy caffemodel weights into an EXISTING model by layer name
    (reference: CaffeLoader.load -- CaffeLoader.scala:57 "load caffe model
    weights into a predefined net").  ``match_all=True`` raises when a
    caffe layer carrying weights finds no same-named installable target
    module; with ``match_all=False`` such layers are skipped.  Target
    layers with no caffe counterpart keep their initialization either way.

    The target's layers must be named after the caffe layers (as
    ``load_caffe`` names them); blob layout conversion is the import
    path's (shared ``_install_blobs`` table).  Caveat: InnerProduct blobs
    copy verbatim with caffe's (C,H,W)-ordered columns -- a hand-built
    model flattening in NHWC order (plain ``nn.Flatten``) needs the
    importer's graph path (``load_caffe``), which inserts an NCHW-ordered
    flatten.  ``prototxt_path`` mirrors the reference signature; matching
    is by name from the caffemodel alone, so it is accepted but not read.
    Returns the model.
    """
    if not model.is_built():
        raise ValueError("copy_weights expects a built model")
    wnet = _read_net(model_path, binary=True)
    blobs_by_name = {}
    for name, _, _, _, lpb in _layers(wnet):
        if lpb.blobs:
            blobs_by_name[name] = [_blob_to_array(b) for b in lpb.blobs]

    def walk(mod, params, state):
        matched = []
        name = getattr(mod, "name", None)
        if name in blobs_by_name and isinstance(params, dict):
            if _install_blobs(mod, params, state, blobs_by_name[name]):
                matched.append(name)
        topo = getattr(mod, "_topo", None)
        if topo is not None:
            for i, node in enumerate(topo):
                if node.module is not None and str(i) in params:
                    matched += walk(node.module, params[str(i)],
                                    state.get(str(i), {}))
        else:
            for i, child in enumerate(mod.children()):
                if isinstance(params, dict) and str(i) in params:
                    matched += walk(child, params[str(i)],
                                    state.get(str(i), {})
                                    if isinstance(state, dict) else {})
        return matched

    matched = walk(model, model._params, model._state)
    if match_all:
        unmatched = [m for m in blobs_by_name if m not in matched]
        if unmatched:
            raise ValueError(
                f"caffe layers with no installable target module "
                f"(matchAll=True, reference CaffeLoader semantics): "
                f"{unmatched}")
    return model
