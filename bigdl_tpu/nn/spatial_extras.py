"""Spatial, temporal and volumetric layer extras.

Reference: nn/SpatialZeroPadding.scala, Cropping{2D,3D}.scala,
UpSampling{1D,2D,3D}.scala, ResizeBilinear.scala,
SpatialSeparableConvolution.scala, SpatialShareConvolution.scala,
SpatialWithinChannelLRN.scala, SpatialSubtractiveNormalization.scala,
SpatialDivisiveNormalization.scala, SpatialContrastiveNormalization.scala,
RoiPooling.scala, TemporalMaxPooling.scala,
Volumetric{Convolution,MaxPooling,AveragePooling,FullConvolution}.scala.

Layout: NHWC for 2-D, NDHWC for 3-D (TPU-native); reference is NCHW/NCDHW.
"""

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.conv import SpatialConvolution
from bigdl_tpu.nn.initialization import Xavier
from bigdl_tpu.nn.module import Module, child_rng


class SpatialZeroPadding(Module):
    """Zero-pad H/W (reference: nn/SpatialZeroPadding.scala; negatives
    crop)."""

    def __init__(self, pad_left, pad_right=None, pad_top=None,
                 pad_bottom=None, name=None):
        super().__init__(name)
        self.pads = (pad_left,
                     pad_left if pad_right is None else pad_right,
                     pad_left if pad_top is None else pad_top,
                     pad_left if pad_bottom is None else pad_bottom)

    def apply(self, params, state, input, *, training=False, rng=None):
        l, r, t, b = self.pads
        x = input
        if min(self.pads) < 0:
            h, w = x.shape[1], x.shape[2]
            x = x[:, max(-t, 0):h - max(-b, 0),
                  max(-l, 0):w - max(-r, 0), :]
        cfg = [(0, 0), (max(t, 0), max(b, 0)), (max(l, 0), max(r, 0)),
               (0, 0)]
        return jnp.pad(x, cfg), state


class Cropping2D(Module):
    """Crop H/W (reference: nn/Cropping2D.scala)."""

    def __init__(self, height_crop=(0, 0), width_crop=(0, 0), name=None):
        super().__init__(name)
        self.hc, self.wc = tuple(height_crop), tuple(width_crop)

    def apply(self, params, state, input, *, training=False, rng=None):
        h, w = input.shape[1], input.shape[2]
        return input[:, self.hc[0]:h - self.hc[1],
                     self.wc[0]:w - self.wc[1], :], state


class Cropping3D(Module):
    """Crop D/H/W of NDHWC (reference: nn/Cropping3D.scala)."""

    def __init__(self, dim1_crop=(0, 0), dim2_crop=(0, 0), dim3_crop=(0, 0),
                 name=None):
        super().__init__(name)
        self.c1, self.c2, self.c3 = (tuple(dim1_crop), tuple(dim2_crop),
                                     tuple(dim3_crop))

    def apply(self, params, state, input, *, training=False, rng=None):
        d, h, w = input.shape[1], input.shape[2], input.shape[3]
        return input[:, self.c1[0]:d - self.c1[1],
                     self.c2[0]:h - self.c2[1],
                     self.c3[0]:w - self.c3[1], :], state


class UpSampling1D(Module):
    """Repeat timesteps ``length`` times (reference: nn/UpSampling1D.scala)."""

    def __init__(self, length=2, name=None):
        super().__init__(name)
        self.length = length

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.repeat(input, self.length, axis=1), state


class UpSampling2D(Module):
    """Nearest-neighbour upsample H/W (reference: nn/UpSampling2D.scala)."""

    def __init__(self, size=(2, 2), name=None):
        super().__init__(name)
        self.size = tuple(size)

    def apply(self, params, state, input, *, training=False, rng=None):
        x = jnp.repeat(input, self.size[0], axis=1)
        return jnp.repeat(x, self.size[1], axis=2), state


class UpSampling3D(Module):
    """Nearest-neighbour upsample D/H/W (reference: nn/UpSampling3D.scala)."""

    def __init__(self, size=(2, 2, 2), name=None):
        super().__init__(name)
        self.size = tuple(size)

    def apply(self, params, state, input, *, training=False, rng=None):
        x = jnp.repeat(input, self.size[0], axis=1)
        x = jnp.repeat(x, self.size[1], axis=2)
        return jnp.repeat(x, self.size[2], axis=3), state


class ResizeBilinear(Module):
    """Bilinear resize to (out_height, out_width)
    (reference: nn/ResizeBilinear.scala; align_corners semantics)."""

    def __init__(self, out_height, out_width, align_corners=False,
                 name=None):
        super().__init__(name)
        self.out_hw = (out_height, out_width)
        self.align_corners = align_corners

    def apply(self, params, state, input, *, training=False, rng=None):
        n, _, _, c = input.shape
        if self.align_corners:
            h, w = input.shape[1], input.shape[2]
            oh, ow = self.out_hw
            ys = jnp.linspace(0, h - 1, oh)
            xs = jnp.linspace(0, w - 1, ow)
            y0 = jnp.floor(ys).astype(jnp.int32)
            x0 = jnp.floor(xs).astype(jnp.int32)
            y1 = jnp.minimum(y0 + 1, h - 1)
            x1 = jnp.minimum(x0 + 1, w - 1)
            wy = (ys - y0)[None, :, None, None]
            wx = (xs - x0)[None, None, :, None]
            g = input
            out = ((1 - wy) * (1 - wx) * g[:, y0][:, :, x0]
                   + (1 - wy) * wx * g[:, y0][:, :, x1]
                   + wy * (1 - wx) * g[:, y1][:, :, x0]
                   + wy * wx * g[:, y1][:, :, x1])
            return out, state
        out = jax.image.resize(input, (n,) + self.out_hw + (c,), "bilinear")
        return out, state


class SpatialShareConvolution(SpatialConvolution):
    """Alias of SpatialConvolution: the reference variant shares im2col
    buffers across replicas (nn/SpatialShareConvolution.scala), a concern
    XLA's buffer assignment makes moot."""


class SpatialSeparableConvolution(Module):
    """Depthwise conv (multiplier per channel) + 1x1 pointwise
    (reference: nn/SpatialSeparableConvolution.scala)."""

    def __init__(self, n_input_channel, n_output_channel, depth_multiplier,
                 kernel_w, kernel_h, stride_w=1, stride_h=1, pad_w=0,
                 pad_h=0, with_bias=True, name=None):
        super().__init__(name)
        self.cin = n_input_channel
        self.cout = n_output_channel
        self.mult = depth_multiplier
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.with_bias = with_bias

    def setup(self, rng, input_spec):
        kh, kw = self.kernel
        mid = self.cin * self.mult
        dw = Xavier().init(child_rng(rng, 0), (kh, kw, 1, mid),
                           kh * kw, self.mult)
        pw = Xavier().init(child_rng(rng, 1), (1, 1, mid, self.cout),
                           mid, self.cout)
        params = {"depth_weight": dw, "point_weight": pw}
        if self.with_bias:
            params["bias"] = jnp.zeros((self.cout,), jnp.float32)
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        ph, pw_ = self.pad
        y = lax.conv_general_dilated(
            input, params["depth_weight"].astype(input.dtype),
            self.stride, [(ph, ph), (pw_, pw_)],
            feature_group_count=self.cin,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = lax.conv_general_dilated(
            y, params["point_weight"].astype(y.dtype), (1, 1),
            [(0, 0), (0, 0)], dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state


def _spatial_avg_window(x, size):
    """Mean over a size x size spatial window, SAME padding, per channel."""
    dims, strides = (1, size, size, 1), (1, 1, 1, 1)
    total = lax.reduce_window(x, 0.0, lax.add, dims, strides, "SAME")
    count = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims, strides,
                              "SAME")
    return total / count


class SpatialWithinChannelLRN(Module):
    """LRN over a spatial window within each channel
    (reference: nn/SpatialWithinChannelLRN.scala)."""

    def __init__(self, size=5, alpha=1.0, beta=0.75, name=None):
        super().__init__(name)
        self.size, self.alpha, self.beta = size, alpha, beta

    def apply(self, params, state, input, *, training=False, rng=None):
        x32 = input.astype(jnp.float32)
        mean_sq = _spatial_avg_window(jnp.square(x32), self.size)
        denom = jnp.power(1.0 + self.alpha * mean_sq, self.beta)
        return (x32 / denom).astype(input.dtype), state


class SpatialSubtractiveNormalization(Module):
    """Subtract the local (kernel-weighted) mean
    (reference: nn/SpatialSubtractiveNormalization.scala; uniform kernel)."""

    def __init__(self, n_input_plane=1, kernel_size=9, name=None):
        super().__init__(name)
        self.size = kernel_size

    def apply(self, params, state, input, *, training=False, rng=None):
        return input - _spatial_avg_window(input, self.size), state


class SpatialDivisiveNormalization(Module):
    """Divide by the local std (reference:
    nn/SpatialDivisiveNormalization.scala; threshold at the global mean
    std like the reference)."""

    def __init__(self, n_input_plane=1, kernel_size=9, threshold=1e-4,
                 name=None):
        super().__init__(name)
        self.size = kernel_size
        self.threshold = threshold

    def apply(self, params, state, input, *, training=False, rng=None):
        local_sq = _spatial_avg_window(jnp.square(input), self.size)
        local_std = jnp.sqrt(jnp.maximum(local_sq, 0.0))
        mean_std = jnp.mean(local_std, axis=(1, 2, 3), keepdims=True)
        denom = jnp.maximum(jnp.maximum(local_std, mean_std), self.threshold)
        return input / denom, state


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive normalization
    (reference: nn/SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane=1, kernel_size=9, threshold=1e-4,
                 name=None):
        super().__init__(name)
        self.sub = SpatialSubtractiveNormalization(n_input_plane,
                                                   kernel_size)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel_size,
                                                threshold)

    def apply(self, params, state, input, *, training=False, rng=None):
        y, _ = self.sub.apply((), (), input)
        return self.div.apply((), (), y)[0], state


class RoiPooling(Module):
    """ROI max pooling: (features NHWC, rois (R, 5) [batch, x1, y1, x2, y2])
    -> (R, pooled_h, pooled_w, C) (reference: nn/RoiPooling.scala).

    Implemented as a vectorized bin-assignment + segment max — static
    shapes, no gather loops, jit-safe.
    """

    def __init__(self, pooled_w, pooled_h, spatial_scale=1.0, name=None):
        super().__init__(name)
        self.pw, self.ph = pooled_w, pooled_h
        self.scale = spatial_scale

    def apply(self, params, state, input, *, training=False, rng=None):
        feats, rois = input
        n, h, w, c = feats.shape
        ys = jnp.arange(h)
        xs = jnp.arange(w)

        def one_roi(roi):
            b = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * self.scale)
            y1 = jnp.round(roi[2] * self.scale)
            x2 = jnp.round(roi[3] * self.scale)
            y2 = jnp.round(roi[4] * self.scale)
            rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
            rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
            in_y = (ys >= y1) & (ys <= y2)
            in_x = (xs >= x1) & (xs <= x2)
            ry = (ys - y1).astype(feats.dtype)     # row offset within roi
            rx = (xs - x1).astype(feats.dtype)
            fmap = feats[b]                        # (H, W, C)

            # Caffe bin boundaries overlap: bin i covers rows
            # [floor(i*rh/ph), ceil((i+1)*rh/ph)) -- a pixel may belong to
            # two adjacent bins (reference: nn/RoiPooling.scala semantics)
            def bin_body(i, acc):
                iy, ix = i // self.pw, i % self.pw
                y_lo = jnp.floor(iy * rh / self.ph)
                y_hi = jnp.ceil((iy + 1) * rh / self.ph)
                x_lo = jnp.floor(ix * rw / self.pw)
                x_hi = jnp.ceil((ix + 1) * rw / self.pw)
                my = in_y & (ry >= y_lo) & (ry < y_hi)
                mx = in_x & (rx >= x_lo) & (rx < x_hi)
                mask = (my[:, None] & mx[None, :])[..., None]
                val = jnp.max(jnp.where(mask, fmap, -jnp.inf), axis=(0, 1))
                val = jnp.where(jnp.isfinite(val), val, 0.0)
                return acc.at[iy, ix].set(val)

            init = jnp.zeros((self.ph, self.pw, c), fmap.dtype)
            return lax.fori_loop(0, self.ph * self.pw, bin_body, init)

        return jax.vmap(one_roi)(rois.astype(feats.dtype)), state


class TemporalMaxPooling(Module):
    """1-D max pooling over (N, T, C)
    (reference: nn/TemporalMaxPooling.scala)."""

    def __init__(self, k_w, d_w=None, name=None):
        super().__init__(name)
        self.k_w = k_w
        self.d_w = d_w or k_w

    def apply(self, params, state, input, *, training=False, rng=None):
        return lax.reduce_window(
            input, -jnp.inf, lax.max, (1, self.k_w, 1), (1, self.d_w, 1),
            "VALID"), state


class VolumetricConvolution(Module):
    """3-D convolution over NDHWC
    (reference: nn/VolumetricConvolution.scala)."""

    def __init__(self, n_input_plane, n_output_plane, k_t, k_w, k_h,
                 d_t=1, d_w=1, d_h=1, pad_t=0, pad_w=0, pad_h=0,
                 with_bias=True, name=None):
        super().__init__(name)
        self.cin, self.cout = n_input_plane, n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.with_bias = with_bias

    def setup(self, rng, input_spec):
        kt, kh, kw = self.kernel
        fan_in = self.cin * kt * kh * kw
        w = Xavier().init(rng, (kt, kh, kw, self.cin, self.cout), fan_in,
                          self.cout)
        params = {"weight": w}
        if self.with_bias:
            params["bias"] = jnp.zeros((self.cout,), jnp.float32)
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        pt, ph, pw = self.pad
        y = lax.conv_general_dilated(
            input, params["weight"].astype(input.dtype), self.stride,
            [(pt, pt), (ph, ph), (pw, pw)],
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state


class VolumetricFullConvolution(Module):
    """Transposed 3-D convolution (reference:
    nn/VolumetricFullConvolution.scala)."""

    def __init__(self, n_input_plane, n_output_plane, k_t, k_w, k_h,
                 d_t=1, d_w=1, d_h=1, pad_t=0, pad_w=0, pad_h=0,
                 adj_t=0, adj_w=0, adj_h=0, with_bias=True, name=None):
        super().__init__(name)
        self.cin, self.cout = n_input_plane, n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.adj = (adj_t, adj_h, adj_w)
        self.with_bias = with_bias

    def setup(self, rng, input_spec):
        kt, kh, kw = self.kernel
        fan_in = self.cin * kt * kh * kw
        w = Xavier().init(rng, (kt, kh, kw, self.cin, self.cout), fan_in,
                          self.cout)
        params = {"weight": w}
        if self.with_bias:
            params["bias"] = jnp.zeros((self.cout,), jnp.float32)
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        kt, kh, kw = self.kernel
        st, sh, sw = self.stride
        pt, ph, pw = self.pad
        at, ah, aw = self.adj
        # Transposed conv = conv with lhs dilation over the spatially
        # flipped kernel (same construction as SpatialFullConvolution)
        w = params["weight"].astype(input.dtype)[::-1, ::-1, ::-1, :, :]
        y = lax.conv_general_dilated(
            input, w,
            window_strides=(1, 1, 1),
            padding=((kt - 1 - pt, kt - 1 - pt + at),
                     (kh - 1 - ph, kh - 1 - ph + ah),
                     (kw - 1 - pw, kw - 1 - pw + aw)),
            lhs_dilation=(st, sh, sw),
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state


class _VolumetricPool(Module):
    def __init__(self, k_t, k_w, k_h, d_t=None, d_w=None, d_h=None,
                 pad_t=0, pad_w=0, pad_h=0, name=None):
        super().__init__(name)
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)


class VolumetricMaxPooling(_VolumetricPool):
    """3-D max pooling (reference: nn/VolumetricMaxPooling.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        kt, kh, kw = self.kernel
        st, sh, sw = self.stride
        pt, ph, pw = self.pad
        return lax.reduce_window(
            input, -jnp.inf, lax.max, (1, kt, kh, kw, 1),
            (1, st, sh, sw, 1),
            [(0, 0), (pt, pt), (ph, ph), (pw, pw), (0, 0)]), state


class VolumetricAveragePooling(_VolumetricPool):
    """3-D average pooling (reference: nn/VolumetricAveragePooling.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        kt, kh, kw = self.kernel
        st, sh, sw = self.stride
        pt, ph, pw = self.pad
        pads = [(0, 0), (pt, pt), (ph, ph), (pw, pw), (0, 0)]
        total = lax.reduce_window(input, 0.0, lax.add, (1, kt, kh, kw, 1),
                                  (1, st, sh, sw, 1), pads)
        return total / (kt * kh * kw), state
