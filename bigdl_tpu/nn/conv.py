"""Convolution layers.

Reference: nn/SpatialConvolution.scala:54 (im2col + MKL gemm,
NNPrimitive.im2col at :613-624).  TPU-native redesign: one
``lax.conv_general_dilated`` -- XLA lowers it straight onto the MXU; there is
no im2col, no layout juggling, no JNI.  Weights are stored HWIO and compute
prefers NHWC (TPU-native); an NCHW facade is kept because the reference
defaults to NCHW (nn/abstractnn/DataFormat.scala) -- conversion happens once
at the module boundary.
"""

from typing import Optional

import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.initialization import RandomUniform, Xavier, Zeros
from bigdl_tpu.nn.module import Module, child_rng


class SpatialConvolution(Module):
    """2-D convolution over NCHW or NHWC batches.

    Constructor mirrors the reference signature
    (nInputPlane, nOutputPlane, kW, kH, dW, dH, padW, padH, nGroup).
    """

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        n_group: int = 1,
        dilation_w: int = 1,
        dilation_h: int = 1,
        with_bias: bool = True,
        data_format: str = "NHWC",
        weight_init=None,
        bias_init=None,
        w_regularizer=None,
        b_regularizer=None,
        name=None,
    ):
        super().__init__(name)
        self.set_regularizer(w_regularizer, b_regularizer)
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        assert data_format in ("NHWC", "NCHW")
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.dilation = (dilation_h, dilation_w)
        self.n_group = n_group
        self.with_bias = with_bias
        self.data_format = data_format
        self.weight_init = weight_init or Xavier()
        self.bias_init = bias_init or Zeros()

    def setup(self, rng, input_spec):
        kh, kw = self.kernel
        cin_g = self.n_input_plane // self.n_group
        fan_in = cin_g * kh * kw
        fan_out = (self.n_output_plane // self.n_group) * kh * kw
        params = {
            "weight": self.weight_init.init(
                child_rng(rng, 0), (kh, kw, cin_g, self.n_output_plane),
                fan_in, fan_out,
            )
        }
        if self.with_bias:
            params["bias"] = self.bias_init.init(
                child_rng(rng, 1), (self.n_output_plane,), fan_in, fan_out
            )
        return params, ()

    def _padding(self):
        ph, pw = self.pad
        if ph == -1 and pw == -1:  # reference convention: -1 => SAME
            return "SAME"
        return ((ph, ph), (pw, pw))

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        if self.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        if "weight_q" in params:
            # post-training-quantized weights (nn/quantized): int8 conv
            # accumulation, bias in fp32, cast back to the input dtype
            from bigdl_tpu.nn.quantized import int8_conv

            y = int8_conv(x, params["weight_q"], params["scale"],
                          stride=self.stride, padding=self._padding(),
                          dilation=self.dilation, groups=self.n_group)
            if self.with_bias:
                y = y + params["bias"]
            y = y.astype(input.dtype)
        else:
            y = lax.conv_general_dilated(
                x,
                params["weight"].astype(x.dtype),
                window_strides=self.stride,
                padding=self._padding(),
                rhs_dilation=self.dilation,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=self.n_group,
            )
            if self.with_bias:
                y = y + params["bias"].astype(y.dtype)
        if self.data_format == "NCHW":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y, state


class SpaceToDepthStem(SpatialConvolution):
    """Stride-2 odd-kernel conv computed over a 2x2 space-to-depth input.

    The MLPerf-TPU "conv0" trick, TPU-first and no reference analogue: a
    7x7/s2 conv on a 3-channel image leaves most of the MXU contraction
    idle (7*7*3 = 147 tiny channels at 224x224).  Packing each 2x2 pixel
    block into channels turns it into an equivalent 4x4/s1 conv on
    112x112x12 -- bigger contraction, quarter the spatial positions,
    friendlier layout.

    Parameters are byte-identical to the plain ``SpatialConvolution``
    stem (weight ``[k, k, cin, cout]``, same init): the space-to-depth
    reshape of BOTH input and weight happens inside ``apply``, so
    checkpoints, serialization and the param count are interchangeable
    with the standard stem.  Equivalence is pinned by
    tests/test_conv.py::test_space_to_depth_stem_equivalence.

    Requires: square odd kernel, stride 2, pad (k-1)//2 with k % 4 == 3
    (so the padded offset lands on a block boundary: 7x7/pad 3 is the
    ResNet stem), even H/W, no groups/dilation.
    """

    def __init__(self, n_input_plane, n_output_plane, kernel=7, **kw):
        kw.setdefault("with_bias", False)
        super().__init__(
            n_input_plane, n_output_plane, kernel, kernel, 2, 2,
            (kernel - 1) // 2, (kernel - 1) // 2, **kw)
        kh, kw_ = self.kernel
        assert kh == kw_ and kh % 4 == 3, "kernel must be odd with pad+1 even"
        assert self.n_group == 1 and self.dilation == (1, 1)

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        if self.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        n, h, w_sz, c = x.shape
        assert h % 2 == 0 and w_sz % 2 == 0, "space-to-depth needs even H/W"
        x = (x.reshape(n, h // 2, 2, w_sz // 2, 2, c)
              .transpose(0, 1, 3, 2, 4, 5)
              .reshape(n, h // 2, w_sz // 2, 4 * c))
        wgt = params["weight"]                       # [k, k, c, o]
        k, o = wgt.shape[0], wgt.shape[-1]
        kb = (k + 1) // 2
        # zero row/col at the top-left aligns the k-tap window onto 2x2
        # blocks; splitting each padded axis as (block, in-block) then
        # regrouping gives the equivalent block-space kernel
        wgt = jnp.pad(wgt, ((1, 0), (1, 0), (0, 0), (0, 0)))
        wgt = (wgt.reshape(kb, 2, kb, 2, c, o)
                  .transpose(0, 2, 1, 3, 4, 5)
                  .reshape(kb, kb, 4 * c, o))
        pb = (self.pad[0] + 1) // 2
        pa = kb - 1 - pb
        y = lax.conv_general_dilated(
            x, wgt.astype(x.dtype), window_strides=(1, 1),
            padding=((pb, pa), (pb, pa)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        if self.data_format == "NCHW":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y, state


class SpatialDilatedConvolution(SpatialConvolution):
    """Reference: nn/SpatialDilatedConvolution.scala."""

    def __init__(
        self, n_input_plane, n_output_plane, kernel_w, kernel_h,
        stride_w=1, stride_h=1, pad_w=0, pad_h=0,
        dilation_w=1, dilation_h=1, **kw,
    ):
        super().__init__(
            n_input_plane, n_output_plane, kernel_w, kernel_h, stride_w,
            stride_h, pad_w, pad_h, dilation_w=dilation_w,
            dilation_h=dilation_h, **kw,
        )


class SpatialFullConvolution(Module):
    """Transposed convolution (reference: nn/SpatialFullConvolution.scala).

    Implemented with input dilation (``lhs_dilation``) so XLA emits the
    canonical transposed-conv HLO for the MXU.
    """

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        adj_w: int = 0,
        adj_h: int = 0,
        with_bias: bool = True,
        data_format: str = "NHWC",
        weight_init=None,
        bias_init=None,
        name=None,
    ):
        super().__init__(name)
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.adj = (adj_h, adj_w)
        self.with_bias = with_bias
        self.data_format = data_format
        self.weight_init = weight_init or Xavier()
        self.bias_init = bias_init or Zeros()

    def setup(self, rng, input_spec):
        kh, kw = self.kernel
        fan_in = self.n_input_plane * kh * kw
        fan_out = self.n_output_plane * kh * kw
        params = {
            "weight": self.weight_init.init(
                child_rng(rng, 0), (kh, kw, self.n_input_plane, self.n_output_plane),
                fan_in, fan_out,
            )
        }
        if self.with_bias:
            params["bias"] = self.bias_init.init(
                child_rng(rng, 1), (self.n_output_plane,), fan_in, fan_out
            )
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        if self.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        ah, aw = self.adj
        # Transposed conv = conv with lhs dilation; padding chosen so the
        # output size is s*(i-1) + k - 2p + adj, matching the reference.
        pad = ((kh - 1 - ph, kh - 1 - ph + ah), (kw - 1 - pw, kw - 1 - pw + aw))
        w = params["weight"].astype(x.dtype)
        # Flip spatial dims: transposed conv correlates with the flipped kernel.
        w = w[::-1, ::-1, :, :]
        y = lax.conv_general_dilated(
            x, w,
            window_strides=(1, 1),
            padding=pad,
            lhs_dilation=(sh, sw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        if self.data_format == "NCHW":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y, state


class Conv1D(Module):
    """Temporal convolution over (N, T, C) (reference: nn/TemporalConvolution.scala)."""

    def __init__(
        self, input_frame_size, output_frame_size, kernel_w, stride_w=1,
        pad_w=0, with_bias=True, weight_init=None, bias_init=None, name=None,
    ):
        super().__init__(name)
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.pad_w = pad_w
        self.with_bias = with_bias
        self.weight_init = weight_init or Xavier()
        self.bias_init = bias_init or Zeros()

    def setup(self, rng, input_spec):
        fan_in = self.input_frame_size * self.kernel_w
        fan_out = self.output_frame_size * self.kernel_w
        params = {
            "weight": self.weight_init.init(
                child_rng(rng, 0),
                (self.kernel_w, self.input_frame_size, self.output_frame_size),
                fan_in, fan_out,
            )
        }
        if self.with_bias:
            params["bias"] = self.bias_init.init(
                child_rng(rng, 1), (self.output_frame_size,), fan_in, fan_out
            )
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        # pad_w == -1 means SAME (same convention as SpatialConvolution)
        pad = "SAME" if self.pad_w == -1 else ((self.pad_w, self.pad_w),)
        y = lax.conv_general_dilated(
            input,
            params["weight"].astype(input.dtype),
            window_strides=(self.stride_w,),
            padding=pad,
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state


TemporalConvolution = Conv1D


class SpatialConvolutionMap(Module):
    """Convolution over a generic input->output connection table
    (reference: nn/SpatialConvolutionMap.scala; Torch's legacy
    nn.SpatialConvolutionMap).

    ``conn_table``: ``(n_connections, 2)`` array of 0-BASED
    ``[input_feature, output_feature]`` pairs (the pyspark compat layer
    shifts Torch's 1-based tables down).  Parameters follow the Torch
    layout -- one ``(kh, kw)`` kernel per CONNECTION plus one bias per
    output plane -- and apply scatters them into a dense ``(kh, kw,
    n_in, n_out)`` kernel for ONE full conv: the MXU-friendly
    formulation of a sparse connection pattern (zeros contribute
    nothing, gradients flow only to the scattered taps).
    """

    def __init__(self, conn_table, kernel_w, kernel_h, stride_w=1,
                 stride_h=1, pad_w=0, pad_h=0, data_format="NHWC",
                 w_regularizer=None, b_regularizer=None, name=None):
        super().__init__(name)
        self.set_regularizer(w_regularizer, b_regularizer)
        import numpy as _np
        table = _np.asarray(conn_table, _np.int64).reshape(-1, 2)
        self.conn_in = tuple(int(i) for i in table[:, 0])
        self.conn_out = tuple(int(o) for o in table[:, 1])
        self.n_input_plane = max(self.conn_in) + 1
        self.n_output_plane = max(self.conn_out) + 1
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        assert data_format in ("NHWC", "NCHW")
        self.data_format = data_format

    def setup(self, rng, input_spec):
        kh, kw = self.kernel
        n_conn = len(self.conn_in)
        # Torch reset: stdv over the per-OUTPUT fan-in (nInputPlane of a
        # full table); use the busiest output's connection count
        fan = kh * kw * max(
            sum(1 for o in self.conn_out if o == out)
            for out in set(self.conn_out))
        init = RandomUniform(-1.0 / fan ** 0.5, 1.0 / fan ** 0.5)
        return {
            "weight": init.init(child_rng(rng, 0), (n_conn, kh, kw),
                                fan, fan),
            "bias": init.init(child_rng(rng, 1), (self.n_output_plane,),
                              fan, fan),
        }, ()

    def _padding(self):
        ph, pw = self.pad
        if ph == -1 and pw == -1:  # reference convention: -1 => SAME
            return "SAME"
        return ((ph, ph), (pw, pw))

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        if self.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        kh, kw = self.kernel
        dense = jnp.zeros((kh, kw, self.n_input_plane, self.n_output_plane),
                          params["weight"].dtype)
        dense = dense.at[:, :, jnp.asarray(self.conn_in),
                         jnp.asarray(self.conn_out)].set(
            jnp.moveaxis(params["weight"], 0, -1))
        y = lax.conv_general_dilated(
            x, dense.astype(x.dtype),
            window_strides=self.stride,
            padding=self._padding(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = y + params["bias"].astype(y.dtype)
        if self.data_format == "NCHW":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y, state
