"""Recurrent stack: cells unrolled with lax.scan.

Reference: nn/Recurrent.scala:47 (container unrolling a Cell over time),
nn/Cell.scala:48, nn/LSTM.scala, nn/GRU.scala, nn/RnnCell.scala,
nn/BiRecurrent.scala, nn/RecurrentDecoder.scala, nn/TimeDistributed.scala,
nn/MultiRNNCell.scala.

TPU-native: the reference clones the cell per timestep and iterates in Scala
(Recurrent.scala:66); here the unroll is one ``lax.scan`` -- a single fused
XLA while-loop whose body is the (MXU-friendly, batched) cell matmul.  Gate
layouts follow torch (i,f,g,o / r,z,n) so goldens compare directly.

Inputs are batch-first (N, T, F), matching the reference's default
``batchNormParams``-free layout.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.initialization import RandomUniform
from bigdl_tpu.nn.module import Container, Module, child_rng


class Cell(Module):
    """Single-timestep recurrence (reference: nn/Cell.scala:48).

    Contract: ``init_hidden`` builds the h0 pytree; ``step`` advances one
    timestep.  ``apply`` runs one step on (x_t, hidden) tables so a Cell is
    also usable standalone, as in the reference.
    """

    hidden_size: int
    p: float = 0.0          # in-cell dropout prob (reference LSTM.scala:57)

    def init_hidden(self, batch_size, dtype=jnp.float32):
        raise NotImplementedError

    def step(self, params, x_t, hidden, drop_key=None):
        """-> (output_t, new_hidden); ``drop_key`` is a per-timestep PRNG
        key, passed only when training with in-cell dropout (p > 0)."""
        raise NotImplementedError

    def _gate_matmul(self, x, weight, n_gates, drop_key):
        """x @ weight.T computed per GATE with an independent dropout
        mask on x for each gate (reference LSTM.scala:93-106: four
        Dropout(p) nodes feeding four Linears).  With drop_key None the
        fused single matmul is used."""
        dt = x.dtype
        w = weight.astype(dt)
        if drop_key is None or self.p <= 0.0:
            return x @ w.T
        h = w.shape[0] // n_gates
        keep = 1.0 - self.p
        masks = jax.random.bernoulli(
            drop_key, keep, (n_gates,) + x.shape).astype(dt) / keep
        wg = w.reshape(n_gates, h, w.shape[1])
        # (g,N,i) x (g,h,i) -> (N, g*h), matching the fused layout
        out = jnp.einsum("gni,ghi->ngh", x[None] * masks, wg)
        return out.reshape(x.shape[0], n_gates * h)

    def apply(self, params, state, input, *, training=False, rng=None):
        x_t, hidden = input
        drop_key = (rng if training and rng is not None and self.p > 0.0
                    else None)
        out, new_hidden = self.step(params, x_t, hidden, drop_key=drop_key)
        return (out, new_hidden), state


class RnnCell(Cell):
    """Vanilla tanh/relu RNN cell (reference: nn/RnnCell.scala)."""

    def __init__(self, input_size, hidden_size, activation=jnp.tanh, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation

    def setup(self, rng, input_spec):
        init = RandomUniform()
        h, i = self.hidden_size, self.input_size
        return {
            "weight_ih": init.init(child_rng(rng, 0), (h, i), h, h),
            "weight_hh": init.init(child_rng(rng, 1), (h, h), h, h),
            "bias_ih": init.init(child_rng(rng, 2), (h,), h, h),
            "bias_hh": init.init(child_rng(rng, 3), (h,), h, h),
        }, ()

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def step(self, params, x_t, h, drop_key=None):
        pre = (x_t @ params["weight_ih"].astype(x_t.dtype).T
               + params["bias_ih"].astype(x_t.dtype)
               + h @ params["weight_hh"].astype(x_t.dtype).T
               + params["bias_hh"].astype(x_t.dtype))
        h_new = self.activation(pre)
        return h_new, h_new


class LSTM(Cell):
    """LSTM cell, gate order i,f,g,o (reference: nn/LSTM.scala)."""

    def __init__(self, input_size, hidden_size, p=0.0, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.p = float(p)

    def setup(self, rng, input_spec):
        init = RandomUniform()
        h, i = self.hidden_size, self.input_size
        return {
            "weight_ih": init.init(child_rng(rng, 0), (4 * h, i), h, h),
            "weight_hh": init.init(child_rng(rng, 1), (4 * h, h), h, h),
            "bias_ih": init.init(child_rng(rng, 2), (4 * h,), h, h),
            "bias_hh": init.init(child_rng(rng, 3), (4 * h,), h, h),
        }, ()

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return (jnp.zeros((batch_size, self.hidden_size), dtype),
                jnp.zeros((batch_size, self.hidden_size), dtype))

    def step(self, params, x_t, hidden, drop_key=None):
        h, c = hidden
        dt = x_t.dtype
        ki = kh = None
        if drop_key is not None:
            ki, kh = jax.random.split(drop_key)
        gates = (self._gate_matmul(x_t, params["weight_ih"], 4, ki)
                 + params["bias_ih"].astype(dt)
                 + self._gate_matmul(h, params["weight_hh"], 4, kh)
                 + params["bias_hh"].astype(dt))
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class GRU(Cell):
    """GRU cell, gate order r,z,n (reference: nn/GRU.scala).

    ``reset_after=True`` (default): n = tanh(Wx + b_i + r*(Uh + b_h)) --
    the torch / keras reset_after=True convention.
    ``reset_after=False``: n = tanh(Wx + b_i + U(r*h) + b_h) -- the
    keras-1 / keras reset_after=False convention (reset gate applied
    BEFORE the recurrent matmul).  The two differ whenever U is not
    diagonal, so importers must match the source convention.
    """

    def __init__(self, input_size, hidden_size, p=0.0, reset_after=True,
                 name=None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.p = float(p)
        self.reset_after = reset_after

    def setup(self, rng, input_spec):
        init = RandomUniform()
        h, i = self.hidden_size, self.input_size
        return {
            "weight_ih": init.init(child_rng(rng, 0), (3 * h, i), h, h),
            "weight_hh": init.init(child_rng(rng, 1), (3 * h, h), h, h),
            "bias_ih": init.init(child_rng(rng, 2), (3 * h,), h, h),
            "bias_hh": init.init(child_rng(rng, 3), (3 * h,), h, h),
        }, ()

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def step(self, params, x_t, h, drop_key=None):
        dt = x_t.dtype
        nh = self.hidden_size
        ki = kh = None
        if drop_key is not None:
            ki, kh = jax.random.split(drop_key)
        gi = (self._gate_matmul(x_t, params["weight_ih"], 3, ki)
              + params["bias_ih"].astype(dt))
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        W_hh = params["weight_hh"]
        b_hh = params["bias_hh"].astype(dt)
        if self.reset_after:
            gh = self._gate_matmul(h, W_hh, 3, kh) + b_hh
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
        else:
            kh1 = kh2 = None
            if kh is not None:
                kh1, kh2 = jax.random.split(kh)
            gh = (self._gate_matmul(h, W_hh[: 2 * nh], 2, kh1)
                  + b_hh[: 2 * nh])
            h_r, h_z = jnp.split(gh, 2, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n
                         + self._gate_matmul(r * h, W_hh[2 * nh:], 1, kh2)
                         + b_hh[2 * nh:])
        h_new = (1.0 - z) * n + z * h
        return h_new, h_new


class MultiRNNCell(Cell):
    """Stacked cells acting as one (reference: nn/MultiRNNCell.scala)."""

    def __init__(self, cells, name=None):
        super().__init__(name)
        self.cells = cells
        self.hidden_size = cells[-1].hidden_size

    def children(self):
        return list(self.cells)

    @property
    def p(self):
        # any inner cell with dropout makes the stack dropout-bearing,
        # so Recurrent threads per-timestep keys through
        return max((getattr(c, "p", 0.0) for c in self.cells), default=0.0)

    def setup(self, rng, input_spec):
        params = {}
        for i, c in enumerate(self.cells):
            p, _ = c.setup(child_rng(rng, i), input_spec)
            params[str(i)] = p
        return params, ()

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return tuple(c.init_hidden(batch_size, dtype) for c in self.cells)

    def step(self, params, x_t, hidden, drop_key=None):
        keys = (jax.random.split(drop_key, len(self.cells))
                if drop_key is not None else [None] * len(self.cells))
        new_hidden = []
        out = x_t
        for i, c in enumerate(self.cells):
            out, h = c.step(params[str(i)], out, hidden[i],
                            drop_key=keys[i])
            new_hidden.append(h)
        return out, tuple(new_hidden)


class Recurrent(Container):
    """Unroll a Cell over the time axis with lax.scan
    (reference: nn/Recurrent.scala:47,66).

    input (N, T, F) -> output (N, T, H).
    """

    def __init__(self, cell: Optional[Cell] = None, reverse=False,
                 name=None):
        # cell may arrive via .add() instead (the reference pyspark
        # pattern ``Recurrent().add(LSTM(...))``, Recurrent.scala addAll)
        super().__init__(name)
        self.cell = None
        self.reverse = reverse
        if cell is not None:
            self.add(cell)          # registers as the Container child too

    def add(self, module):
        if self.cell is None:
            self.cell = module
        elif module is self.cell:
            return self                 # idempotent: already held
        else:
            raise ValueError("Recurrent holds exactly ONE cell")
        return super().add(module)

    def _param_child_items(self, params):
        # setup() returns the CELL's params directly (no index level),
        # like MapTable -- route the whole subtree to it for the
        # frozen-mask walk
        return [(None, self.cell)] if self.cell is not None else []

    def setup(self, rng, input_spec):
        if self.cell is None:
            raise ValueError("Recurrent needs a cell: Recurrent(cell) "
                             "or Recurrent().add(cell)")
        xt_spec = jax.ShapeDtypeStruct(
            (input_spec.shape[0],) + input_spec.shape[2:], input_spec.dtype)
        return self.cell.setup(rng, xt_spec)

    def apply(self, params, state, input, *, training=False, rng=None):
        n = input.shape[0]
        xs = jnp.swapaxes(input, 0, 1)  # (T, N, F)
        if self.reverse:
            xs = xs[::-1]
        h0 = self.cell.init_hidden(n, input.dtype)

        use_drop = (training and rng is not None
                    and getattr(self.cell, "p", 0.0) > 0.0)
        if use_drop:
            keys = jax.random.split(rng, xs.shape[0])

            def body(h, xk):
                x_t, k = xk
                out, h_new = self.cell.step(params, x_t, h, drop_key=k)
                return h_new, out

            _, outs = jax.lax.scan(body, h0, (xs, keys))
        else:
            def body(h, x_t):
                out, h_new = self.cell.step(params, x_t, h)
                return h_new, out

            _, outs = jax.lax.scan(body, h0, xs)
        if self.reverse:
            outs = outs[::-1]
        return jnp.swapaxes(outs, 0, 1), state


class BiRecurrent(Container):
    """Bidirectional unroll, merged by concat or sum
    (reference: nn/BiRecurrent.scala)."""

    def __init__(self, fwd_cell: Cell, bwd_cell: Cell, merge="concat", name=None):
        super().__init__(name)
        self.fwd = Recurrent(fwd_cell)
        self.bwd = Recurrent(bwd_cell, reverse=True)
        self.merge = merge
        self.add(self.fwd)
        self.add(self.bwd)

    def setup(self, rng, input_spec):
        pf, _ = self.fwd.setup(child_rng(rng, 0), input_spec)
        pb, _ = self.bwd.setup(child_rng(rng, 1), input_spec)
        return {"fwd": pf, "bwd": pb}, ()

    def _param_child_items(self, params):
        return [("fwd", self.fwd), ("bwd", self.bwd)]

    def apply(self, params, state, input, *, training=False, rng=None):
        rf = rb = None
        if rng is not None:
            rf, rb = jax.random.split(rng)
        yf, _ = self.fwd.apply(params["fwd"], (), input, training=training,
                               rng=rf)
        yb, _ = self.bwd.apply(params["bwd"], (), input, training=training,
                               rng=rb)
        if self.merge == "concat":
            return jnp.concatenate([yf, yb], axis=-1), state
        return yf + yb, state


class RecurrentDecoder(Container):
    """Autoregressive unroll feeding output back as input
    (reference: nn/RecurrentDecoder.scala).

    input (N, F) = first-step input; output (N, seq_length, F).
    Requires cell output size == input size.
    """

    def __init__(self, cell: Cell, seq_length: int, name=None):
        super().__init__(name)
        self.cell = cell
        self.seq_length = seq_length
        self.add(cell)

    def _param_child_items(self, params):
        # setup() returns the cell's params directly
        return [(None, self.cell)]

    def setup(self, rng, input_spec):
        return self.cell.setup(rng, input_spec)

    def apply(self, params, state, input, *, training=False, rng=None):
        h0 = self.cell.init_hidden(input.shape[0], input.dtype)
        use_drop = (training and rng is not None
                    and getattr(self.cell, "p", 0.0) > 0.0)
        if use_drop:
            keys = jax.random.split(rng, self.seq_length)

            def body(carry, k):
                x, h = carry
                out, h_new = self.cell.step(params, x, h, drop_key=k)
                return (out, h_new), out

            _, outs = jax.lax.scan(body, (input, h0), keys)
        else:
            def body(carry, _):
                x, h = carry
                out, h_new = self.cell.step(params, x, h)
                return (out, h_new), out

            _, outs = jax.lax.scan(body, (input, h0), None,
                                   length=self.seq_length)
        return jnp.swapaxes(outs, 0, 1), state


class TimeDistributed(Container):
    """Apply an inner module independently at each timestep
    (reference: nn/TimeDistributed.scala).  Implemented as a (N*T, ...)
    reshape so the inner matmul stays one big MXU-friendly batch instead of a
    scan."""

    def __init__(self, module: Module, name=None):
        super().__init__(name)
        self.module = module
        self.add(module)

    def _param_child_items(self, params):
        # setup() returns the inner module's params directly
        return [(None, self.module)]

    def setup(self, rng, input_spec):
        inner = jax.ShapeDtypeStruct(
            (input_spec.shape[0] * input_spec.shape[1],) + input_spec.shape[2:],
            input_spec.dtype)
        return self.module.setup(rng, inner)

    def apply(self, params, state, input, *, training=False, rng=None):
        n, t = input.shape[0], input.shape[1]
        flat = input.reshape((n * t,) + input.shape[2:])
        y, new_state = self.module.apply(params, state, flat,
                                         training=training, rng=rng)
        return y.reshape((n, t) + y.shape[1:]), new_state


class LSTMPeephole(Cell):
    """LSTM with peephole connections (reference: nn/LSTMPeephole.scala:29).

    Each of the input/forget/output gates additionally sees the *previous*
    cell state through a learned diagonal (per-unit) weight -- the CMul in
    buildGate (LSTMPeephole.scala:109).  Gate order i, f, g, o as in the
    reference's narrow offsets (:120-136).
    """

    def __init__(self, input_size, hidden_size, with_peephole=True, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.with_peephole = with_peephole

    def setup(self, rng, input_spec):
        init = RandomUniform()
        h, i = self.hidden_size, self.input_size
        params = {
            "weight_ih": init.init(child_rng(rng, 0), (4 * h, i), h, h),
            "weight_hh": init.init(child_rng(rng, 1), (4 * h, h), h, h),
            "bias": init.init(child_rng(rng, 2), (4 * h,), h, h),
        }
        if self.with_peephole:
            params["peep_i"] = jnp.zeros((h,), jnp.float32)
            params["peep_f"] = jnp.zeros((h,), jnp.float32)
            params["peep_o"] = jnp.zeros((h,), jnp.float32)
        return params, ()

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return (jnp.zeros((batch_size, self.hidden_size), dtype),
                jnp.zeros((batch_size, self.hidden_size), dtype))

    def step(self, params, x_t, hidden, drop_key=None):
        h, c = hidden
        dt = x_t.dtype
        gates = (x_t @ params["weight_ih"].astype(dt).T
                 + h @ params["weight_hh"].astype(dt).T
                 + params["bias"].astype(dt))
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if self.with_peephole:
            i = i + c * params["peep_i"].astype(dt)
            f = f + c * params["peep_f"].astype(dt)
            o = o + c * params["peep_o"].astype(dt)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class _ConvLSTMBase(Cell):
    """Shared conv-LSTM machinery for 2-D and 3-D variants."""

    ndim: int  # spatial dims

    def __init__(self, input_size, output_size, kernel_i, kernel_c,
                 stride=1, with_peephole=True, name=None):
        super().__init__(name)
        assert stride == 1, "SAME-padding conv-LSTM keeps spatial dims (stride 1)"
        self.input_size = input_size
        self.output_size = output_size
        self.hidden_size = output_size
        self.kernel_i = kernel_i
        self.kernel_c = kernel_c
        self.with_peephole = with_peephole
        self._spatial = None  # bound at setup from the input spec

    def _dn(self):
        if self.ndim == 2:
            return ("NCHW", "OIHW", "NCHW")
        return ("NCDHW", "OIDHW", "NCDHW")

    def _conv(self, x, w, b=None):
        y = jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), (1,) * self.ndim, "SAME",
            dimension_numbers=self._dn())
        if b is not None:
            y = y + b.astype(x.dtype).reshape((1, -1) + (1,) * self.ndim)
        return y

    def setup(self, rng, input_spec):
        # input spec: (N, C, *spatial)
        self._spatial = tuple(input_spec.shape[2:])
        init = RandomUniform()
        o, i = self.output_size, self.input_size
        ki = (self.kernel_i,) * self.ndim
        kc = (self.kernel_c,) * self.ndim
        fan_i = i * self.kernel_i ** self.ndim
        fan_c = o * self.kernel_c ** self.ndim
        params = {
            # 4 gates stacked on the output-channel axis (i, f, g, o)
            "weight_ih": init.init(child_rng(rng, 0), (4 * o, i) + ki, fan_i, o),
            "weight_hh": init.init(child_rng(rng, 1), (4 * o, o) + kc, fan_c, o),
            "bias": jnp.zeros((4 * o,), jnp.float32),
        }
        if self.with_peephole:
            # per-channel peephole (CMul(Array(1, outputSize, 1, 1)))
            for k in ("peep_i", "peep_f", "peep_o"):
                params[k] = jnp.zeros((o,), jnp.float32)
        return params, ()

    def init_hidden(self, batch_size, dtype=jnp.float32):
        shape = (batch_size, self.output_size) + self._spatial
        return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)

    def step(self, params, x_t, hidden, drop_key=None):
        h, c = hidden
        gates = (self._conv(x_t, params["weight_ih"], params["bias"])
                 + self._conv(h, params["weight_hh"]))
        i, f, g, o = jnp.split(gates, 4, axis=1)

        def peep(name):
            return (c * params[name].astype(c.dtype)
                    .reshape((1, -1) + (1,) * self.ndim))

        if self.with_peephole:
            i = i + peep("peep_i")
            f = f + peep("peep_f")
            o = o + peep("peep_o")
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class ConvLSTMPeephole(_ConvLSTMBase):
    """2-D convolutional LSTM with peepholes
    (reference: nn/ConvLSTMPeephole.scala:54). Input (N, C, H, W) per step;
    the recurrence convolves both input and hidden state, peepholes are
    per-channel."""

    ndim = 2


class ConvLSTMPeephole3D(_ConvLSTMBase):
    """3-D (volumetric) variant (reference: nn/ConvLSTMPeephole3D.scala).
    Input (N, C, D, H, W) per step."""

    ndim = 3
