"""Normalization and regularization layers.

Reference: nn/BatchNormalization.scala:51, nn/SpatialBatchNormalization.scala,
nn/Dropout.scala, nn/SpatialCrossMapLRN.scala, nn/Normalize.scala.
"""

from contextlib import contextmanager

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module

#: trace-time switch: when a mesh axis name (or tuple of names) is set,
#: training-mode batch statistics are cross-replica (pmean over the axis)
#: -- SyncBN.  Per-shard statistics remain the default, matching the
#: reference's per-replica BN semantics (nn/BatchNormalization.scala
#: normalizes each worker's local batch).
_SYNC_AXIS = None


@contextmanager
def sync_batchnorm(axis):
    """Within this context (at TRACE time, e.g. around ``model.apply``
    inside a shard_map), BatchNormalization layers normalize with
    cross-replica batch statistics over the mesh ``axis`` -- the
    distributed step then matches the single-device full-batch math
    instead of per-shard statistics."""
    global _SYNC_AXIS
    prev, _SYNC_AXIS = _SYNC_AXIS, axis
    try:
        yield
    finally:
        _SYNC_AXIS = prev


class BatchNormalization(Module):
    """Batch norm over (N, C) inputs (reference: nn/BatchNormalization.scala:51).

    Running stats follow the reference/Torch update:
    ``running = (1 - momentum) * running + momentum * batch`` with the
    *unbiased* batch variance feeding the running estimate while the biased
    one normalises the batch.
    """

    reduce_axes = (0,)

    def __init__(self, n_output, eps=1e-5, momentum=0.1, affine=True, name=None):
        super().__init__(name)
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine

    def setup(self, rng, input_spec):
        params = {}
        if self.affine:
            params = {
                "weight": jnp.ones((self.n_output,), jnp.float32),
                "bias": jnp.zeros((self.n_output,), jnp.float32),
            }
        state = {
            "running_mean": jnp.zeros((self.n_output,), jnp.float32),
            "running_var": jnp.ones((self.n_output,), jnp.float32),
        }
        return params, state

    def apply(self, params, state, input, *, training=False, rng=None):
        # statistics accumulate in fp32 WITHOUT materialising an fp32 copy
        # of the activations: the elementwise cast/square fuse into the
        # reduction, and the normalise runs in the input dtype so it fuses
        # with the surrounding convs (bf16 on TPU).  E[x^2]-E[x]^2 in fp32
        # is the standard fused-BN formulation (post-conv activations are
        # ~zero-mean, so cancellation is benign at fp32).
        if training:
            mean = jnp.mean(input, axis=self.reduce_axes,
                            dtype=jnp.float32)
            sq = jnp.mean(jnp.square(input.astype(jnp.float32)),
                          axis=self.reduce_axes, dtype=jnp.float32)
            n = input.size // input.shape[-1]
            if _SYNC_AXIS is not None:
                # SyncBN: moments pooled across replicas (grad of pmean is
                # pmean, so backward stat reductions sync the same way)
                mean = lax.pmean(mean, _SYNC_AXIS)
                sq = lax.pmean(sq, _SYNC_AXIS)
                n = n * lax.psum(1, _SYNC_AXIS)
            var = jnp.maximum(sq - jnp.square(mean), 0.0)
            unbiased = var * n / jnp.maximum(n - 1, 1)
            m = self.momentum
            state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
        inv = lax.rsqrt(var + self.eps)
        scale, shift = inv, -mean * inv
        if self.affine:
            scale = scale * params["weight"]
            shift = shift * params["weight"] + params["bias"]
        y = input * scale.astype(input.dtype) + shift.astype(input.dtype)
        return y, state


class SpatialBatchNormalization(BatchNormalization):
    """Batch norm over NHWC images, per-channel (reference: nn/SpatialBatchNormalization.scala)."""

    reduce_axes = (0, 1, 2)


class LayerNorm(Module):
    """Layer norm over the last dim.  Not in the reference (pre-transformer);
    required by the transformer/long-context stack."""

    def __init__(self, n_output, eps=1e-6, name=None):
        super().__init__(name)
        self.n_output = n_output
        self.eps = eps

    def setup(self, rng, input_spec):
        return {
            "weight": jnp.ones((self.n_output,), jnp.float32),
            "bias": jnp.zeros((self.n_output,), jnp.float32),
        }, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        # fp32-accumulated statistics, normalise in the input dtype (fused
        # like BatchNormalization above -- no fp32 activation copy)
        mean = jnp.mean(input, axis=-1, keepdims=True, dtype=jnp.float32)
        sq = jnp.mean(jnp.square(input.astype(jnp.float32)), axis=-1,
                      keepdims=True, dtype=jnp.float32)
        var = jnp.maximum(sq - jnp.square(mean), 0.0)
        inv = lax.rsqrt(var + self.eps)
        dt = input.dtype
        y = (input - mean.astype(dt)) * inv.astype(dt)
        y = y * params["weight"].astype(dt) + params["bias"].astype(dt)
        return y, state


class RMSNorm(Module):
    """RMS norm (transformer stack; not in the reference)."""

    def __init__(self, n_output, eps=1e-6, name=None):
        super().__init__(name)
        self.n_output = n_output
        self.eps = eps

    def setup(self, rng, input_spec):
        return {"weight": jnp.ones((self.n_output,), jnp.float32)}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        sq = jnp.mean(jnp.square(input.astype(jnp.float32)), -1,
                      keepdims=True, dtype=jnp.float32)
        inv = lax.rsqrt(sq + self.eps).astype(input.dtype)
        return input * inv * params["weight"].astype(input.dtype), state


class Dropout(Module):
    """Inverted dropout (reference: nn/Dropout.scala -- scales by 1/(1-p) at train)."""

    def __init__(self, init_p=0.5, name=None):
        super().__init__(name)
        self.p = init_p

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return input, state
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, jnp.shape(input))
        return jnp.where(mask, input / keep, 0.0).astype(input.dtype), state


class SpatialCrossMapLRN(Module):
    """Local response normalization across channels (reference: nn/SpatialCrossMapLRN.scala).

    NHWC layout: channel window sum via a 1-D reduce_window over the last axis.
    """

    def __init__(self, size=5, alpha=1.0, beta=0.75, k=1.0, data_format="NHWC", name=None):
        super().__init__(name)
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        if self.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        half = (self.size - 1) // 2
        sq = jnp.square(x.astype(jnp.float32))
        window_sum = lax.reduce_window(
            sq, 0.0, lax.add,
            (1, 1, 1, self.size), (1, 1, 1, 1),
            ((0, 0), (0, 0), (0, 0), (half, self.size - 1 - half)),
        )
        denom = jnp.power(self.k + self.alpha / self.size * window_sum, self.beta)
        y = (x.astype(jnp.float32) / denom).astype(input.dtype)
        if self.data_format == "NCHW":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y, state


class Normalize(Module):
    """L_p normalisation over the last dim (reference: nn/Normalize.scala)."""

    def __init__(self, p=2.0, eps=1e-10, name=None):
        super().__init__(name)
        self.p = p
        self.eps = eps

    def apply(self, params, state, input, *, training=False, rng=None):
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(input), axis=-1, keepdims=True)
        else:
            norm = jnp.power(
                jnp.sum(jnp.power(jnp.abs(input), self.p), axis=-1, keepdims=True),
                1.0 / self.p,
            )
        return input / (norm + self.eps), state
