"""Parameterized small layers, stochastic regularizers, penalties, reducers.

Reference: nn/CAdd.scala, CMul.scala, Mul.scala, Scale.scala,
Bilinear.scala, Cosine.scala, Euclidean.scala, Maxout.scala, Highway.scala,
LocallyConnected{1D,2D}.scala, RReLU.scala, SReLU.scala,
BinaryThreshold.scala, GaussianDropout.scala, GaussianNoise.scala,
GradientReversal.scala, Masking.scala, MaskedSelect.scala, L1Penalty.scala,
ActivityRegularization.scala, NegativeEntropyPenalty.scala, Echo.scala,
SpatialDropout{1D,2D,3D}.scala, Sum.scala, Mean.scala, Max.scala,
Min.scala, Reverse.scala, GaussianSampler.scala.

TPU-native notes: penalties (L1Penalty & co.) are identity maps whose
regularization enters through ``jax.custom_vjp`` (the reference mutates
gradInput in ``updateGradInput``); stochastic layers consume the traced
``rng`` key.  All dims 0-based.
"""

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.initialization import RandomUniform, Xavier, Zeros
from bigdl_tpu.nn.module import Module, child_rng


class CAdd(Module):
    """Learnable broadcast bias of shape ``size``
    (reference: nn/CAdd.scala)."""

    def __init__(self, size, name=None):
        super().__init__(name)
        self.size = tuple(size)

    def setup(self, rng, input_spec):
        return {"bias": jnp.zeros(self.size, jnp.float32)}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        return input + params["bias"].astype(input.dtype), state


class CMul(Module):
    """Learnable broadcast scale of shape ``size``
    (reference: nn/CMul.scala)."""

    def __init__(self, size, name=None):
        super().__init__(name)
        self.size = tuple(size)

    def setup(self, rng, input_spec):
        fan = max(int(jnp.prod(jnp.asarray(self.size))), 1)
        w = RandomUniform(-1.0 / fan ** 0.5, 1.0 / fan ** 0.5).init(
            rng, self.size, fan, fan)
        return {"weight": w}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        return input * params["weight"].astype(input.dtype), state


class Mul(Module):
    """Single learnable scalar multiplier (reference: nn/Mul.scala)."""

    def setup(self, rng, input_spec):
        return {"weight": jnp.ones((), jnp.float32)}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        return input * params["weight"].astype(input.dtype), state


class Scale(Module):
    """CMul then CAdd (reference: nn/Scale.scala)."""

    def __init__(self, size, name=None):
        super().__init__(name)
        self.size = tuple(size)

    def setup(self, rng, input_spec):
        return {"weight": jnp.ones(self.size, jnp.float32),
                "bias": jnp.zeros(self.size, jnp.float32)}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        return (input * params["weight"].astype(input.dtype)
                + params["bias"].astype(input.dtype)), state


class Bilinear(Module):
    """(x1, x2) -> x1 W x2 + b, output ``output_size``
    (reference: nn/Bilinear.scala)."""

    def __init__(self, input_size1, input_size2, output_size, bias_res=True,
                 name=None):
        super().__init__(name)
        self.d1, self.d2, self.out = input_size1, input_size2, output_size
        self.bias_res = bias_res

    def setup(self, rng, input_spec):
        k = 1.0 / self.d1 ** 0.5
        w = RandomUniform(-k, k).init(
            rng, (self.out, self.d1, self.d2), self.d1, self.out)
        params = {"weight": w}
        if self.bias_res:
            params["bias"] = jnp.zeros((self.out,), jnp.float32)
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        x1, x2 = input
        w = params["weight"].astype(x1.dtype)
        y = jnp.einsum("ni,oij,nj->no", x1, w, x2)
        if self.bias_res:
            y = y + params["bias"].astype(y.dtype)
        return y, state


class Cosine(Module):
    """Cosine similarity of the input to each of ``output_size`` weight rows
    (reference: nn/Cosine.scala)."""

    def __init__(self, input_size, output_size, name=None):
        super().__init__(name)
        self.input_size, self.output_size = input_size, output_size

    def setup(self, rng, input_spec):
        w = Xavier().init(rng, (self.output_size, self.input_size),
                          self.input_size, self.output_size)
        return {"weight": w}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        w = params["weight"].astype(input.dtype)
        xn = input / jnp.maximum(
            jnp.linalg.norm(input, axis=-1, keepdims=True), 1e-12)
        wn = w / jnp.maximum(jnp.linalg.norm(w, axis=-1, keepdims=True),
                             1e-12)
        return xn @ wn.T, state


class Euclidean(Module):
    """Euclidean distance of the input to each weight row
    (reference: nn/Euclidean.scala)."""

    def __init__(self, input_size, output_size, name=None):
        super().__init__(name)
        self.input_size, self.output_size = input_size, output_size

    def setup(self, rng, input_spec):
        w = Xavier().init(rng, (self.output_size, self.input_size),
                          self.input_size, self.output_size)
        return {"weight": w}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        w = params["weight"].astype(input.dtype)
        diff = input[:, None, :] - w[None, :, :]
        return jnp.linalg.norm(diff, axis=-1), state


class Maxout(Module):
    """Linear to pool*out features, max over each pool group
    (reference: nn/Maxout.scala)."""

    def __init__(self, input_size, output_size, maxout_number, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.maxout_number = maxout_number

    def setup(self, rng, input_spec):
        n_out = self.output_size * self.maxout_number
        w = Xavier().init(rng, (n_out, self.input_size), self.input_size,
                          n_out)
        return {"weight": w, "bias": jnp.zeros((n_out,), jnp.float32)}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        y = input @ params["weight"].astype(input.dtype).T \
            + params["bias"].astype(input.dtype)
        y = y.reshape(y.shape[:-1] + (self.maxout_number, self.output_size))
        return jnp.max(y, axis=-2), state


class Highway(Module):
    """y = t * g(Wx+b) + (1-t) * x with t = sigmoid(Wt x + bt)
    (reference: nn/Highway.scala)."""

    def __init__(self, size, with_bias=True, activation=None, name=None):
        super().__init__(name)
        self.size = size
        self.with_bias = with_bias
        self.activation = activation

    def setup(self, rng, input_spec):
        w1 = Xavier().init(child_rng(rng, 0), (self.size, self.size),
                           self.size, self.size)
        w2 = Xavier().init(child_rng(rng, 1), (self.size, self.size),
                           self.size, self.size)
        params = {"w_t": w1, "w_h": w2}
        if self.with_bias:
            # gate bias < 0 biases toward carry at init (keras convention)
            params["b_t"] = jnp.full((self.size,), -1.0, jnp.float32)
            params["b_h"] = jnp.zeros((self.size,), jnp.float32)
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        t = input @ params["w_t"].astype(input.dtype).T
        h = input @ params["w_h"].astype(input.dtype).T
        if self.with_bias:
            t = t + params["b_t"].astype(input.dtype)
            h = h + params["b_h"].astype(input.dtype)
        t = jax.nn.sigmoid(t)
        if self.activation is not None:
            h, _ = self.activation.apply((), (), h)
        else:
            h = jnp.tanh(h)
        return t * h + (1.0 - t) * input, state


class LocallyConnected2D(Module):
    """Unshared 2-D convolution: one kernel per output position
    (reference: nn/LocallyConnected2D.scala).  NHWC; implemented as
    patch-extraction + per-position einsum, which XLA maps to batched
    matmuls on the MXU."""

    def __init__(self, n_input_plane, input_width, input_height,
                 n_output_plane, kernel_w, kernel_h, stride_w=1, stride_h=1,
                 pad_w=0, pad_h=0, with_bias=True, name=None):
        super().__init__(name)
        self.cin = n_input_plane
        self.cout = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.with_bias = with_bias
        self.in_hw = (input_height, input_width)

    def _out_hw(self):
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        h, w = self.in_hw
        return ((h + 2 * ph - kh) // sh + 1, (w + 2 * pw - kw) // sw + 1)

    def setup(self, rng, input_spec):
        kh, kw = self.kernel
        oh, ow = self._out_hw()
        fan_in = self.cin * kh * kw
        w = Xavier().init(rng, (oh, ow, kh * kw * self.cin, self.cout),
                          fan_in, self.cout)
        params = {"weight": w}
        if self.with_bias:
            params["bias"] = jnp.zeros((oh, ow, self.cout), jnp.float32)
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        from jax import lax
        kh, kw = self.kernel
        patches = lax.conv_general_dilated_patches(
            input, (kh, kw), self.stride,
            [(self.pad[0], self.pad[0]), (self.pad[1], self.pad[1])],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # patches: (N, OH, OW, C*kh*kw) with channel-major ordering; weight
        # stored to match
        y = jnp.einsum("nhwk,hwko->nhwo", patches,
                       params["weight"].astype(input.dtype))
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state


class LocallyConnected1D(Module):
    """Unshared temporal convolution over (N, T, C)
    (reference: nn/LocallyConnected1D.scala)."""

    def __init__(self, n_input_frame, input_frame_size, output_frame_size,
                 kernel_w, stride_w=1, with_bias=True, name=None):
        super().__init__(name)
        self.n_input_frame = n_input_frame
        self.cin = input_frame_size
        self.cout = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.with_bias = with_bias

    def setup(self, rng, input_spec):
        ot = (self.n_input_frame - self.kernel_w) // self.stride_w + 1
        fan_in = self.cin * self.kernel_w
        w = Xavier().init(rng, (ot, self.kernel_w * self.cin, self.cout),
                          fan_in, self.cout)
        params = {"weight": w}
        if self.with_bias:
            params["bias"] = jnp.zeros((ot, self.cout), jnp.float32)
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        ot = (input.shape[1] - self.kernel_w) // self.stride_w + 1
        idx = (jnp.arange(ot)[:, None] * self.stride_w
               + jnp.arange(self.kernel_w)[None, :])
        windows = input[:, idx, :]                  # (N, OT, kW, C)
        windows = windows.reshape(windows.shape[0], ot, -1)
        y = jnp.einsum("ntk,tko->nto", windows,
                       params["weight"].astype(input.dtype))
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state


class RReLU(Module):
    """Randomized leaky ReLU: slope ~ U(lower, upper) at train, the mean
    slope at eval (reference: nn/RReLU.scala)."""

    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, name=None):
        super().__init__(name)
        self.lower, self.upper = lower, upper

    def apply(self, params, state, input, *, training=False, rng=None):
        if training and rng is not None:
            a = jax.random.uniform(rng, input.shape, input.dtype,
                                   self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(input >= 0, input, a * input), state


class SReLU(Module):
    """S-shaped ReLU with 4 learnable per-channel params
    (reference: nn/SReLU.scala, keras SReLU)."""

    def __init__(self, shared_axes=None, name=None):
        super().__init__(name)
        self.shared_axes = shared_axes

    def setup(self, rng, input_spec):
        shape = list(input_spec.shape[1:])
        if self.shared_axes:
            for ax in self.shared_axes:
                shape[ax - 1] = 1
        shape = tuple(shape)
        return {"t_left": jnp.zeros(shape, jnp.float32),
                "a_left": jnp.zeros(shape, jnp.float32),
                "t_right": jnp.ones(shape, jnp.float32),
                "a_right": jnp.ones(shape, jnp.float32)}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        tl = params["t_left"].astype(input.dtype)
        al = params["a_left"].astype(input.dtype)
        tr = params["t_right"].astype(input.dtype)
        ar = params["a_right"].astype(input.dtype)
        y = jnp.where(input <= tl, tl + al * (input - tl), input)
        return jnp.where(y >= tr, tr + ar * (y - tr), y), state


class BinaryThreshold(Module):
    """x > th ? 1 : 0 (reference: nn/BinaryThreshold.scala)."""

    def __init__(self, th=1e-6, name=None):
        super().__init__(name)
        self.th = th

    def apply(self, params, state, input, *, training=False, rng=None):
        return (input > self.th).astype(input.dtype), state


class GaussianDropout(Module):
    """Multiply by N(1, rate/(1-rate)) at train
    (reference: nn/GaussianDropout.scala)."""

    def __init__(self, rate, name=None):
        super().__init__(name)
        self.rate = rate

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or rng is None or self.rate <= 0:
            return input, state
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + std * jax.random.normal(rng, input.shape, input.dtype)
        return input * noise, state


class GaussianNoise(Module):
    """Additive N(0, stddev) noise at train
    (reference: nn/GaussianNoise.scala)."""

    def __init__(self, stddev, name=None):
        super().__init__(name)
        self.stddev = stddev

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or rng is None:
            return input, state
        return input + self.stddev * jax.random.normal(
            rng, input.shape, input.dtype), state


class GradientReversal(Module):
    """Identity forward, gradient scaled by ``-lambda`` backward
    (reference: nn/GradientReversal.scala)."""

    def __init__(self, the_lambda=1.0, name=None):
        super().__init__(name)
        self.the_lambda = the_lambda

        @jax.custom_vjp
        def rev(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_, g):
            return (-self.the_lambda * g,)

        rev.defvjp(fwd, bwd)
        self._rev = rev

    def apply(self, params, state, input, *, training=False, rng=None):
        return self._rev(input), state


class Masking(Module):
    """Zero every timestep whose features all equal ``mask_value``
    (reference: nn/Masking.scala)."""

    def __init__(self, mask_value=0.0, name=None):
        super().__init__(name)
        self.mask_value = mask_value

    def apply(self, params, state, input, *, training=False, rng=None):
        keep = jnp.any(input != self.mask_value, axis=-1, keepdims=True)
        return input * keep.astype(input.dtype), state


class MaskedSelect(Module):
    """(tensor, mask) -> selected elements.  Dynamic output size: the
    reference returns a 1-D tensor of the mask's true entries
    (nn/MaskedSelect.scala); under jit this is not traceable, so eager use
    only (guarded with a clear error)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        t, mask = input
        if isinstance(t, jax.core.Tracer):
            raise NotImplementedError(
                "MaskedSelect produces a data-dependent shape; use it "
                "eagerly (outside jit), or mask with where() instead")
        import numpy as np
        return jnp.asarray(np.asarray(t)[np.asarray(mask).astype(bool)]), \
            state


def _identity_with_penalty_grad(penalty_grad_fn):
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, x

    def bwd(x, g):
        return (g + penalty_grad_fn(x),)

    f.defvjp(fwd, bwd)
    return f


class L1Penalty(Module):
    """Identity whose backward adds ``l1weight * sign(x)``
    (reference: nn/L1Penalty.scala adds the penalty in updateGradInput)."""

    def __init__(self, l1weight, size_average=False, provide_output=True,
                 name=None):
        super().__init__(name)
        self.l1weight = l1weight
        self.size_average = size_average
        self._f = _identity_with_penalty_grad(self._grad)

    def _grad(self, x):
        w = self.l1weight / x.size if self.size_average else self.l1weight
        return w * jnp.sign(x)

    def apply(self, params, state, input, *, training=False, rng=None):
        return self._f(input) if training else input, state


class ActivityRegularization(Module):
    """Identity + (l1 |x| + l2 x^2) penalty gradient
    (reference: nn/ActivityRegularization.scala)."""

    def __init__(self, l1=0.0, l2=0.0, name=None):
        super().__init__(name)
        self.l1, self.l2 = l1, l2
        self._f = _identity_with_penalty_grad(
            lambda x: self.l1 * jnp.sign(x) + 2.0 * self.l2 * x)

    def apply(self, params, state, input, *, training=False, rng=None):
        return self._f(input) if training else input, state


class NegativeEntropyPenalty(Module):
    """Identity + beta * d(-H(p))/dp penalty gradient over probabilities
    (reference: nn/NegativeEntropyPenalty.scala)."""

    def __init__(self, beta=0.01, name=None):
        super().__init__(name)
        self.beta = beta
        self._f = _identity_with_penalty_grad(
            lambda p: self.beta * (jnp.log(jnp.maximum(p, 1e-12)) + 1.0))

    def apply(self, params, state, input, *, training=False, rng=None):
        return self._f(input) if training else input, state


class Echo(Module):
    """Identity that logs the activation shape when traced
    (reference: nn/Echo.scala prints to stdout)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        import logging
        logging.getLogger("bigdl_tpu").info(
            "Echo %s: shape %s dtype %s", self.name, input.shape, input.dtype)
        return input, state


class _SpatialDropoutBase(Module):
    drop_axes = ()

    def __init__(self, init_p=0.5, name=None):
        super().__init__(name)
        self.p = init_p

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or rng is None or self.p <= 0:
            return input, state
        shape = list(input.shape)
        for ax in self.drop_axes:
            shape[ax] = 1
        keep = jax.random.bernoulli(rng, 1.0 - self.p, tuple(shape))
        return input * keep.astype(input.dtype) / (1.0 - self.p), state


class SpatialDropout1D(_SpatialDropoutBase):
    """Drop whole channels of (N, T, C)
    (reference: nn/SpatialDropout1D.scala)."""
    drop_axes = (1,)


class SpatialDropout2D(_SpatialDropoutBase):
    """Drop whole channels of (N, H, W, C)
    (reference: nn/SpatialDropout2D.scala)."""
    drop_axes = (1, 2)


class SpatialDropout3D(_SpatialDropoutBase):
    """Drop whole channels of (N, D, H, W, C)
    (reference: nn/SpatialDropout3D.scala)."""
    drop_axes = (1, 2, 3)


class _ReduceDim(Module):
    def __init__(self, dimension=0, squeeze=True, name=None):
        super().__init__(name)
        self.dimension = dimension
        self.squeeze = squeeze

    def fn(self, x, axis, keepdims):
        raise NotImplementedError

    def apply(self, params, state, input, *, training=False, rng=None):
        return self.fn(input, self.dimension, not self.squeeze), state


class Sum(_ReduceDim):
    """Sum over ``dimension`` (reference: nn/Sum.scala)."""

    def __init__(self, dimension=0, squeeze=True, size_average=False,
                 name=None):
        super().__init__(dimension, squeeze, name)
        self.size_average = size_average

    def fn(self, x, axis, keepdims):
        y = jnp.sum(x, axis=axis, keepdims=keepdims)
        if self.size_average:
            y = y / x.shape[axis]
        return y


class Mean(_ReduceDim):
    """Mean over ``dimension`` (reference: nn/Mean.scala)."""

    def fn(self, x, axis, keepdims):
        return jnp.mean(x, axis=axis, keepdims=keepdims)


class Max(_ReduceDim):
    """Max over ``dimension`` (reference: nn/Max.scala)."""

    def fn(self, x, axis, keepdims):
        return jnp.max(x, axis=axis, keepdims=keepdims)


class Min(_ReduceDim):
    """Min over ``dimension`` (reference: nn/Min.scala)."""

    def fn(self, x, axis, keepdims):
        return jnp.min(x, axis=axis, keepdims=keepdims)


class Reverse(Module):
    """Flip along ``dimension`` (reference: nn/Reverse.scala)."""

    def __init__(self, dimension=0, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.flip(input, axis=self.dimension), state


class GaussianSampler(Module):
    """(mean, log_var) -> mean + exp(log_var/2) * eps — the VAE
    reparameterization (reference: nn/GaussianSampler.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        mean, log_var = input
        if rng is None:
            return mean, state
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + jnp.exp(log_var * 0.5) * eps, state


class Add(Module):
    """Learnable bias add over a flattened ``input_size`` vector
    (reference: nn/Add.scala; Torch nn.Add): ``y = x + b`` with ``b``
    broadcast over the batch dimension."""

    def __init__(self, input_size, name=None):
        super().__init__(name)
        self.input_size = int(input_size)

    def setup(self, rng, input_spec):
        stdv = 1.0 / self.input_size ** 0.5
        b = RandomUniform(-stdv, stdv).init(
            rng, (self.input_size,), self.input_size, self.input_size)
        return {"bias": b}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        b = params["bias"].astype(input.dtype)
        if input.shape[1:] != b.shape:
            b = b.reshape(input.shape[1:])
        return input + b, state
