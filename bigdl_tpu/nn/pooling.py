"""Pooling layers.

Reference: nn/SpatialMaxPooling.scala, nn/SpatialAveragePooling.scala.
Implemented with ``lax.reduce_window`` -- XLA maps these to the VPU with
fused padding; no explicit im2col-style buffers.
"""

import numpy as np

import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module


def _pool_pads(in_size, k, s, p, ceil_mode):
    """(lo, hi) padding per spatial dim honoring the reference's floor/ceil modes."""
    if ceil_mode:
        out = int(np.ceil((in_size + 2 * p - k) / s)) + 1
        # Torch/BigDL rule: last window must start inside the (left-)padded input.
        if (out - 1) * s >= in_size + p:
            out -= 1
    else:
        out = int(np.floor((in_size + 2 * p - k) / s)) + 1
    hi = max((out - 1) * s + k - in_size - p, p)
    return (p, hi)


class _SpatialPool(Module):
    def __init__(
        self, kernel_w, kernel_h, stride_w=None, stride_h=None, pad_w=0,
        pad_h=0, ceil_mode=False, data_format="NHWC", name=None,
    ):
        super().__init__(name)
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h or kernel_h, stride_w or kernel_w)
        self.pad = (pad_h, pad_w)
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def ceil(self):
        self.ceil_mode = True
        return self

    def _window(self, x):
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        pads_h = _pool_pads(x.shape[1], kh, sh, ph, self.ceil_mode)
        pads_w = _pool_pads(x.shape[2], kw, sw, pw, self.ceil_mode)
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        padding = ((0, 0), pads_h, pads_w, (0, 0))
        return dims, strides, padding

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        if self.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        y = self._pool(x)
        if self.data_format == "NCHW":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y, state


class SpatialMaxPooling(_SpatialPool):
    """Reference: nn/SpatialMaxPooling.scala (floor mode default, .ceil() to switch)."""

    def _pool(self, x):
        dims, strides, padding = self._window(x)
        return lax.reduce_window(
            x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else
            jnp.iinfo(x.dtype).min,
            lax.max, dims, strides, padding,
        )


class SpatialAveragePooling(_SpatialPool):
    """Reference: nn/SpatialAveragePooling.scala.

    ``count_include_pad=True`` (the reference/Torch default) divides by the
    full kernel size; otherwise by the number of valid elements.
    """

    def __init__(self, *args, count_include_pad=True, **kw):
        super().__init__(*args, **kw)
        self.count_include_pad = count_include_pad

    def _pool(self, x):
        dims, strides, padding = self._window(x)
        summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
        if self.count_include_pad:
            return summed / (self.kernel[0] * self.kernel[1])
        ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, padding)
        return summed / counts


class GlobalAveragePooling2D(Module):
    """Mean over spatial dims (keras-layer analogue: nn/keras/GlobalAveragePooling2D.scala)."""

    def __init__(self, data_format="NHWC", name=None):
        super().__init__(name)
        self.data_format = data_format

    def apply(self, params, state, input, *, training=False, rng=None):
        axes = (1, 2) if self.data_format == "NHWC" else (2, 3)
        return jnp.mean(input, axis=axes), state


class GlobalMaxPooling2D(Module):
    def __init__(self, data_format="NHWC", name=None):
        super().__init__(name)
        self.data_format = data_format

    def apply(self, params, state, input, *, training=False, rng=None):
        axes = (1, 2) if self.data_format == "NHWC" else (2, 3)
        return jnp.max(input, axis=axes), state
