"""SSD MultiBox training criterion.

The reference trains SSD via its model-zoo MultiBoxLoss (the in-tree nn/
package ships the inference heads: PriorBox nn/PriorBox.scala:43,
DetectionOutputSSD); this provides the training-side counterpart so the
detection path is trainable end-to-end, jit-compatible on TPU:

- static shapes: gt comes padded to (B, M, 5) rows [label, x1, y1, x2, y2]
  (label < 0 marks padding), priors (P, 4) corner form, predictions
  loc (B, P, 4) offsets + conf (B, P, C) logits;
- matching (bipartite-ish, vectorised): priors with IoU > threshold to any
  gt are positive, plus each gt's best prior is forced positive;
- loc loss: smooth-L1 on SSD-encoded offsets (center/size with variances
  0.1/0.2) over positives;
- conf loss: softmax CE over positives + hard-negative mining at
  ``neg_pos_ratio`` (3:1 default) -- top-k implemented with a static sort.
"""

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Criterion


def _iou(priors, boxes):
    """(P, 4) x (M, 4) corner boxes -> (P, M) IoU."""
    px1, py1, px2, py2 = [priors[:, i:i + 1] for i in range(4)]
    gx1, gy1, gx2, gy2 = [boxes[None, :, i] for i in range(4)]
    ix1 = jnp.maximum(px1, gx1)
    iy1 = jnp.maximum(py1, gy1)
    ix2 = jnp.minimum(px2, gx2)
    iy2 = jnp.minimum(py2, gy2)
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    pa = jnp.clip(px2 - px1, 0) * jnp.clip(py2 - py1, 0)
    ga = jnp.clip(gx2 - gx1, 0) * jnp.clip(gy2 - gy1, 0)
    union = pa + ga - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _encode(matched, priors, variances=(0.1, 0.2)):
    """gt corner boxes matched per prior -> SSD regression targets."""
    pcx = (priors[:, 0] + priors[:, 2]) / 2
    pcy = (priors[:, 1] + priors[:, 3]) / 2
    pw = jnp.clip(priors[:, 2] - priors[:, 0], 1e-6)
    ph = jnp.clip(priors[:, 3] - priors[:, 1], 1e-6)
    gcx = (matched[:, 0] + matched[:, 2]) / 2
    gcy = (matched[:, 1] + matched[:, 3]) / 2
    gw = jnp.clip(matched[:, 2] - matched[:, 0], 1e-6)
    gh = jnp.clip(matched[:, 3] - matched[:, 1], 1e-6)
    return jnp.stack([
        (gcx - pcx) / pw / variances[0],
        (gcy - pcy) / ph / variances[0],
        jnp.log(gw / pw) / variances[1],
        jnp.log(gh / ph) / variances[1],
    ], axis=-1)


def _smooth_l1(x):
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


class MultiBoxCriterion(Criterion):
    """loss((loc (B,P,4), conf (B,P,C)), (priors (P,4), gt (B,M,5)))."""

    def __init__(self, num_classes, overlap_threshold=0.5,
                 neg_pos_ratio=3.0, background_label=0, loc_weight=1.0):
        self.num_classes = num_classes
        self.threshold = overlap_threshold
        self.neg_pos_ratio = neg_pos_ratio
        self.background = background_label
        self.loc_weight = loc_weight

    def apply(self, output, target):
        loc, conf = output
        priors, gt = target

        def one(loc_i, conf_i, gt_i):
            labels = gt_i[:, 0]
            boxes = gt_i[:, 1:5]
            valid = labels >= 0                        # (M,)
            iou = _iou(priors, boxes) * valid[None, :]  # (P, M)
            best_gt = jnp.argmax(iou, axis=1)          # (P,)
            best_iou = jnp.max(iou, axis=1)
            # force each valid gt's best prior to match it -- scatter-MAX so
            # a padding row (argmax over its all-zero column = prior 0)
            # cannot clobber a valid gt's forced positive at the same index
            best_prior = jnp.argmax(iou, axis=0)       # (M,)
            m = gt_i.shape[0]
            forced = jnp.zeros_like(best_iou).at[best_prior].max(
                jnp.where(valid, 2.0, 0.0))
            best_gt = best_gt.at[best_prior].set(
                jnp.where(valid, jnp.arange(m), best_gt[best_prior]))
            pos = (best_iou > self.threshold) | (forced > 1.0)

            matched_boxes = boxes[best_gt]
            matched_labels = jnp.where(
                pos, labels[best_gt].astype(jnp.int32), self.background)

            # localization
            t = _encode(matched_boxes, priors)
            l_loss = jnp.sum(
                _smooth_l1(loc_i - t).sum(-1) * pos.astype(loc_i.dtype))

            # confidence with hard negative mining
            logp = jax.nn.log_softmax(conf_i, axis=-1)
            ce = -jnp.take_along_axis(
                logp, matched_labels[:, None], axis=-1)[:, 0]
            n_pos = jnp.sum(pos)
            n_neg = jnp.minimum(
                (self.neg_pos_ratio * n_pos).astype(jnp.int32),
                jnp.asarray(pos.shape[0], jnp.int32))
            neg_score = jnp.where(pos, -jnp.inf,
                                  -logp[:, self.background])
            order = jnp.argsort(-neg_score)
            rank = jnp.zeros_like(order).at[order].set(
                jnp.arange(order.shape[0]))
            neg = (~pos) & (rank < n_neg)
            c_loss = jnp.sum(ce * (pos | neg).astype(ce.dtype))
            return l_loss, c_loss, n_pos

        l_loss, c_loss, n_pos = jax.vmap(one)(loc, conf, gt)
        denom = jnp.maximum(jnp.sum(n_pos).astype(loc.dtype), 1.0)
        return (self.loc_weight * jnp.sum(l_loss) + jnp.sum(c_loss)) / denom
