"""Graph-level control flow: the DynamicGraph/ControlOps analogue.

Reference: nn/DynamicGraph.scala:28 executes breadth-first with a Scheduler,
and nn/FrameManager.scala:31 + nn/tf/ControlOps.scala (Switch/Merge/Enter/
Exit/NextIteration) implement TF-style data-dependent control flow by
scheduling only the live branch at runtime.

TPU-native redesign: under XLA everything is one traced program, so there is
no scheduler to skip dead branches -- data-dependent control flow lowers to
``lax.cond`` (conditional diamond) and ``lax.while_loop`` (frames).  The
API keeps the reference's graph-construction surface:

    s = Switch()(data_node, pred_node)          # -> (false_out, true_out)
    a = SomeModule()(s.true_edge())
    b = OtherModule()(s.false_edge())
    out = Merge()(a, b)
    model = DynamicGraph([inputs], [out])       # lowers diamond to lax.cond

    loop = WhileLoop(cond_graph, body_graph)    # lax.while_loop module

Semantic difference, by design: the reference executes ONLY the taken
branch; XLA traces BOTH branches and selects (lax.cond executes one branch
on device, but both must be traceable with the same output structure).
"""

from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.graph import Graph, Node
from bigdl_tpu.nn.module import Container, Module, child_rng


class Switch(Module):
    """(data, pred) -> (false_out, true_out) (reference: ControlOps.scala:65
    SwitchOps -- output 1 is the false branch, output 2 the true branch).

    Under XLA both outputs carry the data; the selection happens at the
    matching Merge (lax.cond/select), not by scheduling.
    """

    def __call__(self, data: Node, pred: Node) -> "SwitchNode":
        node = SwitchNode(self, [data, pred])
        return node

    def apply(self, params, state, input, *, training=False, rng=None):
        data, pred = input
        return (data, pred), state


class SwitchNode(Node):
    """Node wrapper exposing false/true edges (reference:
    SwitchControlNode.availableNodes)."""

    def false_edge(self) -> Node:
        return Node(_SwitchBranch(False), [self])

    def true_edge(self) -> Node:
        return Node(_SwitchBranch(True), [self])


class _SwitchBranch(Module):
    def __init__(self, taken: bool, name=None):
        super().__init__(name)
        self.taken = taken

    def apply(self, params, state, input, *, training=False, rng=None):
        data, pred = input
        return (data, pred, jnp.asarray(self.taken)), state


class Merge(Module):
    """Join the two arms of a Switch diamond (reference: ControlOps.scala:87
    MergeOps passes through whichever input arrived; here: select on the
    predicate that the Switch threaded through the arms)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        (a, pred_a, taken_a), (b, _pred_b, _taken_b) = input
        # arm outputs carry (value, pred, arm_polarity); pick by predicate
        pred = jnp.reshape(pred_a, ()).astype(bool)
        first_if = jnp.asarray(taken_a, bool)
        pick_a = jnp.where(pred, first_if, ~first_if)
        return jax.tree.map(
            lambda x, y: jnp.where(pick_a, x, y), a, b), state


class _Passthrough(Module):
    """Keeps the (value, pred, polarity) triple through a module applied to
    a switch arm: applies the wrapped module to the value only."""

    def __init__(self, inner, name=None):
        super().__init__(name)
        self.inner = inner

    def setup(self, rng, input_spec):
        val_spec = input_spec[0]
        return self.inner.setup(rng, val_spec)

    def children(self):
        return [self.inner]

    def apply(self, params, state, input, *, training=False, rng=None):
        val, pred, taken = input
        out, state = self.inner.apply(params, state, val,
                                      training=training, rng=rng)
        return (out, pred, taken), state


def on_branch(module: Module, arm: Node) -> Node:
    """Apply ``module`` to a switch arm, threading the control triple."""
    return Node(_Passthrough(module), [arm])


class DynamicGraph(Graph):
    """Graph that accepts Switch/Merge nodes (reference: DynamicGraph.scala
    schedules them; here they trace to select/cond -- the only difference
    from Graph is the construction sugar, since under jit static topology +
    lax select IS dynamic execution)."""


class WhileLoop(Module):
    """lax.while_loop over loop-carried values, with condition and body
    given as Graphs over those values (reference: tf while frames --
    Enter/Merge/LoopCond/Switch/NextIteration/Exit,
    nn/tf/ControlOps.scala:182-240).

    cond_graph: Graph mapping the N loop vars -> boolean scalar.
    body_graph: Graph mapping the N loop vars -> N updated vars.
    apply input: tuple of N initial values -> tuple of N final values.
    """

    def __init__(self, cond_graph: Graph, body_graph: Graph, name=None):
        super().__init__(name)
        self.cond_graph = cond_graph
        self.body_graph = body_graph

    def children(self):
        return [self.cond_graph, self.body_graph]

    def setup(self, rng, input_spec):
        spec = input_spec if isinstance(input_spec, tuple) else (input_spec,)
        cp, cs = self.cond_graph.setup(
            child_rng(rng, 0), spec if len(spec) > 1 else spec[0])
        bp, bs = self.body_graph.setup(
            child_rng(rng, 1), spec if len(spec) > 1 else spec[0])
        return {"cond": cp, "body": bp}, {"cond": cs, "body": bs}

    def apply(self, params, state, input, *, training=False, rng=None):
        init = input if isinstance(input, tuple) else (input,)
        single = not isinstance(input, tuple)

        def cond_fn(vs):
            out, _ = self.cond_graph.apply(
                params["cond"], state["cond"], vs[0] if single else vs,
                training=False, rng=None)
            return jnp.reshape(out, ()).astype(bool)

        def body_fn(vs):
            out, _ = self.body_graph.apply(
                params["body"], state["body"], vs[0] if single else vs,
                training=False, rng=None)
            out = out if isinstance(out, tuple) else (out,)
            # keep carried dtypes/shapes stable across iterations
            return tuple(jnp.asarray(o).astype(v.dtype)
                         for o, v in zip(out, vs))

        final = lax.while_loop(cond_fn, body_fn,
                               tuple(jnp.asarray(v) for v in init))
        return (final[0] if single else final), state
