"""Embedding layers.

Reference: nn/LookupTable.scala (gather + optional max-norm),
nn/LookupTableSparse.scala.
"""

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.initialization import RandomNormal
from bigdl_tpu.nn.module import Module


class LookupTable(Module):
    """Embedding lookup (reference: nn/LookupTable.scala).

    ``input``: int indices (0-based), any shape; output gains a trailing
    ``n_output`` dim.  The gather lowers to a one-hot matmul or dynamic-gather
    depending on XLA's choice -- both TPU-native.
    """

    def __init__(self, n_index, n_output, padding_value=None, max_norm=None,
                 norm_type=2.0, weight_init=None, name=None):
        super().__init__(name)
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.weight_init = weight_init or RandomNormal(0.0, 1.0)

    def setup(self, rng, input_spec):
        w = self.weight_init.init(
            rng, (self.n_index, self.n_output), self.n_index, self.n_output
        )
        return {"weight": w}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        w = params["weight"]
        if self.max_norm is not None:
            norms = jnp.linalg.norm(w, ord=self.norm_type, axis=-1, keepdims=True)
            w = w * jnp.minimum(1.0, self.max_norm / jnp.maximum(norms, 1e-12))
        idx = input.astype(jnp.int32)
        y = jnp.take(w, jnp.clip(idx, 0, self.n_index - 1), axis=0)
        if self.padding_value is not None:
            y = jnp.where((idx == self.padding_value)[..., None], 0.0, y)
        return y, state
