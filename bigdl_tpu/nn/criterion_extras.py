"""Criterion breadth: the remaining reference loss functions.

Reference: nn/CategoricalCrossEntropy.scala, CosineDistanceCriterion.scala,
CosineProximityCriterion.scala, DiceCoefficientCriterion.scala,
DotProductCriterion.scala, L1HingeEmbeddingCriterion.scala,
MarginRankingCriterion.scala, MeanAbsolutePercentageCriterion.scala,
MeanSquaredLogarithmicCriterion.scala, MultiLabelMarginCriterion.scala,
MultiMarginCriterion.scala, PoissonCriterion.scala,
SoftMarginCriterion.scala, KLDCriterion.scala, GaussianCriterion.scala,
TransformerCriterion.scala, TimeDistributedMaskCriterion.scala,
ClassSimplexCriterion.scala.
"""

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.criterion import Criterion


class CategoricalCrossEntropy(Criterion):
    """-sum(target * log(prob)) with probability inputs
    (reference: nn/CategoricalCrossEntropy.scala; keras semantics)."""

    def __init__(self, epsilon=1e-8):
        self.epsilon = epsilon

    def apply(self, input, target):
        p = jnp.clip(input, self.epsilon, 1.0)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        return -jnp.mean(jnp.sum(target * jnp.log(p), axis=-1))


class CosineDistanceCriterion(Criterion):
    """mean(1 - cos(input, target))
    (reference: nn/CosineDistanceCriterion.scala)."""

    def __init__(self, size_average=True):
        self.size_average = size_average

    def apply(self, input, target):
        num = jnp.sum(input * target, axis=-1)
        den = jnp.maximum(jnp.linalg.norm(input, axis=-1)
                          * jnp.linalg.norm(target, axis=-1), 1e-12)
        loss = 1.0 - num / den
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class CosineProximityCriterion(Criterion):
    """-mean(cos of l2-normalized input/target)
    (reference: nn/CosineProximityCriterion.scala; keras cosine_proximity)."""

    def apply(self, input, target):
        xn = input / jnp.maximum(
            jnp.linalg.norm(input, axis=-1, keepdims=True), 1e-12)
        yn = target / jnp.maximum(
            jnp.linalg.norm(target, axis=-1, keepdims=True), 1e-12)
        return -jnp.mean(jnp.sum(xn * yn, axis=-1))


class DiceCoefficientCriterion(Criterion):
    """1 - 2|X∩Y| / (|X|+|Y|) (reference:
    nn/DiceCoefficientCriterion.scala)."""

    def __init__(self, size_average=True, epsilon=1.0):
        self.size_average = size_average
        self.epsilon = epsilon

    def apply(self, input, target):
        axes = tuple(range(1, input.ndim))
        inter = jnp.sum(input * target, axis=axes)
        union = jnp.sum(input, axis=axes) + jnp.sum(target, axis=axes)
        loss = 1.0 - (2.0 * inter + self.epsilon) / (union + self.epsilon)
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class DotProductCriterion(Criterion):
    """-sum(input * target) (reference: nn/DotProductCriterion.scala)."""

    def __init__(self, size_average=False):
        self.size_average = size_average

    def apply(self, input, target):
        dots = jnp.sum(input * target, axis=-1)
        return -(jnp.mean(dots) if self.size_average else jnp.sum(dots))


class L1HingeEmbeddingCriterion(Criterion):
    """Table input (x1, x2), y in {1, -1}: L1 distance hinge
    (reference: nn/L1HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin=1.0):
        self.margin = margin

    def apply(self, input, target):
        x1, x2 = input
        d = jnp.sum(jnp.abs(x1 - x2), axis=-1)
        y = jnp.reshape(target, d.shape)
        loss = jnp.where(y > 0, d, jnp.maximum(0.0, self.margin - d))
        return jnp.mean(loss)


class MarginRankingCriterion(Criterion):
    """Table input (x1, x2), y: max(0, -y*(x1-x2) + margin)
    (reference: nn/MarginRankingCriterion.scala)."""

    def __init__(self, margin=1.0, size_average=True):
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        x1, x2 = input
        y = jnp.reshape(target, jnp.shape(x1))
        loss = jnp.maximum(0.0, -y * (x1 - x2) + self.margin)
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class MeanAbsolutePercentageCriterion(Criterion):
    """100 * mean(|x - y| / clip(|y|))
    (reference: nn/MeanAbsolutePercentageCriterion.scala)."""

    def apply(self, input, target):
        diff = jnp.abs(input - target) / jnp.clip(jnp.abs(target), 1e-7,
                                                  None)
        return 100.0 * jnp.mean(diff)


class MeanSquaredLogarithmicCriterion(Criterion):
    """mean((log(y+1) - log(x+1))^2)
    (reference: nn/MeanSquaredLogarithmicCriterion.scala)."""

    def apply(self, input, target):
        a = jnp.log(jnp.clip(input, 1e-7, None) + 1.0)
        b = jnp.log(jnp.clip(target, 1e-7, None) + 1.0)
        return jnp.mean(jnp.square(a - b))


class MultiLabelMarginCriterion(Criterion):
    """Multi-label hinge: targets are 0-padded lists of class indices
    (0-based here; reference nn/MultiLabelMarginCriterion.scala is 1-based
    with 0 as the stop marker -- here -1 marks padding)."""

    def __init__(self, size_average=True):
        self.size_average = size_average

    def apply(self, input, target):
        n, c = input.shape
        tgt = target.astype(jnp.int32)
        valid = tgt >= 0
        safe = jnp.clip(tgt, 0, c - 1)
        is_target = jnp.sum(
            jax.nn.one_hot(safe, c) * valid[:, :, None], axis=1) > 0
        x_t = jnp.take_along_axis(input, safe, axis=1)     # (n, k)
        margins = 1.0 - (x_t[:, :, None] - input[:, None, :])   # (n,k,c)
        mask = (valid[:, :, None] & ~is_target[:, None, :])
        loss = jnp.sum(jnp.maximum(0.0, margins) * mask, axis=(1, 2)) / c
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class MultiMarginCriterion(Criterion):
    """Single-label margin hinge: sum_j max(0, margin - x_y + x_j)^p / C
    (reference: nn/MultiMarginCriterion.scala)."""

    def __init__(self, p=1, weights=None, margin=1.0, size_average=True):
        self.p = p
        self.weights = weights
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        n, c = input.shape
        t = jnp.clip(target.astype(jnp.int32), 0, c - 1)
        x_t = jnp.take_along_axis(input, t[:, None], axis=1)
        m = jnp.maximum(0.0, self.margin - x_t + input) ** self.p
        if self.weights is not None:
            m = m * jnp.asarray(self.weights)[t][:, None]
        m = m * (1.0 - jax.nn.one_hot(t, c))
        loss = jnp.sum(m, axis=1) / c
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class PoissonCriterion(Criterion):
    """mean(input - target * log(input))
    (reference: nn/PoissonCriterion.scala)."""

    def apply(self, input, target):
        return jnp.mean(input - target
                        * jnp.log(jnp.clip(input, 1e-7, None)))


class SoftMarginCriterion(Criterion):
    """mean(log(1 + exp(-y * x))) (reference: nn/SoftMarginCriterion.scala)."""

    def __init__(self, size_average=True):
        self.size_average = size_average

    def apply(self, input, target):
        loss = jnp.log1p(jnp.exp(-target * input))
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class KLDCriterion(Criterion):
    """KL(N(mu, sigma^2) || N(0, 1)) from (mean, log_var) table input — the
    VAE regularizer (reference: nn/KLDCriterion.scala)."""

    def __init__(self, size_average=True):
        self.size_average = size_average

    def apply(self, input, target=None):
        mean, log_var = input
        kld = 0.5 * jnp.sum(
            jnp.square(mean) + jnp.exp(log_var) - 1.0 - log_var, axis=-1)
        return jnp.mean(kld) if self.size_average else jnp.sum(kld)


class GaussianCriterion(Criterion):
    """Negative log-likelihood of target under N(mean, exp(log_var))
    given a (mean, log_var) table input
    (reference: nn/GaussianCriterion.scala)."""

    def apply(self, input, target):
        mean, log_var = input
        nll = 0.5 * (jnp.log(2.0 * jnp.pi) + log_var
                     + jnp.square(target - mean) / jnp.exp(log_var))
        return jnp.sum(nll)


class TransformerCriterion(Criterion):
    """Wrap a criterion with input/target transformer modules
    (reference: nn/TransformerCriterion.scala)."""

    def __init__(self, criterion, input_transformer=None,
                 target_transformer=None):
        self.criterion = criterion
        self.input_transformer = input_transformer
        self.target_transformer = target_transformer

    def _run(self, mod, x):
        if mod is None:
            return x
        if not mod.is_built():
            from bigdl_tpu.utils.shape import spec_of
            mod.build(spec_of(x))
        y, _ = mod.apply(mod._params, mod._state, x)
        return y

    def apply(self, input, target):
        return self.criterion.apply(
            self._run(self.input_transformer, input),
            self._run(self.target_transformer, target))


class TimeDistributedMaskCriterion(Criterion):
    """Per-timestep criterion with a padding mask: entries where target ==
    ``padding_value`` contribute nothing
    (reference: nn/TimeDistributedMaskCriterion.scala)."""

    def __init__(self, criterion, padding_value=0):
        self.criterion = criterion
        self.padding_value = padding_value

    def apply(self, input, target):
        n, t = target.shape[0], target.shape[1]
        flat_in = input.reshape((n * t,) + input.shape[2:])
        flat_t = target.reshape((n * t,) + target.shape[2:])
        mask = (flat_t != self.padding_value).astype(flat_in.dtype)
        per = jax.vmap(
            lambda x, y: self.criterion.apply(x[None], y[None]))(
                flat_in, flat_t)
        m = mask.reshape(per.shape) if mask.ndim == per.ndim else mask
        return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)


class ClassSimplexCriterion(Criterion):
    """MSE against a regular simplex embedding of the classes
    (reference: nn/ClassSimplexCriterion.scala)."""

    def __init__(self, n_classes):
        import numpy as np
        self.n_classes = n_classes
        # orthonormal corner embedding (the reference's simplex up to
        # rotation; targets map to distinct equidistant vertices)
        self.simplex = jnp.asarray(np.eye(n_classes, dtype=np.float32))

    def apply(self, input, target):
        t = jnp.clip(target.astype(jnp.int32), 0, self.n_classes - 1)
        goal = self.simplex[t]
        k = goal.shape[-1]
        return jnp.mean(jnp.sum(jnp.square(input[..., :k] - goal), axis=-1))


class SmoothL1CriterionWithWeights(Criterion):
    """Smooth-L1 with per-element inside/outside weights, as used by the
    Fast-RCNN bbox head (reference: nn/SmoothL1CriterionWithWeights.scala).

    ``target`` is (targets, inside_w, outside_w) or a plain tensor (weights
    default to 1)."""

    def __init__(self, sigma=1.0, num=0):
        self.sigma2 = sigma * sigma
        self.num = num

    def apply(self, input, target):
        if isinstance(target, tuple):
            tgt, w_in, w_out = target
        else:
            tgt = target
            w_in = w_out = jnp.ones_like(input)
        d = w_in * (input - tgt)
        ad = jnp.abs(d)
        loss = jnp.where(ad < 1.0 / self.sigma2,
                         0.5 * self.sigma2 * d * d,
                         ad - 0.5 / self.sigma2)
        total = jnp.sum(w_out * loss)
        return total / self.num if self.num > 0 else total


class SoftmaxWithCriterion(Criterion):
    """Softmax + NLL over NHWC spatial maps, with optional label ignore —
    caffe's SoftmaxWithLoss (reference: nn/SoftmaxWithCriterion.scala)."""

    def __init__(self, ignore_label=None, normalize_mode="VALID"):
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def apply(self, input, target):
        logp = jax.nn.log_softmax(input, axis=-1)
        t = jnp.clip(target.astype(jnp.int32), 0, input.shape[-1] - 1)
        nll = -jnp.take_along_axis(logp, t[..., None], axis=-1)[..., 0]
        if self.ignore_label is not None:
            mask = (target != self.ignore_label).astype(nll.dtype)
            nll = nll * mask
            denom = jnp.maximum(jnp.sum(mask), 1.0)
        else:
            denom = nll.size
        if self.normalize_mode == "NONE":
            return jnp.sum(nll)
        return jnp.sum(nll) / denom


class PGCriterion(Criterion):
    """Policy-gradient criterion: -sum(target * log prob) with the target
    carrying (one-hot action * advantage)
    (reference: nn/PGCriterion.scala)."""

    def __init__(self, size_average=False):
        self.size_average = size_average

    def apply(self, input, target):
        logp = jnp.log(jnp.clip(input, 1e-8, 1.0))
        loss = -jnp.sum(target * logp, axis=-1)
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)
