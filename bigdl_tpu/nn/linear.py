"""Linear / fully-connected layers.

Reference: nn/Linear.scala (weight (out, in), bias (out), default Xavier).
The matmul lowers to ``lax.dot_general`` -> MXU.
"""

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn.initialization import RandomUniform, Xavier, Zeros
from bigdl_tpu.nn.module import Module, child_rng


class Linear(Module):
    """y = x W^T + b.  Weight layout (out_features, in_features) as in the reference."""

    def __init__(
        self,
        input_size: Optional[int] = None,
        output_size: int = None,
        with_bias: bool = True,
        weight_init=None,
        bias_init=None,
        w_regularizer=None,
        b_regularizer=None,
        name=None,
    ):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.weight_init = weight_init or Xavier()
        self.bias_init = bias_init or Zeros()
        self.set_regularizer(w_regularizer, b_regularizer)

    def setup(self, rng, input_spec):
        in_size = self.input_size or input_spec.shape[-1]
        self.input_size = in_size
        params = {
            "weight": self.weight_init.init(
                child_rng(rng, 0), (self.output_size, in_size), in_size,
                self.output_size,
            )
        }
        if self.with_bias:
            params["bias"] = self.bias_init.init(
                child_rng(rng, 1), (self.output_size,), in_size, self.output_size
            )
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        if "weight_q" in params:
            # post-training-quantized weights (nn/quantized.quantize_params
            # rewrote this layer's tree): int8 contraction on the MXU,
            # bias added in fp32 real units, result cast like the float
            # path.  Reached through the SAME module structure, so the
            # scan-stacked transformer layout quantizes without any
            # module swap.
            from bigdl_tpu.nn.quantized import int8_matmul

            y = int8_matmul(input, params["weight_q"], params["scale"])
            if self.with_bias:
                y = y + params["bias"]
            return y.astype(input.dtype), state
        y = input @ params["weight"].astype(input.dtype).T
        if self.with_bias:
            y = y + params["bias"].astype(input.dtype)
        return y, state
