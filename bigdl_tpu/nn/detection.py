"""Object-detection heads: SSD / Faster-RCNN post-processing, TPU-native.

Reference surface (all under spark/dl/src/main/scala/com/intel/analytics/bigdl/):
  nn/PriorBox.scala:43          -- multibox prior generation
  nn/Anchor.scala:25            -- RPN anchor grid
  nn/Nms.scala:26               -- greedy non-maximum suppression
  nn/Proposal.scala:34          -- RPN proposal layer
  nn/NormalizeScale.scala:37    -- L2-normalise + learned per-channel scale
  nn/DetectionOutputSSD.scala:48   -- SSD decode + per-class NMS
  nn/DetectionOutputFrcnn.scala:48 -- Faster-RCNN post-process
  transform/vision/image/util/BboxUtil.scala -- box decode/clip helpers

TPU-native redesign: the reference runs scalar while-loops over boxes; here
every box op is vectorised. NMS is the one sequential algorithm -- it is
expressed as a `lax.fori_loop` over a precomputed pairwise-IoU matrix
(static shapes, mask semantics), so the whole detection head can live under
`jit` on device; ragged final assembly (variable #detections per image)
happens host-side, as in the reference (which runs this on CPU threads).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module


# --------------------------------------------------------------------------- #
# Box utilities (reference: BboxUtil.scala)
# --------------------------------------------------------------------------- #

def bbox_transform_inv(boxes, deltas):
    """Apply (dx, dy, dw, dh) deltas to corner boxes.

    boxes: (N, 4) [x1, y1, x2, y2]; deltas: (N, 4a).
    Reference: BboxUtil.bboxTransformInv (BboxUtil.scala:53) -- widths use
    the pixel +1 convention.
    """
    boxes = jnp.asarray(boxes, jnp.float32)
    deltas = jnp.asarray(deltas, jnp.float32)
    n, cols = deltas.shape
    d = deltas.reshape(n, cols // 4, 4)
    x1, y1 = boxes[:, 0:1], boxes[:, 1:2]
    w = boxes[:, 2:3] - x1 + 1.0
    h = boxes[:, 3:4] - y1 + 1.0
    ctr_x = d[..., 0] * w + x1 + w / 2
    ctr_y = d[..., 1] * h + y1 + h / 2
    half_w = jnp.exp(d[..., 2]) * w / 2
    half_h = jnp.exp(d[..., 3]) * h / 2
    out = jnp.stack(
        [ctr_x - half_w, ctr_y - half_h, ctr_x + half_w, ctr_y + half_h], axis=-1
    )
    return out.reshape(n, cols)


def clip_boxes(boxes, height, width, min_h=0.0, min_w=0.0, scores=None):
    """Clip boxes to [0, width-1] x [0, height-1]; optionally zero the score
    of boxes smaller than (min_h, min_w).

    Reference: BboxUtil.clipBoxes (BboxUtil.scala:108).
    Returns (boxes, scores) -- scores unchanged if None.
    """
    n, cols = boxes.shape
    b = boxes.reshape(n, cols // 4, 4)
    x1 = jnp.clip(b[..., 0], 0.0, width - 1.0)
    y1 = jnp.clip(b[..., 1], 0.0, height - 1.0)
    x2 = jnp.clip(b[..., 2], 0.0, width - 1.0)
    y2 = jnp.clip(b[..., 3], 0.0, height - 1.0)
    out = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, cols)
    if scores is not None:
        keep = jnp.all(
            (x2 - x1 + 1 >= min_w) & (y2 - y1 + 1 >= min_h), axis=-1
        )
        scores = jnp.where(keep, scores, 0.0)
    return out, scores


def decode_boxes(prior_boxes, prior_variances, bboxes,
                 variance_encoded_in_target=False, clip=False):
    """SSD box decode: priors (P,4) + variances (P,4) + loc preds (P,4) -> (P,4).

    Reference: BboxUtil.decodeBoxes / decodeSingleBbox (BboxUtil.scala:283,303).
    """
    p = jnp.asarray(prior_boxes, jnp.float32)
    v = jnp.asarray(prior_variances, jnp.float32)
    b = jnp.asarray(bboxes, jnp.float32)
    pw = p[:, 2] - p[:, 0]
    ph = p[:, 3] - p[:, 1]
    pcx = (p[:, 0] + p[:, 2]) / 2
    pcy = (p[:, 1] + p[:, 3]) / 2
    if variance_encoded_in_target:
        cx = b[:, 0] * pw + pcx
        cy = b[:, 1] * ph + pcy
        w = jnp.exp(b[:, 2]) * pw
        h = jnp.exp(b[:, 3]) * ph
    else:
        cx = v[:, 0] * b[:, 0] * pw + pcx
        cy = v[:, 1] * b[:, 1] * ph + pcy
        w = jnp.exp(v[:, 2] * b[:, 2]) * pw
        h = jnp.exp(v[:, 3] * b[:, 3]) * ph
    out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _pairwise_iou(boxes, normalized):
    """(N, 4) -> (N, N) IoU matrix. normalized=True uses [0,1]-range box
    areas (no +1), matching Nms.getAreas (Nms.scala:186)."""
    off = 0.0 if normalized else 1.0
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = (x2 - x1 + off) * (y2 - y1 + off)
    iw = jnp.minimum(x2[:, None], x2[None, :]) - jnp.maximum(x1[:, None], x1[None, :]) + off
    ih = jnp.minimum(y2[:, None], y2[None, :]) - jnp.maximum(y1[:, None], y1[None, :]) + off
    inter = jnp.maximum(iw, 0.0) * jnp.maximum(ih, 0.0)
    union = areas[:, None] + areas[None, :] - inter
    return inter / jnp.maximum(union, 1e-12)


def nms(boxes, scores, iou_threshold, score_threshold=None, topk=-1,
        normalized=False, sorted_input=False):
    """Greedy NMS, XLA-native: static shapes, returns (order, keep_mask).

    `order` is the score-descending candidate order and `keep_mask[i]` says
    whether candidate `order[i]` survives. Greedy suppression (a box is
    dropped if it overlaps an already-kept higher-scoring box above
    `iou_threshold`) is a `lax.fori_loop` over a precomputed pairwise-IoU
    matrix, so it jits and runs on device -- the TPU answer to the scalar
    suppression loop in Nms.scala:95-110.
    """
    boxes = jnp.asarray(boxes, jnp.float32)
    scores = jnp.asarray(scores, jnp.float32)
    n = scores.shape[0]
    if n == 0:
        return jnp.zeros((0,), jnp.int32), jnp.zeros((0,), bool)
    if sorted_input:
        order = jnp.arange(n, dtype=jnp.int32)
        sboxes, sscores = boxes, scores
    else:
        order = jnp.argsort(-scores).astype(jnp.int32)
        sboxes, sscores = boxes[order], scores[order]
    # candidates beyond the topk prefix can neither be kept nor suppress
    # anything, so drop them BEFORE the O(n^2) IoU matrix (static shapes)
    if topk is not None and topk > 0 and topk < n:
        n = topk
        order, sboxes, sscores = order[:n], sboxes[:n], sscores[:n]
    valid = jnp.ones((n,), bool)
    if score_threshold is not None:
        valid &= sscores >= score_threshold
    ious = _pairwise_iou(sboxes, normalized)
    idx = jnp.arange(n)

    def body(i, keep):
        suppressed = jnp.any(keep & (ious[:, i] > iou_threshold) & (idx < i))
        return keep.at[i].set(valid[i] & ~suppressed)

    keep = lax.fori_loop(0, n, body, jnp.zeros((n,), bool))
    return order, keep


class Nms:
    """Object-style facade over :func:`nms` (reference: nn/Nms.scala:26)."""

    def nms(self, scores, boxes, thresh, sorted=False):
        """-> numpy array of kept indices (0-based), score-descending."""
        order, keep = nms(boxes, scores, thresh, sorted_input=sorted)
        order, keep = np.asarray(order), np.asarray(keep)
        return order[keep]

    def nms_fast(self, scores, boxes, nms_thresh, score_thresh, topk=-1,
                 normalized=True):
        """Reference: Nms.nmsFast (Nms.scala:131) with eta=1."""
        order, keep = nms(
            boxes, scores, nms_thresh, score_threshold=score_thresh,
            topk=topk, normalized=normalized,
        )
        order, keep = np.asarray(order), np.asarray(keep)
        return order[keep]


# --------------------------------------------------------------------------- #
# PriorBox (reference: nn/PriorBox.scala:43)
# --------------------------------------------------------------------------- #

class PriorBox(Module):
    """Generate multibox priors over a feature map.

    Output (1, 2, H*W*num_priors*4): channel 0 = prior corner coords
    normalised by image size, channel 1 = variances -- the exact layout of
    PriorBox.updateOutput (PriorBox.scala:125-144). Priors are computed with
    one broadcasted expression instead of the reference's scalar fill loop.
    """

    def __init__(self, min_sizes, max_sizes=None, aspect_ratios=None,
                 is_flip=True, is_clip=False, variances=None, offset=0.5,
                 img_h=0, img_w=0, img_size=0, step_h=0.0, step_w=0.0,
                 step=0.0, name=None):
        super().__init__(name)
        self.min_sizes = list(min_sizes)
        self.max_sizes = list(max_sizes) if max_sizes else []
        if self.max_sizes:
            assert len(self.max_sizes) == len(self.min_sizes)
        # dedup'd ratio list starting at 1, optionally flipped
        # (PriorBox.init, PriorBox.scala:55-72)
        ars = [1.0]
        for ar in (aspect_ratios or []):
            if not any(abs(ar - a) < 1e-6 for a in ars):
                ars.append(float(ar))
                if is_flip:
                    ars.append(1.0 / float(ar))
        self.aspect_ratios = ars
        self.num_priors = len(ars) * len(self.min_sizes) + len(self.max_sizes)
        self.is_clip = is_clip
        self.variances = list(variances) if variances is not None else [0.1]
        if len(self.variances) > 1:
            assert len(self.variances) == 4, "must provide exactly 4 variances"
        self.offset = offset
        self.img_h = img_h or img_size
        self.img_w = img_w or img_size
        self.step_h = step_h or step
        self.step_w = step_w or step

    def _cell_templates(self):
        """Per-cell (half_w, half_h) templates in reference prior order:
        for each min_size: unit box, [sqrt(min*max) box], then each ar != 1."""
        half = []
        for s, mn in enumerate(self.min_sizes):
            mn_i = float(int(mn))
            half.append((mn_i / 2, mn_i / 2))
            if self.max_sizes:
                mx = float(int(self.max_sizes[s]))
                hw = float(np.sqrt(mn_i * mx) / 2)
                half.append((hw, hw))
            for ar in self.aspect_ratios:
                if abs(ar - 1.0) >= 1e-6:
                    v = float(np.sqrt(ar))
                    half.append((mn_i * v / 2, mn_i / v / 2))
        return np.asarray(half, np.float32)  # (P, 2)

    def apply(self, params, state, input, *, training=False, rng=None):
        feat = input[0] if isinstance(input, (tuple, list)) else input
        layer_h, layer_w = feat.shape[2], feat.shape[3]
        assert self.img_w > 0 and self.img_h > 0, "imgW and imgH must > 0"
        step_w = self.step_w or self.img_w / float(layer_w)
        step_h = self.step_h or self.img_h / float(layer_h)

        half = self._cell_templates()                       # (P, 2)
        cx = (np.arange(layer_w, dtype=np.float32) + self.offset) * step_w
        cy = (np.arange(layer_h, dtype=np.float32) + self.offset) * step_h
        # (H, W, P, 4) ordered (h, w, prior) like the reference fill loop
        cx = cx[None, :, None]
        cy = cy[:, None, None]
        hw = half[None, None, :, 0]
        hh = half[None, None, :, 1]
        boxes = np.stack(
            [
                np.broadcast_to((cx - hw) / self.img_w, (layer_h, layer_w, hw.shape[-1])),
                np.broadcast_to((cy - hh) / self.img_h, (layer_h, layer_w, hw.shape[-1])),
                np.broadcast_to((cx + hw) / self.img_w, (layer_h, layer_w, hw.shape[-1])),
                np.broadcast_to((cy + hh) / self.img_h, (layer_h, layer_w, hw.shape[-1])),
            ],
            axis=-1,
        )
        dim = layer_h * layer_w * self.num_priors * 4
        flat = boxes.reshape(dim)
        if self.is_clip:
            flat = np.clip(flat, 0.0, 1.0)
        if len(self.variances) == 1:
            var = np.full((dim,), self.variances[0], np.float32)
        else:
            var = np.tile(np.asarray(self.variances, np.float32), dim // 4)
        out = jnp.asarray(np.stack([flat, var])[None, :, :])
        return out, state


# --------------------------------------------------------------------------- #
# Anchor (reference: nn/Anchor.scala:25)
# --------------------------------------------------------------------------- #

class Anchor:
    """Regular grid of multi-scale / multi-aspect anchors for RPN."""

    def __init__(self, ratios, scales, base_size=16.0):
        self.ratios = np.asarray(ratios, np.float32)
        self.scales = np.asarray(scales, np.float32)
        self.anchor_num = len(ratios) * len(scales)
        self.basic_anchors = self._generate_basic(base_size)  # (A, 4)

    def _generate_basic(self, base_size):
        # ratio enumeration around the (0, 0, base-1, base-1) window with the
        # reference's round-to-int semantics (Anchor.ratioEnum, Anchor.scala:195)
        w = h = base_size
        x_ctr = y_ctr = (base_size - 1) / 2
        area = w * h
        ws = np.round(np.sqrt(area / self.ratios))
        hs = np.round(ws * self.ratios)
        ratio_anchors = self._mk_anchors(ws, hs, x_ctr, y_ctr)
        out = []
        for ra in ratio_anchors:
            aw = ra[2] - ra[0] + 1
            ah = ra[3] - ra[1] + 1
            acx = ra[0] + 0.5 * (aw - 1)
            acy = ra[1] + 0.5 * (ah - 1)
            out.append(self._mk_anchors(self.scales * aw, self.scales * ah, acx, acy))
        return np.concatenate(out, axis=0).astype(np.float32)

    @staticmethod
    def _mk_anchors(ws, hs, x_ctr, y_ctr):
        w = ws / 2 - 0.5
        h = hs / 2 - 0.5
        return np.stack([x_ctr - w, y_ctr - h, x_ctr + w, y_ctr + h], axis=-1)

    def generate_anchors(self, width, height, feat_stride=16.0):
        """All anchors over a (height, width) feature map, ordered
        (y, x, anchor) like Anchor.getAllAnchors (Anchor.scala:76-115)."""
        shift_x = np.arange(width, dtype=np.float32) * feat_stride
        shift_y = np.arange(height, dtype=np.float32) * feat_stride
        shifts = np.stack(
            np.broadcast_arrays(
                shift_x[None, :, None], shift_y[:, None, None],
                shift_x[None, :, None], shift_y[:, None, None],
            ),
            axis=-1,
        )  # (H, W, 1, 4)
        all_anchors = shifts + self.basic_anchors[None, None, :, :]
        return all_anchors.reshape(-1, 4)


# --------------------------------------------------------------------------- #
# Proposal (reference: nn/Proposal.scala:34)
# --------------------------------------------------------------------------- #

class Proposal(Module):
    """RPN proposal layer: anchors + deltas -> scored, NMS'd RoIs.

    Input table: (cls scores (1, 2A, H, W), bbox deltas (1, 4A, H, W),
    im_info (1, 4) = [height, width, scale_h, scale_w]).
    Output (K, 5): rows [batch_idx=0, x1, y1, x2, y2].
    Forward-only (updateGradInput returns null in the reference).
    """

    def __init__(self, pre_nms_topn, post_nms_topn, ratios, scales,
                 rpn_pre_nms_topn_train=12000, rpn_post_nms_topn_train=2000,
                 min_size=16.0, name=None):
        super().__init__(name)
        self.pre_nms_topn = pre_nms_topn
        self.post_nms_topn = post_nms_topn
        self.rpn_pre_nms_topn_train = rpn_pre_nms_topn_train
        self.rpn_post_nms_topn_train = rpn_post_nms_topn_train
        self.anchor = Anchor(ratios, scales)
        self.min_size = min_size

    def apply(self, params, state, input, *, training=False, rng=None):
        scores_in, deltas_in, im_info = input
        assert scores_in.shape[0] == 1, "currently only support single batch"
        a = self.anchor.anchor_num
        h, w = scores_in.shape[2], scores_in.shape[3]
        # (1, 4A, H, W) -> (H*W*A, 4), row order (h, w, a)
        # (Proposal.transposeAndReshape, Proposal.scala:155)
        deltas = jnp.transpose(
            jnp.reshape(deltas_in[0], (a, 4, h, w)), (2, 3, 0, 1)
        ).reshape(-1, 4)
        # foreground scores = channels [A, 2A)
        scores = jnp.transpose(scores_in[0, a:], (1, 2, 0)).reshape(-1)

        anchors = self.anchor.generate_anchors(w, h)
        proposals = bbox_transform_inv(anchors, deltas)
        min_box_h = self.min_size * im_info[0, 2]
        min_box_w = self.min_size * im_info[0, 3]
        proposals, scores = clip_boxes(
            proposals, im_info[0, 0], im_info[0, 1], min_box_h, min_box_w, scores
        )
        pre_topn = self.rpn_pre_nms_topn_train if training else self.pre_nms_topn
        post_topn = self.rpn_post_nms_topn_train if training else self.post_nms_topn

        order, keep = nms(proposals, scores, 0.7, topk=pre_topn)
        order, keep = np.asarray(order), np.asarray(keep)
        kept = order[keep]
        if post_topn > 0:
            kept = kept[:post_topn]
        boxes = np.asarray(proposals)[kept]
        out = jnp.asarray(
            np.concatenate([np.zeros((boxes.shape[0], 1), np.float32), boxes], axis=1)
        )
        return out, state


# --------------------------------------------------------------------------- #
# NormalizeScale (reference: nn/NormalizeScale.scala:37)
# --------------------------------------------------------------------------- #

class NormalizeScale(Module):
    """L_p-normalise across the channel dim then multiply a learned
    per-channel scale (caffe Normalize; used for SSD conv4_3)."""

    def __init__(self, p=2.0, eps=1e-10, scale=20.0, size=None, name=None):
        super().__init__(name)
        self.p = p
        self.eps = eps
        self.init_scale = scale
        self.size = tuple(size) if size is not None else None

    def setup(self, rng, input_spec):
        size = self.size
        if size is None:
            size = (1, input_spec.shape[1], 1, 1)
        w = jnp.full(size, self.init_scale, jnp.float32)
        return {"weight": w}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        if self.p == 2.0:
            norm = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True)) + self.eps
        else:
            norm = jnp.power(
                jnp.sum(jnp.power(jnp.abs(x), self.p), axis=1, keepdims=True),
                1.0 / self.p,
            ) + self.eps
        return (x / norm) * params["weight"].astype(x.dtype), state


# --------------------------------------------------------------------------- #
# DetectionOutputSSD (reference: nn/DetectionOutputSSD.scala:48)
# --------------------------------------------------------------------------- #

class DetectionOutputSSD(Module):
    """SSD post-processing: decode loc preds against priors, per-class NMS,
    global keep-topk.

    Input table: (loc (B, P*4), conf (B, P*nClasses) logits, prior (1, 2, P*4)).
    Output (B, 1 + maxDet*6): per image [nDet, (label, score, x1, y1, x2, y2)*].
    In training mode passes input through, like the reference.
    """

    def __init__(self, n_classes=21, share_location=True, bg_label=0,
                 nms_thresh=0.45, nms_topk=400, keep_topk=200,
                 conf_thresh=0.01, variance_encoded_in_target=False,
                 conf_post_process=True, name=None):
        super().__init__(name)
        assert share_location, "only shareLocation=true is used by the zoo"
        self.n_classes = n_classes
        self.bg_label = bg_label
        self.nms_thresh = nms_thresh
        self.nms_topk = nms_topk
        self.keep_topk = keep_topk
        self.conf_thresh = conf_thresh
        self.variance_encoded_in_target = variance_encoded_in_target
        self.conf_post_process = conf_post_process

    def apply(self, params, state, input, *, training=False, rng=None):
        if training:
            return input, state
        loc, conf, prior = input
        batch = loc.shape[0]
        n_priors = prior.shape[2] // 4
        if self.conf_post_process:
            conf = jax.nn.softmax(
                conf.reshape(batch, n_priors, self.n_classes), axis=-1
            )
        else:
            conf = conf.reshape(batch, n_priors, self.n_classes)
        prior_boxes = prior[0, 0].reshape(n_priors, 4)
        prior_var = prior[0, 1].reshape(n_priors, 4)
        loc = loc.reshape(batch, n_priors, 4)

        # vectorised decode for the whole batch (device), then per-class NMS
        decoded = jax.vmap(
            lambda l: decode_boxes(
                prior_boxes, prior_var, l,
                variance_encoded_in_target=self.variance_encoded_in_target,
            )
        )(loc)
        decoded_np = np.asarray(decoded)
        conf_np = np.asarray(conf)

        results = []  # per image: list of (label, score, box) already NMS'd
        for b in range(batch):
            dets = []
            for c in range(self.n_classes):
                if c == self.bg_label:
                    continue
                scores_c = conf_np[b, :, c]
                kept = Nms().nms_fast(
                    scores_c, decoded_np[b], self.nms_thresh,
                    self.conf_thresh, topk=self.nms_topk, normalized=True,
                )
                for i in kept:
                    dets.append((c, scores_c[i], decoded_np[b, i]))
            if self.keep_topk > -1 and len(dets) > self.keep_topk:
                dets.sort(key=lambda t: -t[1])
                dets = dets[: self.keep_topk]
                # reference regroups by class after topk (stable class order)
                dets.sort(key=lambda t: t[0])
            results.append(dets)

        max_det = max((len(d) for d in results), default=0)
        out = np.zeros((batch, 1 + max_det * 6), np.float32)
        for b, dets in enumerate(results):
            out[b, 0] = len(dets)
            off = 1
            for (c, s, box) in dets:
                out[b, off:off + 6] = [c, s, box[0], box[1], box[2], box[3]]
                off += 6
        return jnp.asarray(out), state


class DetectionOutputFrcnn(Module):
    """Faster-RCNN post-processing (reference: nn/DetectionOutputFrcnn.scala:48).

    Input table: (cls scores (N, nClasses) softmax'd, bbox preds (N, 4*nClasses),
    rois (N, 5) [batch, x1, y1, x2, y2], im_info (1, 4)).
    Output (1, 1 + nDet*6) in the same layout as DetectionOutputSSD.
    """

    def __init__(self, nms_thresh=0.3, n_classes=21, bbox_vote=False,
                 max_per_image=100, thresh=0.05, name=None):
        super().__init__(name)
        assert not bbox_vote, "bboxVote not supported in the TPU build yet"
        self.nms_thresh = nms_thresh
        self.n_classes = n_classes
        self.max_per_image = max_per_image
        self.thresh = thresh

    def apply(self, params, state, input, *, training=False, rng=None):
        scores, box_deltas, rois, im_info = input
        boxes = rois[:, 1:5]
        pred = bbox_transform_inv(boxes, box_deltas)
        pred, _ = clip_boxes(pred, im_info[0, 0], im_info[0, 1])
        scores_np = np.asarray(scores)
        pred_np = np.asarray(pred).reshape(scores_np.shape[0], -1, 4)

        dets = []
        for c in range(1, self.n_classes):  # skip background class 0
            sc = scores_np[:, c]
            inds = np.where(sc > self.thresh)[0]
            if inds.size == 0:
                continue
            kept = Nms().nms(sc[inds], pred_np[inds, c], self.nms_thresh)
            for i in kept:
                dets.append((c, sc[inds[i]], pred_np[inds[i], c]))
        if self.max_per_image > 0 and len(dets) > self.max_per_image:
            dets.sort(key=lambda t: -t[1])
            dets = dets[: self.max_per_image]
            dets.sort(key=lambda t: t[0])

        out = np.zeros((1, 1 + len(dets) * 6), np.float32)
        out[0, 0] = len(dets)
        off = 1
        for (c, s, box) in dets:
            out[0, off:off + 6] = [c, s, box[0], box[1], box[2], box[3]]
            off += 6
        return jnp.asarray(out), state
