"""Weight initialization methods (reference: nn/InitializationMethod.scala)."""

import math

import jax
import jax.numpy as jnp


class InitializationMethod:
    def init(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        raise NotImplementedError


class Zeros(InitializationMethod):
    def init(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)


class Ones(InitializationMethod):
    def init(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.ones(shape, dtype)


class ConstInitMethod(InitializationMethod):
    def __init__(self, value):
        self.value = value

    def init(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class RandomUniform(InitializationMethod):
    """U(lower, upper); defaults to the Torch fan-in heuristic U(-1/sqrt(fan_in), ...)."""

    def __init__(self, lower=None, upper=None):
        self.lower, self.upper = lower, upper

    def init(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        if self.lower is None:
            bound = 1.0 / math.sqrt(max(fan_in, 1))
            lo, hi = -bound, bound
        else:
            lo, hi = self.lower, self.upper
        return jax.random.uniform(rng, shape, dtype, minval=lo, maxval=hi)


class RandomNormal(InitializationMethod):
    def __init__(self, mean=0.0, stdv=1.0):
        self.mean, self.stdv = mean, stdv

    def init(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return self.mean + self.stdv * jax.random.normal(rng, shape, dtype)


class Xavier(InitializationMethod):
    """Glorot uniform: U(+-sqrt(6/(fan_in+fan_out))) (reference default for conv/linear)."""

    def init(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        bound = math.sqrt(6.0 / max(fan_in + fan_out, 1))
        return jax.random.uniform(rng, shape, dtype, minval=-bound, maxval=bound)


class MsraFiller(InitializationMethod):
    """He/MSRA normal init (reference: nn/InitializationMethod.scala MsraFiller)."""

    def __init__(self, variance_norm_average=True):
        self.variance_norm_average = variance_norm_average

    def init(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        n = (fan_in + fan_out) / 2.0 if self.variance_norm_average else fan_in
        std = math.sqrt(2.0 / max(n, 1))
        return std * jax.random.normal(rng, shape, dtype)


class BilinearFiller(InitializationMethod):
    """Bilinear-interpolation kernel init for upsampling deconvolutions
    (reference: nn/InitializationMethod.scala:340 BilinearFiller, whose
    JVM weights are (..., kH, kW)).  THIS repo's conv weights are HWIO
    -- spatial axes FIRST (conv.py setup: (kh, kw, cin, cout)) -- so the
    (square) kernel is built over the LEADING two axes and broadcast
    across the channel axes."""

    def init(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        kh, kw = shape[0], shape[1]
        if kh != kw:
            raise ValueError(f"Kernel {kh} x {kw} must be square")
        f = int(jnp.ceil(kw / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        x = jnp.arange(kw, dtype=dtype)
        y = jnp.arange(kh, dtype=dtype)
        wx = 1.0 - jnp.abs(x / f - c)
        wy = 1.0 - jnp.abs(y / f - c)
        kernel = (wy[:, None] * wx[None, :]).reshape(
            (kh, kw) + (1,) * (len(shape) - 2))
        return jnp.broadcast_to(kernel, shape).astype(dtype)
