"""Shape-manipulation layers.

Reference: nn/Reshape.scala, nn/View.scala, nn/Squeeze.scala,
nn/Unsqueeze.scala, nn/Transpose.scala, nn/Select.scala, nn/Narrow.scala,
nn/InferReshape.scala, nn/Contiguous.scala, nn/Padding.scala.
All dims 0-based; batch axis is 0.
"""

import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class Reshape(Module):
    """Reshape the non-batch dims to ``size`` (reference: nn/Reshape.scala).

    ``batch_mode=None`` mirrors the reference's auto behaviour: the batch dim
    is preserved; with ``batch_mode=False`` the whole tensor (incl. batch) is
    reshaped.
    """

    def __init__(self, size, batch_mode=None, name=None):
        super().__init__(name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def apply(self, params, state, input, *, training=False, rng=None):
        if self.batch_mode is False:
            return jnp.reshape(input, self.size), state
        return jnp.reshape(input, (input.shape[0],) + self.size), state


class View(Reshape):
    """Reference: nn/View.scala -- same as Reshape with -1 inference allowed."""


class InferReshape(Module):
    """Reshape with -1 (infer) and 0 (copy input dim) entries
    (reference: nn/InferReshape.scala)."""

    def __init__(self, size, batch_mode=False, name=None):
        super().__init__(name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def apply(self, params, state, input, *, training=False, rng=None):
        in_shape = input.shape[1:] if self.batch_mode else input.shape
        out = [in_shape[i] if s == 0 else s for i, s in enumerate(self.size)]
        if self.batch_mode:
            out = [input.shape[0]] + out
        return jnp.reshape(input, tuple(out)), state


class Flatten(Module):
    """Collapse all non-batch dims (keras analogue; nn/keras/Flatten.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.reshape(input, (input.shape[0], -1)), state


class Squeeze(Module):
    def __init__(self, dim=None, name=None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.squeeze(input, axis=self.dim), state


class Unsqueeze(Module):
    def __init__(self, dim, name=None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.expand_dims(input, axis=self.dim), state


class Transpose(Module):
    """Swap listed axis pairs in order (reference: nn/Transpose.scala)."""

    def __init__(self, permutations, name=None):
        super().__init__(name)
        self.permutations = permutations

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        for a, b in self.permutations:
            x = jnp.swapaxes(x, a, b)
        return x, state


class Permute(Module):
    """Full axis permutation (keras analogue)."""

    def __init__(self, dims, name=None):
        super().__init__(name)
        self.dims = tuple(dims)

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.transpose(input, self.dims), state


class Select(Module):
    """Select index ``index`` along ``dim`` (reference: nn/Select.scala)."""

    def __init__(self, dim, index, name=None):
        super().__init__(name)
        self.dim = dim
        self.index = index

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.take(input, self.index, axis=self.dim), state


class Narrow(Module):
    """Slice ``length`` elements from ``offset`` along ``dim`` (reference: nn/Narrow.scala)."""

    def __init__(self, dim, offset, length, name=None):
        super().__init__(name)
        self.dim = dim
        self.offset = offset
        self.length = length

    def apply(self, params, state, input, *, training=False, rng=None):
        length = self.length
        if length < 0:
            length = input.shape[self.dim] - self.offset + 1 + length
        idx = [slice(None)] * input.ndim
        idx[self.dim] = slice(self.offset, self.offset + length)
        return input[tuple(idx)], state


class Contiguous(Module):
    """No-op on TPU (reference: nn/Contiguous.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input, state


class Padding(Module):
    """Zero-pad ``pad`` entries along ``dim`` (neg = before, pos = after)
    (reference: nn/Padding.scala)."""

    def __init__(self, dim, pad, value=0.0, name=None):
        super().__init__(name)
        self.dim = dim
        self.pad = pad
        self.value = value

    def apply(self, params, state, input, *, training=False, rng=None):
        cfg = [(0, 0)] * input.ndim
        cfg[self.dim] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(input, cfg, constant_values=self.value), state


class Replicate(Module):
    """Repeat the tensor ``n_features`` times along a new ``dim``
    (reference: nn/Replicate.scala)."""

    def __init__(self, n_features, dim=0, name=None):
        super().__init__(name)
        self.n_features = n_features
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.repeat(jnp.expand_dims(input, self.dim), self.n_features,
                          axis=self.dim), state


class Tile(Module):
    """Repeat the input ``copies`` times along ``dim``
    (reference: nn/Tile.scala -- output size along ``dim`` is
    ``copies * input_size[dim]``).  ``dim`` is 0-based here; the pyspark
    compat layer translates Torch's 1-based dims."""

    def __init__(self, dim=0, copies=2, name=None):
        super().__init__(name)
        if copies < 2:
            raise ValueError("copies should be at least 2")
        self.dim = int(dim)
        self.copies = int(copies)

    def apply(self, params, state, input, *, training=False, rng=None):
        reps = [1] * input.ndim
        reps[self.dim] = self.copies
        return jnp.tile(input, reps), state
