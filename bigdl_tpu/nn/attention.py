"""Attention and transformer blocks.

No reference analogue -- the reference is a pre-transformer codebase
(SURVEY.md section 5 'Long-context: Absent') -- but the north star requires
sequence-scale capability, so the transformer stack is first-class here.
Distribution: see parallel/ring_attention.py (sequence parallelism) and
parallel/tp.py (tensor parallelism).

Layout: (N, T, D); heads split last.  bf16-friendly: softmax in fp32.
"""

import math
import re
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.containers import (ScanLayers, resolve_checkpoint_policy,
                                     stack_layer_trees, unstack_layer_trees)
from bigdl_tpu.nn.initialization import Xavier, Zeros
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import Container, Module, child_rng
from bigdl_tpu.nn.normalization import Dropout, LayerNorm


def dot_product_attention(q, k, v, causal=False, mask=None, scale=None):
    """Plain attention; q,k,v (..., T, H, Dh) with heads on axis -2.

    Softmax runs in fp32 regardless of input dtype (bf16-safe).
    """
    *_, tq, h, d = q.shape
    scale = scale or (1.0 / math.sqrt(d))
    scores = jnp.einsum("...qhd,...khd->...hqk", q, k).astype(jnp.float32)
    scores = scores * scale
    if causal:
        tk = k.shape[-3]
        qpos = jnp.arange(tq)[:, None]
        kpos = jnp.arange(tk)[None, :]
        scores = jnp.where(kpos <= qpos, scores, -jnp.inf)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("...hqk,...khd->...qhd", weights, v)


class MultiHeadAttention(Module):
    """Self-attention with fused qkv projection (one big MXU matmul)."""

    def __init__(self, hidden_size: int, num_heads: int, causal: bool = False,
                 dropout: float = 0.0, seq_axis_name: Optional[str] = None,
                 seq_mode: str = "ring", use_flash: str = "auto", name=None):
        super().__init__(name)
        assert hidden_size % num_heads == 0
        assert seq_mode in ("ring", "ulysses")
        assert use_flash in ("auto", "never", "always", "interpret")
        self.hidden_size = hidden_size
        self.num_heads = num_heads
        self.head_dim = hidden_size // num_heads
        self.causal = causal
        self.dropout = dropout
        #: when set, apply() is assumed to run inside shard_map with the
        #: sequence sharded over this mesh axis; ``seq_mode`` picks the
        #: strategy: "ring" (ppermute K/V rotation) or "ulysses"
        #: (all-to-all head re-sharding, parallel/ulysses.py).
        self.seq_axis_name = seq_axis_name
        self.seq_mode = seq_mode
        #: "auto": the Pallas flash kernel (ops/flash_attention.py) on TPU
        #: when T is block-aligned; plain attention otherwise.  "interpret"
        #: forces the kernel in interpreter mode (CPU tests).
        self.use_flash = use_flash

    @staticmethod
    def _flash_block_ok(t):
        """Whether T tiles into flash blocks: the kernel's call site uses
        ``block_q = t`` for short sequences, so any sublane-aligned
        ``t < 128`` is block-alignable (a single (t, d) VMEM tile);
        longer sequences must tile exactly into 128-blocks.  (The old
        ``t % 128`` test rejected EVERY short sequence even though the
        kernel handles them -- tests/test_flash_attention.py pins the
        short-T flash-vs-plain agreement.)"""
        if t < 128:
            return t % 8 == 0
        return t % 128 == 0

    def _flash_ok(self, t):
        if self.use_flash == "never" or self.seq_axis_name is not None:
            return False
        if self.use_flash in ("always", "interpret"):
            return True
        if not self._flash_block_ok(t):
            return False
        try:
            return jax.devices()[0].platform == "tpu"
        except Exception:
            return False

    def setup(self, rng, input_spec):
        d = self.hidden_size
        init = Xavier()
        return {
            "qkv_weight": init.init(child_rng(rng, 0), (3 * d, d), d, d),
            "qkv_bias": jnp.zeros((3 * d,), jnp.float32),
            "out_weight": init.init(child_rng(rng, 1), (d, d), d, d),
            "out_bias": jnp.zeros((d,), jnp.float32),
        }, ()

    def _project_qkv(self, params, input):
        """Fused qkv projection; ONE implementation for the full-sequence
        and cached (prefill/decode) paths, so the int8 branch covers
        generation with no second code path."""
        dt = input.dtype
        if "qkv_weight_q" in params:
            # post-training-quantized projections (nn/quantized): the
            # fused qkv and output matmuls -- the layer's MXU work --
            # contract in int8; attention itself stays in the activation
            # dtype (softmax in fp32 as always)
            from bigdl_tpu.nn.quantized import int8_matmul

            return (int8_matmul(input, params["qkv_weight_q"],
                                params["qkv_scale"])
                    + params["qkv_bias"]).astype(dt)
        return input @ params["qkv_weight"].astype(dt).T \
            + params["qkv_bias"].astype(dt)

    def _project_out(self, params, y, dt):
        if "out_weight_q" in params:
            from bigdl_tpu.nn.quantized import int8_matmul

            return (int8_matmul(y, params["out_weight_q"],
                                params["out_scale"])
                    + params["out_bias"]).astype(dt)
        return y @ params["out_weight"].astype(dt).T \
            + params["out_bias"].astype(dt)

    # ----- KV-cache decode mode -------------------------------------------- #
    def init_cache(self, batch: int, max_len: int, dtype=jnp.float32):
        """Per-layer K/V buffers for autoregressive decode: fixed-shape
        ``(batch, max_len, heads, head_dim)`` zero tensors the cached
        ``apply`` fills with ``dynamic_update_slice`` writes.  Fixed
        shapes are the whole point -- every decode step reuses ONE
        compiled executable regardless of how many tokens are live
        (docs/performance.md, "Generation serving")."""
        shape = (batch, int(max_len), self.num_heads, self.head_dim)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def _flash_decode_ok(self, max_len):
        if self.use_flash == "never" or self.seq_axis_name is not None:
            return False
        # the decode kernel tiles the cache with block_k = min(128,
        # max_len): a cache at or under 128 is one block, a longer one
        # must tile exactly -- this gates the FORCED modes too, or an
        # unaligned decode_max_len would trip the kernel's assert on
        # every tick instead of quietly taking the plain path
        if max_len > 128 and max_len % 128:
            return False
        if self.use_flash in ("always", "interpret"):
            return True
        try:
            return jax.devices()[0].platform == "tpu"
        except Exception:
            return False

    def _apply_cached(self, params, input, cache, pos):
        """Incremental attention against a K/V cache.

        Two shapes, one contract (returns ``(y, new_cache)``):

        - PREFILL (``pos is None``): ``input`` is the whole (padded)
          prompt ``(N, T, D)``; K/V are written at positions ``[0, T)``
          and attention is plain causal over the prompt itself --
          identical math to the full-sequence path, so prefill logits
          ARE full-forward logits.
        - DECODE (``pos`` an ``(N,)`` int vector): ``input`` is ONE
          token per row ``(N, 1, D)``; row ``i``'s K/V land at
          ``pos[i]`` (a per-row ``dynamic_update_slice``) and attention
          masks ``kpos <= pos[i]``, so stale positions beyond the
          frontier -- a previous occupant's K/V, or prompt padding not
          yet overwritten -- are invisible until the decode write that
          replaces them makes them real.  Rows only ever write their
          OWN cache row, which is what lets a slot scheduler run
          inactive slots as harmless garbage instead of recompiling.
        """
        n, t, d = input.shape
        dt = input.dtype
        qkv = self._project_qkv(params, input)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (n, t, self.num_heads, self.head_dim)
        q, k, v = q.reshape(shape), k.reshape(shape), v.reshape(shape)
        cdt = cache["k"].dtype
        if pos is None:                                   # prefill
            max_len = cache["k"].shape[1]
            if t > max_len:
                raise ValueError(
                    f"prompt length {t} exceeds the cache's max_len "
                    f"{max_len}")
            new_cache = {"k": cache["k"].at[:, :t].set(k.astype(cdt)),
                         "v": cache["v"].at[:, :t].set(v.astype(cdt))}
            # forced flash modes bypass _flash_ok's block gate, but a
            # prompt rung that doesn't tile (e.g. an unaligned
            # decode_max_len on the ladder) would trip the kernel's
            # shape assert on every prefill -- take the plain path
            if self._flash_ok(t) and self._flash_block_ok(t):
                from bigdl_tpu.ops.flash_attention import flash_attention

                bq = t if t < 128 else 128
                y = flash_attention(q, k, v, causal=self.causal,
                                    block_q=bq, block_k=bq,
                                    interpret=self.use_flash == "interpret")
            else:
                y = dot_product_attention(q, k, v, causal=self.causal)
        else:                                             # one-token step
            if t != 1:
                raise ValueError(
                    f"decode steps take one token per row, got T={t}")
            pos = jnp.asarray(pos, jnp.int32)
            write = jax.vmap(
                lambda c, new, p: jax.lax.dynamic_update_slice(
                    c, new, (p, 0, 0)))
            new_cache = {"k": write(cache["k"], k.astype(cdt), pos),
                         "v": write(cache["v"], v.astype(cdt), pos)}
            max_len = cache["k"].shape[1]
            if self._flash_decode_ok(max_len):
                from bigdl_tpu.ops.flash_attention import \
                    flash_decode_attention

                y = flash_decode_attention(
                    q, new_cache["k"].astype(dt), new_cache["v"].astype(dt),
                    pos, interpret=self.use_flash == "interpret")
            else:
                # scores (N, H, 1, max_len); the position mask broadcasts
                # over heads and the single query row
                mask = (jnp.arange(max_len)[None, :]
                        <= pos[:, None])[:, None, None, :]
                y = dot_product_attention(q, new_cache["k"].astype(dt),
                                          new_cache["v"].astype(dt),
                                          mask=mask)
        y = y.reshape(n, t, d)
        return self._project_out(params, y, dt), new_cache

    # ----- paged KV-cache decode mode --------------------------------------- #
    def init_paged_cache(self, num_blocks: int, block_size: int,
                         dtype=jnp.float32):
        """Per-layer K/V BLOCK POOL for paged decode: fixed-shape
        ``(num_blocks, block_size, heads, head_dim)`` zero tensors that
        ``_apply_paged`` reads and writes THROUGH per-sequence block
        tables (serving/paging.py).  Unlike ``init_cache`` the leading
        axis is physical blocks, not slots: memory scales with tokens
        actually resident, not ``slots x max_len`` worst case.  The
        caller includes the trash block in ``num_blocks`` (by
        convention the last id).

        ``dtype=jnp.int8`` selects the QUANTIZED block layout: int8
        K/V payloads plus fp32 absmax scales -- one scale per (position,
        head) ``head_dim`` vector, i.e. the ops/quantization.py
        blockwise format with the quantization block = ``head_dim``.
        The scale leaves keep the payload's 4-D ``(blocks, block_size,
        heads, 1)`` rank so every pool consumer that tree-maps by rank
        (block copies, donation, byte accounting) handles both layouts
        with one code path."""
        shape = (int(num_blocks), int(block_size), self.num_heads,
                 self.head_dim)
        if jnp.dtype(dtype) == jnp.dtype(jnp.int8):
            sshape = shape[:-1] + (1,)
            return {"k": jnp.zeros(shape, jnp.int8),
                    "v": jnp.zeros(shape, jnp.int8),
                    "k_scale": jnp.zeros(sshape, jnp.float32),
                    "v_scale": jnp.zeros(sshape, jnp.float32)}
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def _paged_quant(self, x):
        """fp K/V vectors ``(..., heads, head_dim)`` -> (int8 payload,
        fp32 scales ``(..., heads, 1)``) through the blockwise wire
        kernel (one absmax scale per head_dim vector; non-finite
        vectors drop to exact zero, same contract as the wire path)."""
        from bigdl_tpu.ops.quantization import quantize_blockwise

        q8, sc = quantize_blockwise(x.reshape(-1), self.head_dim,
                                    scale_dtype=jnp.float32)
        return q8.reshape(x.shape), sc.reshape(x.shape[:-1] + (1,))

    def _paged_dequant(self, q8, sc, dt):
        """Inverse of ``_paged_quant`` over gathered context blocks:
        ``(..., heads, head_dim)`` int8 + ``(..., heads, 1)`` scales ->
        ``dt`` values."""
        from bigdl_tpu.ops.quantization import dequantize_blockwise

        lead = q8.shape[:-2]
        flat = q8.reshape(lead + (q8.shape[-2] * q8.shape[-1],))
        out = dequantize_blockwise(flat, sc.reshape(lead + (-1,)),
                                   self.head_dim)
        return out.reshape(q8.shape).astype(dt)

    def _flash_paged_ok(self, block_size):
        if self.use_flash == "never" or self.seq_axis_name is not None:
            return False
        if self.use_flash in ("always", "interpret"):
            return True
        # on real TPU the paged kernel walks the pool in block_size
        # strides; tiny blocks (the useful CPU/bench sizes) are far
        # below the 128-lane tile, so auto mode only takes the kernel
        # when blocks themselves tile
        if block_size % 128:
            return False
        try:
            return jax.devices()[0].platform == "tpu"
        except Exception:
            return False

    def _apply_paged(self, params, input, pool, tables, pos, lengths):
        """Incremental attention against a paged K/V pool.  Returns
        ``(y, new_pool)``.  ``tables`` maps each row's LOGICAL block
        index to a physical pool block, padded with the trash block id
        (the pool's last block), so the compiled step never sees how
        long any sequence really is.

        Two shapes, mirroring ``_apply_cached``:

        - CHUNK PREFILL (``lengths`` an ``(N,)`` int vector): ``input``
          is one fixed-size chunk per row ``(N, Tc, D)`` whose first
          ``lengths[i]`` tokens are real and start at absolute position
          ``pos[i]``; K/V scatter token-by-token through the table
          (padding tokens redirect to the trash block) and attention
          gathers the row's FULL mapped context, masked causally at
          each token's absolute position -- so a chunk attends to all
          previously-filled blocks (including shared prefix blocks it
          never computed) plus its own earlier tokens.
        - DECODE (``lengths is None``): ``input`` is one token per row
          ``(N, 1, D)`` written at ``pos[i]``; rows whose table is all
          trash (empty slots, rows mid-prefill) write garbage into the
          trash block and read garbage out -- harmless by the same
          frontier argument as the contiguous slot pool.
        """
        n, t, d = input.shape
        dt = input.dtype
        cdt = pool["k"].dtype
        quant = "k_scale" in pool      # int8 payload + fp32 scale leaves
        bs = pool["k"].shape[1]
        max_blocks = tables.shape[1]
        trash = pool["k"].shape[0] - 1
        tables = jnp.asarray(tables, jnp.int32)
        pos = jnp.asarray(pos, jnp.int32)
        qkv = self._project_qkv(params, input)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (n, t, self.num_heads, self.head_dim)
        q, k, v = q.reshape(shape), k.reshape(shape), v.reshape(shape)

        def scatter(phys, off, kf, vf):
            """Write one batch of K/V rows through the table: quantize
            first on an int8 pool (payload + scales land at the same
            (block, offset) address, so the table indirection, COW block
            copies and prefix sharing are format-blind)."""
            if quant:
                kq, ksc = self._paged_quant(kf)
                vq, vsc = self._paged_quant(vf)
                return {"k": pool["k"].at[phys, off].set(kq),
                        "v": pool["v"].at[phys, off].set(vq),
                        "k_scale": pool["k_scale"].at[phys, off].set(ksc),
                        "v_scale": pool["v_scale"].at[phys, off].set(vsc)}
            return {"k": pool["k"].at[phys, off].set(kf.astype(cdt)),
                    "v": pool["v"].at[phys, off].set(vf.astype(cdt))}

        def gather_ctx(new_pool, name):
            """The row's full mapped context from the pool, dequantized
            to the compute dtype on an int8 pool."""
            ctx = max_blocks * bs
            raw = jnp.take(new_pool[name], tables, axis=0).reshape(
                n, ctx, self.num_heads, self.head_dim)
            if quant:
                sc = jnp.take(new_pool[name + "_scale"], tables,
                              axis=0).reshape(n, ctx, self.num_heads, 1)
                return self._paged_dequant(raw, sc, dt)
            return raw.astype(dt)

        if lengths is not None:                           # chunk prefill
            lengths = jnp.asarray(lengths, jnp.int32)
            gpos = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
            valid = jnp.arange(t, dtype=jnp.int32)[None, :] \
                < lengths[:, None]
            logical = jnp.clip(gpos // bs, 0, max_blocks - 1)
            phys = jnp.take_along_axis(tables, logical, axis=1)
            phys = jnp.where(valid, phys, trash)
            off = gpos % bs
            flat = (n * t,)
            new_pool = scatter(phys.reshape(flat), off.reshape(flat),
                               k.reshape(flat + shape[2:]),
                               v.reshape(flat + shape[2:]))
            ctx = max_blocks * bs
            ctx_k = gather_ctx(new_pool, "k")
            ctx_v = gather_ctx(new_pool, "v")
            # (N, 1, Tc, ctx): key at logical position kp is visible to
            # the chunk token at absolute position gpos iff kp <= gpos
            mask = (jnp.arange(ctx, dtype=jnp.int32)[None, None, :]
                    <= gpos[:, :, None])[:, None]
            y = dot_product_attention(q, ctx_k, ctx_v, mask=mask)
        else:                                             # one-token step
            if t != 1:
                raise ValueError(
                    f"paged decode steps take one token per row, got T={t}")
            phys = jnp.take_along_axis(
                tables, (pos // bs)[:, None], axis=1)[:, 0]
            off = pos % bs
            new_pool = scatter(phys, off, k[:, 0], v[:, 0])
            if self._flash_paged_ok(bs):
                from bigdl_tpu.ops.flash_attention import \
                    flash_paged_decode_attention

                if quant:
                    y = flash_paged_decode_attention(
                        q, new_pool["k"], new_pool["v"], tables, pos,
                        k_scale=new_pool["k_scale"],
                        v_scale=new_pool["v_scale"],
                        interpret=self.use_flash == "interpret")
                else:
                    y = flash_paged_decode_attention(
                        q, new_pool["k"].astype(dt),
                        new_pool["v"].astype(dt), tables, pos,
                        interpret=self.use_flash == "interpret")
                y = y.astype(dt)
            else:
                ctx = max_blocks * bs
                ctx_k = gather_ctx(new_pool, "k")
                ctx_v = gather_ctx(new_pool, "v")
                mask = (jnp.arange(ctx, dtype=jnp.int32)[None, :]
                        <= pos[:, None])[:, None, None, :]
                y = dot_product_attention(q, ctx_k, ctx_v, mask=mask)
        y = y.reshape(n, t, d)
        return self._project_out(params, y, dt), new_pool

    def apply(self, params, state, input, *, training=False, rng=None,
              cache=None, pos=None):
        if cache is not None:
            # decode mode returns (output, updated_cache) -- the cache
            # takes the state slot (these eval-mode paths carry no
            # module state); see _apply_cached
            return self._apply_cached(params, input, cache, pos)
        n, t, d = input.shape
        dt = input.dtype
        qkv = self._project_qkv(params, input)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (n, t, self.num_heads, self.head_dim)
        if self.seq_axis_name is not None and self.seq_mode == "ulysses":
            from bigdl_tpu.parallel.ulysses import ulysses_self_attention

            y = ulysses_self_attention(q.reshape(shape), k.reshape(shape),
                                       v.reshape(shape), self.seq_axis_name,
                                       causal=self.causal)
        elif self.seq_axis_name is not None:
            from bigdl_tpu.parallel.ring_attention import ring_self_attention

            y = ring_self_attention(q.reshape(shape), k.reshape(shape),
                                    v.reshape(shape), self.seq_axis_name,
                                    causal=self.causal)
        elif self._flash_ok(t):
            from bigdl_tpu.ops.flash_attention import flash_attention

            bq = t if t < 128 else 128
            y = flash_attention(q.reshape(shape), k.reshape(shape),
                                v.reshape(shape), causal=self.causal,
                                block_q=bq, block_k=bq,
                                interpret=self.use_flash == "interpret")
        else:
            y = dot_product_attention(q.reshape(shape), k.reshape(shape),
                                      v.reshape(shape), causal=self.causal)
        y = self._project_out(params, y.reshape(n, t, d), dt)
        if training and self.dropout > 0 and rng is not None:
            keep = 1.0 - self.dropout
            y = jnp.where(jax.random.bernoulli(rng, keep, y.shape),
                          y / keep, 0.0).astype(dt)
        return y, state


class TransformerBlock(Container):
    """Pre-LN block: x + MHA(LN(x)); x + MLP(LN(x))."""

    def __init__(self, hidden_size, num_heads, mlp_ratio=4, causal=True,
                 dropout=0.0, seq_axis_name=None, seq_mode="ring", name=None):
        super().__init__(name)
        self.ln1 = LayerNorm(hidden_size)
        self.attn = MultiHeadAttention(hidden_size, num_heads, causal, dropout,
                                       seq_axis_name, seq_mode)
        self.ln2 = LayerNorm(hidden_size)
        self.fc1 = Linear(hidden_size, mlp_ratio * hidden_size)
        self.fc2 = Linear(mlp_ratio * hidden_size, hidden_size)
        for m in (self.ln1, self.attn, self.ln2, self.fc1, self.fc2):
            self.add(m)

    def setup(self, rng, input_spec):
        params = {}
        for i, (key, m) in enumerate(
                [("ln1", self.ln1), ("attn", self.attn), ("ln2", self.ln2),
                 ("fc1", self.fc1), ("fc2", self.fc2)]):
            p, _ = m.setup(child_rng(rng, i), input_spec)
            params[key] = p
        return params, ()

    def _param_child_items(self, params):
        # params are keyed by ROLE ("ln1".."fc2"), not by child index --
        # align accordingly so the frozen-mask and quantizer walks reach
        # the right sublayers
        return [("ln1", self.ln1), ("attn", self.attn), ("ln2", self.ln2),
                ("fc1", self.fc1), ("fc2", self.fc2)]

    def init_cache(self, batch: int, max_len: int, dtype=jnp.float32):
        """This block's K/V decode cache (the attention sublayer's)."""
        return self.attn.init_cache(batch, max_len, dtype)

    def init_paged_cache(self, num_blocks: int, block_size: int,
                         dtype=jnp.float32):
        """This block's paged K/V pool (the attention sublayer's)."""
        return self.attn.init_paged_cache(num_blocks, block_size, dtype)

    def apply_paged(self, params, input, pool, tables, pos, lengths=None):
        """Paged prefill-chunk/decode through this block; returns
        ``(out, new_pool)`` (see MultiHeadAttention._apply_paged)."""
        h, _ = self.ln1.apply(params["ln1"], (), input)
        a, new_pool = self.attn._apply_paged(params["attn"], h, pool,
                                             tables, pos, lengths)
        x = input + a
        h, _ = self.ln2.apply(params["ln2"], (), x)
        h, _ = self.fc1.apply(params["fc1"], (), h)
        h = jax.nn.gelu(h)
        h, _ = self.fc2.apply(params["fc2"], (), h)
        return x + h, new_pool

    def apply(self, params, state, input, *, training=False, rng=None,
              cache=None, pos=None):
        if cache is not None:
            # cached prefill/decode: eval-mode block, returns
            # (out, new_cache) like MultiHeadAttention's cached apply
            h, _ = self.ln1.apply(params["ln1"], (), input)
            a, new_cache = self.attn.apply(params["attn"], (), h,
                                           cache=cache, pos=pos)
            x = input + a
            h, _ = self.ln2.apply(params["ln2"], (), x)
            h, _ = self.fc1.apply(params["fc1"], (), h)
            h = jax.nn.gelu(h)
            h, _ = self.fc2.apply(params["fc2"], (), h)
            return x + h, new_cache
        h, _ = self.ln1.apply(params["ln1"], (), input)
        a, _ = self.attn.apply(params["attn"], (), h, training=training,
                               rng=child_rng(rng, 0))
        x = input + a
        h, _ = self.ln2.apply(params["ln2"], (), x)
        h, _ = self.fc1.apply(params["fc1"], (), h)
        h = jax.nn.gelu(h)
        h, _ = self.fc2.apply(params["fc2"], (), h)
        return x + h, state


class TransformerLM(Container):
    """Decoder-only LM: embed + blocks + LN + tied-free head.

    The long-context flagship; pairs with sequence parallelism
    (parallel/ring_attention.py) for T beyond one chip's HBM.

    ``scan_layers=True`` runs the N structurally-identical blocks as ONE
    ``lax.scan`` over LAYER-STACKED params (``nn.ScanLayers``): XLA
    compiles the block body once instead of N times, so jit-compile wall
    time drops roughly N-fold at the deep configs (docs/performance.md,
    "Step-time campaign").  Params then carry one ``"blocks"`` entry
    (every leaf gains a leading num_layers axis) instead of
    ``"block0"``..``"block{N-1}"``; ``stack_block_params`` /
    ``unstack_block_params`` interconvert the two layouts, so stacked
    and unrolled checkpoints are mutually loadable.  Initialization is
    BIT-IDENTICAL across the two modes (per-block setup keys are derived
    the same way, then stacked), as is the per-block dropout rng
    derivation -- scan and unrolled runs from one seed produce the same
    losses.

    ``remat_policy`` names a ``jax.checkpoint_policies`` entry
    (``"nothing_saveable"`` / ``"dots_saveable"`` / None = save block
    inputs only) applied per block during training: per-scan-iteration
    under ``scan_layers``, as a ``jax.checkpoint`` wrapper around each
    unrolled block otherwise (no param-keying change either way).
    """

    def __init__(self, vocab_size, hidden_size, num_heads, num_layers,
                 max_len=2048, mlp_ratio=4, seq_axis_name=None,
                 seq_mode="ring", scan_layers=False, remat_policy=None,
                 name=None):
        super().__init__(name)
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.max_len = max_len
        self.seq_axis_name = seq_axis_name
        self.scan_layers = scan_layers
        resolve_checkpoint_policy(remat_policy)  # unknown names fail HERE
        self.remat_policy = remat_policy
        self.blocks = [TransformerBlock(hidden_size, num_heads, mlp_ratio,
                                        seq_axis_name=seq_axis_name,
                                        seq_mode=seq_mode)
                       for _ in range(num_layers)]
        self.ln_f = LayerNorm(hidden_size)
        if scan_layers:
            self.scan = ScanLayers(self.blocks, policy=remat_policy)
            self.add(self.scan)
        else:
            self.scan = None
            for b in self.blocks:
                self.add(b)
        self.add(self.ln_f)

    def setup(self, rng, input_spec):
        d = self.hidden_size
        params = {
            "wte": 0.02 * jax.random.normal(child_rng(rng, 0),
                                            (self.vocab_size, d)),
            "wpe": 0.01 * jax.random.normal(child_rng(rng, 1),
                                            (self.max_len, d)),
            "head": 0.02 * jax.random.normal(child_rng(rng, 2),
                                             (self.vocab_size, d)),
        }
        hid_spec = jax.ShapeDtypeStruct(
            (input_spec.shape[0], input_spec.shape[1], d), jnp.float32)
        # per-block init keys are derived identically in both layouts, so
        # scan and unrolled models from one seed start bit-identical
        block_params = [b.setup(child_rng(rng, 3 + i), hid_spec)[0]
                        for i, b in enumerate(self.blocks)]
        if self.scan_layers:
            params["blocks"] = stack_layer_trees(block_params)
        else:
            for i, p in enumerate(block_params):
                params[f"block{i}"] = p
        params["ln_f"], _ = self.ln_f.setup(child_rng(rng, 99), hid_spec)
        return params, ()

    def _param_child_items(self, params):
        # params are keyed "block{i}" (unrolled) or "blocks" (the
        # scan-stacked layout, routed to the ScanLayers child) plus
        # "ln_f"; wte/wpe/head are this module's OWN leaves and align to
        # no child (they stay fp32 under the quantizer walk)
        items = [("ln_f", self.ln_f)]
        if self.scan is not None:
            items.append(("blocks", self.scan))
        else:
            items.extend((f"block{i}", b)
                         for i, b in enumerate(self.blocks))
        return items

    # ----- KV-cache decode mode -------------------------------------------- #
    def init_cache(self, batch: int, max_len: Optional[int] = None,
                   dtype=jnp.float32):
        """Per-layer K/V decode buffers in THIS model's param layout:
        unrolled models return ``{"block{i}": {"k", "v"}}``;
        ``scan_layers`` models return ``{"blocks": {"k", "v"}}`` with
        every leaf gaining a leading layer axis (``stack_layer_trees``,
        the same convention the params use), so the decode loop scans
        layers exactly like the forward does.  ``max_len`` caps how far
        a sequence can ever grow (prompt + generated tokens) and is the
        fixed time extent of every buffer; it defaults to the model's
        ``max_len`` but serving usually passes something smaller --
        cache bytes scale linearly with it."""
        max_len = self.max_len if max_len is None else int(max_len)
        if max_len > self.max_len:
            raise ValueError(
                f"cache max_len {max_len} exceeds the model's positional "
                f"table ({self.max_len})")
        per_block = [b.init_cache(batch, max_len, dtype)
                     for b in self.blocks]
        if self.scan_layers:
            return {"blocks": stack_layer_trees(per_block)}
        return {f"block{i}": c for i, c in enumerate(per_block)}

    def init_paged_cache(self, num_blocks: int, block_size: int,
                         dtype=jnp.float32):
        """Per-layer paged K/V pools in THIS model's param layout
        (``"block{i}"`` unrolled / stacked ``"blocks"`` under
        ``scan_layers``, mirroring ``init_cache``).  ``num_blocks`` is
        the allocator's pool size; every layer gets ONE EXTRA block on
        top -- the TRASH block, id ``num_blocks`` -- that padded table
        entries and inactive rows write into (serving/paging.py)."""
        per_block = [b.init_paged_cache(int(num_blocks) + 1, block_size,
                                        dtype)
                     for b in self.blocks]
        if self.scan_layers:
            return {"blocks": stack_layer_trees(per_block)}
        return {f"block{i}": c for i, c in enumerate(per_block)}

    def apply_paged(self, params, input, pool, tables, *, pos,
                    lengths=None):
        """Paged generation step: chunk prefill (``lengths`` given,
        ``input`` ``(N, Tc)`` token chunks starting at absolute
        positions ``pos``) or single-token decode (``lengths=None``,
        ``input`` ``(N, 1)`` at per-row ``pos``).  K/V live in the
        block pools from ``init_paged_cache`` and every row addresses
        them through its padded block-table row -- the shapes the
        executable sees never depend on sequence length, block
        residency, or how a prompt was chunked.  Returns ``(logits,
        new_pool)``."""
        if self.seq_axis_name is not None:
            raise ValueError("cached decode runs on a replicated model; "
                             "sequence-parallel serving is not a thing "
                             "(shard the BATCH axis instead)")
        t = input.shape[1]
        pos = jnp.asarray(pos, jnp.int32)
        x = jnp.take(params["wte"], input.astype(jnp.int32), axis=0)
        if lengths is not None:
            # absolute position of each chunk token; jnp.take clips, so
            # padding tokens past max_len just reuse the last wpe row
            # (they write to trash and are never read)
            gpos = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
            x = x + jnp.take(params["wpe"], gpos, axis=0)
        else:
            x = x + jnp.take(params["wpe"], pos, axis=0)[:, None, :]
        if self.scan_layers:
            inner = self.blocks[0]

            def body(h, sliced):
                p, c = sliced
                y, nc = inner.apply_paged(p, h, c, tables, pos, lengths)
                return y, nc

            x, stacked = jax.lax.scan(
                body, x, (params["blocks"], pool["blocks"]))
            new_pool = {"blocks": stacked}
        else:
            new_pool = {}
            for i, b in enumerate(self.blocks):
                x, nc = b.apply_paged(params[f"block{i}"], x,
                                      pool[f"block{i}"], tables, pos,
                                      lengths)
                new_pool[f"block{i}"] = nc
        x, _ = self.ln_f.apply(params["ln_f"], (), x)
        return x @ params["head"].astype(x.dtype).T, new_pool

    def _apply_cached(self, params, input, cache, pos):
        """Prefill (``pos=None``: whole padded prompt, K/V written at
        ``[0, T)``) or single-token decode (``pos`` (N,): one token per
        row at per-row positions).  Returns ``(logits, new_cache)``.
        Ragged prompts ride the prefill contract: pad the prompt batch
        to one length, prefill once, and read each row's logits at its
        TRUE ``length - 1`` -- padding positions hold garbage K/V that
        the decode frontier mask keeps invisible until the step that
        overwrites them (see MultiHeadAttention._apply_cached)."""
        if self.seq_axis_name is not None:
            raise ValueError("cached decode runs on a replicated model; "
                             "sequence-parallel serving is not a thing "
                             "(shard the BATCH axis instead)")
        t = input.shape[1]
        x = jnp.take(params["wte"], input.astype(jnp.int32), axis=0)
        if pos is None:
            x = x + params["wpe"][:t][None]
        else:
            pos = jnp.asarray(pos, jnp.int32)
            # jnp.take clips out-of-range rows; an inactive slot's
            # clamped position writes only into its own dead cache row
            x = x + jnp.take(params["wpe"], pos, axis=0)[:, None, :]
        if self.scan_layers:
            inner = self.blocks[0]

            def body(h, sliced):
                p, c = sliced
                y, nc = inner.apply(p, (), h, cache=c, pos=pos)
                return y, nc

            x, stacked = jax.lax.scan(
                body, x, (params["blocks"], cache["blocks"]))
            new_cache = {"blocks": stacked}
        else:
            new_cache = {}
            for i, b in enumerate(self.blocks):
                x, nc = b.apply(params[f"block{i}"], (), x,
                                cache=cache[f"block{i}"], pos=pos)
                new_cache[f"block{i}"] = nc
        x, _ = self.ln_f.apply(params["ln_f"], (), x)
        return x @ params["head"].astype(x.dtype).T, new_cache

    def apply(self, params, state, input, *, training=False, rng=None,
              cache=None, pos=None):
        if cache is not None:
            return self._apply_cached(params, input, cache, pos)
        t = input.shape[1]
        x = jnp.take(params["wte"], input.astype(jnp.int32), axis=0)
        if self.seq_axis_name is not None:
            # inside shard_map the block holds T_local tokens; use global
            # positions derived from the device's ring index
            offset = jax.lax.axis_index(self.seq_axis_name) * t
            pos = offset + jnp.arange(t)
            x = x + jnp.take(params["wpe"], pos, axis=0)[None]
        else:
            x = x + params["wpe"][:t][None]
        if self.scan_layers:
            # one scanned block body; layer i draws fold_in(rng, i), the
            # same per-block key derivation as the unrolled loop below
            x, _ = self.scan.apply(params["blocks"], (), x,
                                   training=training, rng=rng)
        else:
            policy = self.remat_policy
            for i, b in enumerate(self.blocks):
                key = child_rng(rng, i)
                if training and policy is not None:
                    # functional remat wrapper: same params keying, the
                    # block's forward re-runs in backward under the policy
                    def f(p, h, _b=b, _key=key):
                        return _b.apply(p, (), h, training=True,
                                        rng=_key)[0]
                    x = jax.checkpoint(
                        f, policy=resolve_checkpoint_policy(policy))(
                        params[f"block{i}"], x)
                else:
                    x, _ = b.apply(params[f"block{i}"], (), x,
                                   training=training, rng=key)
        x, _ = self.ln_f.apply(params["ln_f"], (), x)
        logits = x @ params["head"].astype(x.dtype).T
        return logits, state


#: matches the unrolled per-block param keys ("block0".."block{N-1}")
_BLOCK_KEY = re.compile(r"^block(\d+)$")


def stack_block_params(params):
    """Unrolled ``TransformerLM`` params (``"block{i}"`` keys) -> the
    ``scan_layers`` layout (one ``"blocks"`` entry, every leaf stacked
    along a new leading layer axis).  Non-block entries (wte/wpe/head/
    ln_f) pass through unchanged; this is the checkpoint import path
    into a scan model (docs/performance.md, "Step-time campaign")."""
    idx = sorted(int(m.group(1)) for k in params
                 if (m := _BLOCK_KEY.match(k)))
    if not idx:
        raise ValueError("no 'block{i}' entries to stack (already the "
                         "scan layout?)")
    if idx != list(range(len(idx))):
        raise ValueError(f"non-contiguous block indices {idx}")
    out = {k: v for k, v in params.items() if not _BLOCK_KEY.match(k)}
    out["blocks"] = stack_layer_trees(
        [params[f"block{i}"] for i in idx])
    return out


def unstack_block_params(params):
    """Scan-layout ``TransformerLM`` params (stacked ``"blocks"``) ->
    the unrolled ``"block{i}"`` keying -- the checkpoint export path
    back to per-layer keys (what quantize/regularizer traversals and
    per-layer resharding address)."""
    if "blocks" not in params:
        raise ValueError("no 'blocks' entry to unstack (already the "
                         "unrolled layout?)")
    out = {k: v for k, v in params.items() if k != "blocks"}
    for i, p in enumerate(unstack_layer_trees(params["blocks"])):
        out[f"block{i}"] = p
    return out
