"""Containers and table ops.

Reference: nn/Sequential.scala:31, nn/Concat.scala, nn/ConcatTable.scala,
nn/ParallelTable.scala, nn/CAddTable.scala and friends.  Tables are Python
tuples of arrays.  All dimension indices are 0-based (Python idiom; the
reference is 1-based Torch convention).
"""

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Module, child_rng


class Sequential(Container):
    """Feed-forward chain (reference: nn/Sequential.scala:31)."""

    def setup(self, rng, input_spec):
        params, state = {}, {}
        spec = input_spec
        for i, layer in enumerate(self.modules):
            p, s = layer.setup(child_rng(rng, i), spec)
            params[str(i)], state[str(i)] = p, s
            spec = layer.output_spec(p, s, spec)
        return params, state

    def apply(self, params, state, input, *, training=False, rng=None):
        new_state = dict(state)
        x = input
        for i, layer in enumerate(self.modules):
            x, s = layer.apply(
                params[str(i)], state[str(i)], x,
                training=training, rng=child_rng(rng, i),
            )
            new_state[str(i)] = s
        return x, new_state


class _Branching(Container):
    """Shared setup for containers whose children all see the same spec."""

    def _branch_spec(self, input_spec, i):
        raise NotImplementedError

    def setup(self, rng, input_spec):
        params, state = {}, {}
        for i, layer in enumerate(self.modules):
            p, s = layer.setup(child_rng(rng, i), self._branch_spec(input_spec, i))
            params[str(i)], state[str(i)] = p, s
        return params, state


class ConcatTable(_Branching):
    """Each branch sees the whole input; output is the table of branch outputs.

    Reference: nn/ConcatTable.scala.
    """

    def _branch_spec(self, input_spec, i):
        return input_spec

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], dict(state)
        for i, layer in enumerate(self.modules):
            y, s = layer.apply(
                params[str(i)], state[str(i)], input,
                training=training, rng=child_rng(rng, i),
            )
            outs.append(y)
            new_state[str(i)] = s
        return tuple(outs), new_state


class ParallelTable(_Branching):
    """Branch i consumes input[i] (reference: nn/ParallelTable.scala)."""

    def _branch_spec(self, input_spec, i):
        return input_spec[i]

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], dict(state)
        for i, layer in enumerate(self.modules):
            y, s = layer.apply(
                params[str(i)], state[str(i)], input[i],
                training=training, rng=child_rng(rng, i),
            )
            outs.append(y)
            new_state[str(i)] = s
        return tuple(outs), new_state


class MapTable(Container):
    """One shared module applied to every table element (reference: nn/MapTable.scala).

    Weight sharing is free in the functional core: one params pytree, applied
    to each element.
    """

    def __init__(self, module: Module, name=None):
        super().__init__(name)
        self.add(module)

    def setup(self, rng, input_spec):
        return self.modules[0].setup(rng, input_spec[0])

    def _param_child_items(self, params):
        # the shared module's params ARE this container's params (no key
        # level); the None key routes the whole subtree to it in the
        # frozen-mask walk
        return [(None, self.modules[0])]

    def apply(self, params, state, input, *, training=False, rng=None):
        outs = []
        s = state
        for i, x in enumerate(input):
            y, s = self.modules[0].apply(
                params, state, x, training=training, rng=child_rng(rng, i)
            )
            outs.append(y)
        return tuple(outs), s


class Concat(_Branching):
    """ConcatTable + join along ``dimension`` (reference: nn/Concat.scala)."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name)
        self.dimension = dimension

    def _branch_spec(self, input_spec, i):
        return input_spec

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], dict(state)
        for i, layer in enumerate(self.modules):
            y, s = layer.apply(
                params[str(i)], state[str(i)], input,
                training=training, rng=child_rng(rng, i),
            )
            outs.append(y)
            new_state[str(i)] = s
        return jnp.concatenate(outs, axis=self.dimension), new_state


# --------------------------------------------------------------------------- #
# Table element-wise ops (parameter-free layers).
# --------------------------------------------------------------------------- #


class CAddTable(Module):
    """Sum of table elements (reference: nn/CAddTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = out + x
        return out, state


class CMulTable(Module):
    """Product of table elements (reference: nn/CMulTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = out * x
        return out, state


class CSubTable(Module):
    """input[0] - input[1] (reference: nn/CSubTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input[0] - input[1], state


class CDivTable(Module):
    """input[0] / input[1] (reference: nn/CDivTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input[0] / input[1], state


class CMaxTable(Module):
    """Element-wise max over the table (reference: nn/CMaxTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = jnp.maximum(out, x)
        return out, state


class CMinTable(Module):
    """Element-wise min over the table (reference: nn/CMinTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = jnp.minimum(out, x)
        return out, state


class JoinTable(Module):
    """Concatenate table elements along ``dimension`` (reference: nn/JoinTable.scala)."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.concatenate(list(input), axis=self.dimension), state


class SelectTable(Module):
    """Pick element ``index`` of the input table (reference: nn/SelectTable.scala)."""

    def __init__(self, index: int, name=None):
        super().__init__(name)
        self.index = index

    def apply(self, params, state, input, *, training=False, rng=None):
        return input[self.index], state


class FlattenTable(Module):
    """Flatten a nested table into a flat tuple (reference: nn/FlattenTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return tuple(jax.tree.leaves(input)), state


#: jax.checkpoint_policies entries that are FACTORIES (they take
#: names/offload args and RETURN a policy), not policies themselves.
#: Passing one directly to jax.checkpoint either crashes or -- worse,
#: for the *names factories, whose closure is truthy for every
#: primitive -- silently saves everything, disabling remat.  A string
#: spelling can never supply the factory's arguments, so these are not
#: valid ``policy=`` names; construct the policy and pass the CALLABLE.
_POLICY_FACTORIES = frozenset({
    "offload_dot_with_no_batch_dims",
    "save_and_offload_only_these_names",
    "save_any_names_but_these",
    "save_anything_except_these_names",
    "save_from_both_policies",
    "save_only_these_names",
})


def checkpoint_policy_names():
    """The valid ``jax.checkpoint_policies`` NAMES a ``policy=`` string
    may take (``"dots_saveable"``, ``"nothing_saveable"``, ...).
    Factory entries (``save_only_these_names(...)`` & friends) are
    excluded: they need arguments a name cannot carry."""
    return sorted(
        n for n in dir(jax.checkpoint_policies)
        if not n.startswith("_") and n not in _POLICY_FACTORIES
        and callable(getattr(jax.checkpoint_policies, n)))


def resolve_checkpoint_policy(policy):
    """``None`` / a ``jax.checkpoint_policies`` NAME / a raw callable ->
    the callable ``jax.checkpoint(policy=)`` accepts.

    The one resolution seam ``Remat``, ``ScanLayers`` and the
    ``--rematPolicy`` CLI flag all share: an unknown name fails HERE,
    eagerly, with the list of valid policies -- not as an opaque
    ``AttributeError`` out of ``getattr`` at first apply inside a trace.
    ``None`` means jax.checkpoint's default (save only the wrapped
    computation's inputs).
    """
    if policy is None or callable(policy):
        return policy
    if isinstance(policy, str):
        if policy in _POLICY_FACTORIES:
            raise ValueError(
                f"{policy!r} is a policy FACTORY, not a policy: it takes "
                f"arguments a name cannot carry (and used directly it "
                f"would silently save everything, disabling remat) -- "
                f"construct it yourself and pass the callable, e.g. "
                f"policy=jax.checkpoint_policies.{policy}(...)")
        fn = getattr(jax.checkpoint_policies, policy, None)
        if fn is None or not callable(fn):
            raise ValueError(
                f"unknown checkpoint policy {policy!r}; valid "
                f"jax.checkpoint_policies names: "
                f"{checkpoint_policy_names()}")
        return fn
    raise TypeError(
        f"policy must be None, a jax.checkpoint_policies name or a "
        f"callable, got {type(policy).__name__}")


class Remat(Container):
    """Rematerialise the wrapped module's activations during backward
    (``jax.checkpoint``).

    TPU-first, no reference analogue: the reference's CPU executors are
    compute-bound, but a TPU ResNet train step is HBM-bandwidth-bound
    (docs/performance.md), so recomputing a block's forward inside the
    backward pass trades idle MXU FLOPs for stored-activation HBM
    traffic.  ``policy`` is forwarded to ``jax.checkpoint``; pass the
    NAME of a ``jax.checkpoint_policies`` entry (e.g.
    ``"dots_saveable"``) so the model stays serializable -- a raw
    callable also works but cannot be saved.  The default saves only
    the block inputs.

    Inference (``training=False``) bypasses the checkpoint: there is no
    backward to rematerialise for.

    Params/state follow the Container keying invariant (child i <->
    ``params[str(i)]``) so generic traversals (quantize, regularizers)
    see through the wrapper.
    """

    def __init__(self, module: Module, policy=None, name=None):
        super().__init__(name)
        self.add(module)
        resolve_checkpoint_policy(policy)   # unknown names fail HERE
        self.policy = policy

    def _policy(self):
        return resolve_checkpoint_policy(self.policy)

    def setup(self, rng, input_spec):
        p, s = self.modules[0].setup(rng, input_spec)
        return {"0": p}, {"0": s}

    def output_spec(self, params, state, input_spec, training=False):
        return self.modules[0].output_spec(
            params["0"], state["0"], input_spec, training=training)

    def apply(self, params, state, input, *, training=False, rng=None):
        inner = self.modules[0]
        if not training:
            out, s = inner.apply(params["0"], state["0"], input,
                                 training=False, rng=rng)
            return out, {"0": s}

        # state/rng are closed over: gradients flow only through params
        # and input, which is exactly the differentiation surface.
        def f(p, x):
            return inner.apply(p, state["0"], x, training=True, rng=rng)

        out, s = jax.checkpoint(f, policy=self._policy())(params["0"], input)
        return out, {"0": s}


def stack_layer_trees(trees):
    """[per-layer pytree] -> one pytree with every leaf stacked along a
    new leading LAYER axis (layer i lives at index i of every leaf) --
    the ``ScanLayers`` parameter layout."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *list(trees))


def unstack_layer_trees(tree):
    """Inverse of ``stack_layer_trees``: one stacked pytree -> the list
    of per-layer pytrees (restoring the Container keying invariant's
    per-child view for traversals that need it)."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        raise ValueError("unstack_layer_trees: tree has no array leaves")
    n = leaves[0].shape[0]
    return [jax.tree.map(lambda x: x[i], tree) for i in range(n)]


class ScanLayers(Container):
    """Scan-compiled stack of N structurally-identical layers.

    TPU-first, no reference analogue (the reference's deepest stacks are
    unrolled Sequential chains): an N-layer transformer traced layer by
    layer hands XLA N copies of the same block -- compile time, program
    size and executable HBM all scale with N.  This container stacks the
    children's params/state along a new leading LAYER axis
    (``stack_layer_trees``) and runs ONE ``lax.scan`` over it, so XLA
    compiles the block body once; compile wall time drops roughly
    N-fold (docs/performance.md, "Step-time campaign").

    Each scan iteration runs under ``jax.checkpoint`` during training,
    with ``policy`` naming a ``jax.checkpoint_policies`` entry
    (``"nothing_saveable"`` recomputes everything in backward --
    minimum activation HBM; ``"dots_saveable"`` keeps matmul outputs;
    ``None`` = jax.checkpoint's default, saving only each layer's
    inputs).  Per-iteration checkpointing is what makes scan-over-layers
    memory-sane: without it, autodiff would store every layer's full
    internals for the backward scan.

    The children must be structurally identical: same params/state
    treedef, same leaf shapes/dtypes, and output spec == input spec (the
    scan carry).  Layer i's parameters live at index i of every stacked
    leaf; ``stack_layer_trees``/``unstack_layer_trees`` interconvert
    with the unrolled per-child layout so checkpoints and generic
    traversals (quantize, regularizers, resharding) can always recover
    the per-layer view.  For the frozen-mask walk the whole stacked
    subtree routes to child 0 (all layers freeze together -- slicing a
    static mask out of a scanned carry is not expressible).

    RNG: layer i receives ``fold_in(rng, i)`` -- the same derivation an
    unrolled loop over ``child_rng(rng, i)`` uses, so scan and unrolled
    dropout masks match.
    """

    def __init__(self, modules, policy=None, name=None):
        super().__init__(name)
        modules = list(modules)
        if not modules:
            raise ValueError("ScanLayers needs at least one module")
        for m in modules:
            self.add(m)
        resolve_checkpoint_policy(policy)   # unknown names fail HERE
        self.policy = policy

    def setup(self, rng, input_spec):
        ps, ss = [], []
        struct = None
        for i, m in enumerate(self.modules):
            p, s = m.setup(child_rng(rng, i), input_spec)
            sig = jax.tree.map(
                lambda x: (tuple(x.shape), jnp.asarray(x).dtype), (p, s))
            if struct is None:
                struct = sig
            elif sig != struct:
                raise ValueError(
                    f"ScanLayers children must be structurally identical; "
                    f"child {i} ({self.modules[i].name}) differs from "
                    f"child 0 ({self.modules[0].name})")
            ps.append(p)
            ss.append(s)
        return stack_layer_trees(ps), stack_layer_trees(ss)

    def output_spec(self, params, state, input_spec, training=False):
        return input_spec     # the scan carry: output spec == input spec

    def _param_child_items(self, params):
        # the stacked subtree routes whole to child 0 (layers share
        # frozen status; see class docstring)
        return [(None, self.modules[0])]

    def apply(self, params, state, input, *, training=False, rng=None):
        inner = self.modules[0]
        n = len(self.modules)
        keys = None
        if rng is not None:
            keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
                jnp.arange(n))

        def layer(x, sliced):
            p, s, key = sliced
            y, new_s = inner.apply(p, s, x, training=training, rng=key)
            return y, new_s

        body = layer
        if training:
            # per-iteration remat: backward re-runs each layer's forward
            # under the named policy instead of storing its internals
            body = jax.checkpoint(
                layer, policy=resolve_checkpoint_policy(self.policy))
        out, new_state = jax.lax.scan(body, input, (params, state, keys),
                                      length=n)
        return out, new_state
