"""Containers and table ops.

Reference: nn/Sequential.scala:31, nn/Concat.scala, nn/ConcatTable.scala,
nn/ParallelTable.scala, nn/CAddTable.scala and friends.  Tables are Python
tuples of arrays.  All dimension indices are 0-based (Python idiom; the
reference is 1-based Torch convention).
"""

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Module, child_rng


class Sequential(Container):
    """Feed-forward chain (reference: nn/Sequential.scala:31)."""

    def setup(self, rng, input_spec):
        params, state = {}, {}
        spec = input_spec
        for i, layer in enumerate(self.modules):
            p, s = layer.setup(child_rng(rng, i), spec)
            params[str(i)], state[str(i)] = p, s
            spec = layer.output_spec(p, s, spec)
        return params, state

    def apply(self, params, state, input, *, training=False, rng=None):
        new_state = dict(state)
        x = input
        for i, layer in enumerate(self.modules):
            x, s = layer.apply(
                params[str(i)], state[str(i)], x,
                training=training, rng=child_rng(rng, i),
            )
            new_state[str(i)] = s
        return x, new_state


class _Branching(Container):
    """Shared setup for containers whose children all see the same spec."""

    def _branch_spec(self, input_spec, i):
        raise NotImplementedError

    def setup(self, rng, input_spec):
        params, state = {}, {}
        for i, layer in enumerate(self.modules):
            p, s = layer.setup(child_rng(rng, i), self._branch_spec(input_spec, i))
            params[str(i)], state[str(i)] = p, s
        return params, state


class ConcatTable(_Branching):
    """Each branch sees the whole input; output is the table of branch outputs.

    Reference: nn/ConcatTable.scala.
    """

    def _branch_spec(self, input_spec, i):
        return input_spec

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], dict(state)
        for i, layer in enumerate(self.modules):
            y, s = layer.apply(
                params[str(i)], state[str(i)], input,
                training=training, rng=child_rng(rng, i),
            )
            outs.append(y)
            new_state[str(i)] = s
        return tuple(outs), new_state


class ParallelTable(_Branching):
    """Branch i consumes input[i] (reference: nn/ParallelTable.scala)."""

    def _branch_spec(self, input_spec, i):
        return input_spec[i]

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], dict(state)
        for i, layer in enumerate(self.modules):
            y, s = layer.apply(
                params[str(i)], state[str(i)], input[i],
                training=training, rng=child_rng(rng, i),
            )
            outs.append(y)
            new_state[str(i)] = s
        return tuple(outs), new_state


class MapTable(Container):
    """One shared module applied to every table element (reference: nn/MapTable.scala).

    Weight sharing is free in the functional core: one params pytree, applied
    to each element.
    """

    def __init__(self, module: Module, name=None):
        super().__init__(name)
        self.add(module)

    def setup(self, rng, input_spec):
        return self.modules[0].setup(rng, input_spec[0])

    def _param_child_items(self, params):
        # the shared module's params ARE this container's params (no key
        # level); the None key routes the whole subtree to it in the
        # frozen-mask walk
        return [(None, self.modules[0])]

    def apply(self, params, state, input, *, training=False, rng=None):
        outs = []
        s = state
        for i, x in enumerate(input):
            y, s = self.modules[0].apply(
                params, state, x, training=training, rng=child_rng(rng, i)
            )
            outs.append(y)
        return tuple(outs), s


class Concat(_Branching):
    """ConcatTable + join along ``dimension`` (reference: nn/Concat.scala)."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name)
        self.dimension = dimension

    def _branch_spec(self, input_spec, i):
        return input_spec

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], dict(state)
        for i, layer in enumerate(self.modules):
            y, s = layer.apply(
                params[str(i)], state[str(i)], input,
                training=training, rng=child_rng(rng, i),
            )
            outs.append(y)
            new_state[str(i)] = s
        return jnp.concatenate(outs, axis=self.dimension), new_state


# --------------------------------------------------------------------------- #
# Table element-wise ops (parameter-free layers).
# --------------------------------------------------------------------------- #


class CAddTable(Module):
    """Sum of table elements (reference: nn/CAddTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = out + x
        return out, state


class CMulTable(Module):
    """Product of table elements (reference: nn/CMulTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = out * x
        return out, state


class CSubTable(Module):
    """input[0] - input[1] (reference: nn/CSubTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input[0] - input[1], state


class CDivTable(Module):
    """input[0] / input[1] (reference: nn/CDivTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input[0] / input[1], state


class CMaxTable(Module):
    """Element-wise max over the table (reference: nn/CMaxTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = jnp.maximum(out, x)
        return out, state


class CMinTable(Module):
    """Element-wise min over the table (reference: nn/CMinTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = jnp.minimum(out, x)
        return out, state


class JoinTable(Module):
    """Concatenate table elements along ``dimension`` (reference: nn/JoinTable.scala)."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.concatenate(list(input), axis=self.dimension), state


class SelectTable(Module):
    """Pick element ``index`` of the input table (reference: nn/SelectTable.scala)."""

    def __init__(self, index: int, name=None):
        super().__init__(name)
        self.index = index

    def apply(self, params, state, input, *, training=False, rng=None):
        return input[self.index], state


class FlattenTable(Module):
    """Flatten a nested table into a flat tuple (reference: nn/FlattenTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return tuple(jax.tree.leaves(input)), state


class Remat(Container):
    """Rematerialise the wrapped module's activations during backward
    (``jax.checkpoint``).

    TPU-first, no reference analogue: the reference's CPU executors are
    compute-bound, but a TPU ResNet train step is HBM-bandwidth-bound
    (docs/performance.md), so recomputing a block's forward inside the
    backward pass trades idle MXU FLOPs for stored-activation HBM
    traffic.  ``policy`` is forwarded to ``jax.checkpoint``; pass the
    NAME of a ``jax.checkpoint_policies`` entry (e.g.
    ``"dots_saveable"``) so the model stays serializable -- a raw
    callable also works but cannot be saved.  The default saves only
    the block inputs.

    Inference (``training=False``) bypasses the checkpoint: there is no
    backward to rematerialise for.

    Params/state follow the Container keying invariant (child i <->
    ``params[str(i)]``) so generic traversals (quantize, regularizers)
    see through the wrapper.
    """

    def __init__(self, module: Module, policy=None, name=None):
        super().__init__(name)
        self.add(module)
        self.policy = policy

    def _policy(self):
        if isinstance(self.policy, str):
            return getattr(jax.checkpoint_policies, self.policy)
        return self.policy

    def setup(self, rng, input_spec):
        p, s = self.modules[0].setup(rng, input_spec)
        return {"0": p}, {"0": s}

    def output_spec(self, params, state, input_spec, training=False):
        return self.modules[0].output_spec(
            params["0"], state["0"], input_spec, training=training)

    def apply(self, params, state, input, *, training=False, rng=None):
        inner = self.modules[0]
        if not training:
            out, s = inner.apply(params["0"], state["0"], input,
                                 training=False, rng=rng)
            return out, {"0": s}

        # state/rng are closed over: gradients flow only through params
        # and input, which is exactly the differentiation surface.
        def f(p, x):
            return inner.apply(p, state["0"], x, training=True, rng=rng)

        out, s = jax.checkpoint(f, policy=self._policy())(params["0"], input)
        return out, {"0": s}
