"""Module system core: functional layers with a Torch-style imperative facade.

Reference contract: ``AbstractModule[A, B, T]``
(nn/abstractnn/AbstractModule.scala:59) -- every layer has mutable
``output``/``gradInput``, template methods ``updateOutput`` /
``updateGradInput`` / ``accGradParameters`` and a ``parameters()`` accessor.

TPU-native redesign: the *core* of every layer is a pair of pure functions

    setup(rng, input_spec)                  -> (params, state)
    apply(params, state, input, training, rng) -> (output, new_state)

``params`` / ``state`` are pytrees of jax Arrays; ``input``/``output`` are
activities (a single array or a nested tuple -- the analogue of the
reference's ``Activity = Tensor | Table``).  The backward pass is autodiff
(``jax.vjp``) instead of hand-written ``updateGradInput`` -- there is nothing
to hand-derive, and XLA fuses the whole step.

The imperative facade (``forward``/``backward``/``parameters``/
``zero_grad_parameters``/``training``/``evaluate``) reproduces the reference
API surface for tests and interactive use.  The hot path -- Local/Distri
optimizers -- never uses the facade: they extract ``setup``/``apply`` and jit
one fused train step (see optim/local_optimizer.py).
"""

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.utils.random_generator import RNG
from bigdl_tpu.utils.shape import spec_of, tree_add

Params = Any
State = Any
Activity = Any

_name_counters = {}


def _record_init(cls):
    """Wrap ``cls.__init__`` to record the constructor call on the instance.

    The outermost (most-derived) call wins; nested super().__init__ calls
    see ``_init_args`` already set and leave it alone.  This is the
    reflection seam the protobuf serializer uses to round-trip EVERY module
    without per-class converters (reference: ModuleSerializable's
    constructor-mirror reflection, utils/serializer/ModuleSerializable.scala).
    """
    orig = cls.__dict__["__init__"]

    @functools.wraps(orig)
    def __init__(self, *args, **kwargs):
        if not hasattr(self, "_init_args"):
            self._init_args = (args, dict(kwargs))
        orig(self, *args, **kwargs)

    cls.__init__ = __init__


def _install_pending_after_setup(cls):
    """Wrap ``cls.setup`` so arrays stored by set_weights /
    set_state_entries BEFORE build install into the freshly created
    params/state no matter who runs setup -- containers call child.setup
    directly (never child.build), so without this hook pending weights on
    nested unbuilt layers would be silently ignored."""
    orig = cls.__dict__["setup"]

    @functools.wraps(orig)
    def setup(self, rng, input_spec):
        p, s = orig(self, rng, input_spec)
        pw = getattr(self, "_pending_weights", None)
        if pw is not None:
            self._pending_weights = None
            self._install_weight_list(pw, tree=p)
        ps = getattr(self, "_pending_state", None)
        if ps is not None:
            self._pending_state = None
            self._install_state_entries(ps, tree=s)
        return p, s

    cls.setup = setup


class _Name(str):
    """Module name that is BOTH an attribute and callable.

    The reference exposes the name as a METHOD (pyspark Layer.name(),
    AbstractModule.getName), while this codebase reads ``module.name`` as
    a plain string everywhere; a callable str subclass satisfies both
    (``m.name`` and ``m.name()`` return the same string)."""

    def __call__(self) -> str:
        return str(self)


def _auto_name(cls_name: str) -> str:
    n = _name_counters.get(cls_name, 0)
    _name_counters[cls_name] = n + 1
    return f"{cls_name}{n}"


def child_rng(rng, index: int):
    """Deterministic per-child key derivation (traceable)."""
    if rng is None:
        return None
    return jax.random.fold_in(rng, index)


class Module:
    """Base class of every layer (reference: AbstractModule.scala:59)."""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if "__init__" in cls.__dict__:
            _record_init(cls)
        if "setup" in cls.__dict__:
            _install_pending_after_setup(cls)

    def __init__(self, name: Optional[str] = None):
        self.name = name or _auto_name(type(self).__name__)
        self.train_mode: bool = True
        # facade state
        self.output: Activity = None
        self.grad_input: Activity = None
        self._params: Params = None
        self._state: State = None
        self._grads: Params = None
        self._last_rng = None
        self._build_spec = None

    @property
    def name(self) -> "_Name":
        return self._name

    @name.setter
    def name(self, value):
        # every assignment (constructors, deserializers, caffe importer)
        # funnels through here, so the name()-callable parity survives a
        # save/load round-trip
        self._name = _Name(value)

    # ------------------------------------------------------------------ #
    # Functional contract -- override these two in every layer.
    # ------------------------------------------------------------------ #
    def setup(self, rng, input_spec) -> Tuple[Params, State]:
        """Create (params, state) for the given abstract input spec."""
        return (), ()

    def apply(
        self, params: Params, state: State, input: Activity, *, training: bool = False,
        rng=None,
    ) -> Tuple[Activity, State]:
        raise NotImplementedError(type(self).__name__)

    def output_spec(self, params, state, input_spec, training: bool = False):
        out, _ = jax.eval_shape(
            lambda p, s, x: self.apply(p, s, x, training=training, rng=None),
            params, state, input_spec,
        )
        return out

    # ------------------------------------------------------------------ #
    # Imperative facade (reference API surface).
    # ------------------------------------------------------------------ #
    def is_built(self) -> bool:
        return self._params is not None or self._state is not None

    def build(self, input_spec, rng=None) -> "Module":
        """Materialise params/state for an input spec (lazy in forward())."""
        if rng is None:
            rng = RNG.next_key()
        self._build_spec = input_spec     # recorded for serialization
        self._params, self._state = self.setup(rng, input_spec)
        self._grads = None
        # pending set_weights/set_state_entries arrays are normally
        # installed by the setup wrapper (_install_pending_after_setup);
        # classes inheriting the base no-param setup are not wrapped, so
        # consume (and validate) any leftovers here
        pending = getattr(self, "_pending_weights", None)
        if pending is not None:
            self._pending_weights = None
            self._install_weight_list(pending)
        pending_state = getattr(self, "_pending_state", None)
        if pending_state is not None:
            self._pending_state = None
            self._install_state_entries(pending_state)
        return self

    # static loaders (reference: Scala `object Module` + pyspark
    # Model.load_torch/load_keras/load_caffe/load_caffe_model/
    # load_tensorflow, pyspark/bigdl/nn/layer.py:772-850)
    @staticmethod
    def load_torch(path):
        """Load a Torch .t7 serialized module."""
        from bigdl_tpu.utils.torch_file import load_torch_module

        return load_torch_module(path)

    @staticmethod
    def load_keras(json_path=None, hdf5_path=None, by_name=False):
        """Load a Keras JSON/HDF5 model definition (+weights)."""
        if by_name:
            raise NotImplementedError(
                "by_name weight matching is not supported; load the full "
                "topology (json_path) with its weights instead")
        from bigdl_tpu.keras.converter import load_keras

        return load_keras(json_path=json_path, hdf5_path=hdf5_path)

    @staticmethod
    def load_caffe(model, defPath, modelPath, match_all=True):
        """Copy caffe weights into an existing model (by layer name)."""
        from bigdl_tpu.interop.caffe import load

        return load(model, defPath, modelPath, match_all=match_all)

    @staticmethod
    def load_caffe_model(defPath, modelPath):
        """Build a model purely from a caffe prototxt + caffemodel."""
        from bigdl_tpu.interop.caffe import load_caffe

        return load_caffe(defPath, modelPath)

    @staticmethod
    def load_tensorflow(path, inputs, outputs, byte_order="little_endian",
                        bin_file=None):
        """Import a frozen TF GraphDef as a trainable module."""
        if byte_order != "little_endian":
            raise ValueError("only little_endian byte order is supported")
        if bin_file is not None:
            raise NotImplementedError(
                "separate dumped-weights bin_file is not supported; export "
                "a frozen GraphDef with the weights folded in")
        from bigdl_tpu.interop.tensorflow import load_tf

        return load_tf(path, inputs, outputs)

    def set_running_mean(self, running_mean) -> "Module":
        """Install a BatchNormalization running mean (reference: pyspark
        Layer.set_running_mean -> PythonBigDL.setRunningMean)."""
        return self.set_state_entries({"running_mean": running_mean})

    def set_running_std(self, running_std) -> "Module":
        """Install a BatchNormalization running VARIANCE -- the reference
        method is named *std* but stores into runningVar verbatim
        (PythonBigDL.scala:2731 setRunningStd -> module.runningVar.set);
        the naming quirk is kept for drop-in parity."""
        return self.set_state_entries({"running_var": running_std})

    def set_state_entries(self, entries):
        """Install {key: array} into the state pytree by leaf-dict key name
        (e.g. BN running_mean/running_var).  Before build, kept pending and
        installed when build() runs -- the state analogue of set_weights."""
        import numpy as np

        entries = {k: np.asarray(v, np.float32) for k, v in entries.items()}
        if not self.is_built():
            # MERGE: set_running_mean then set_running_std before build is
            # the normal pyspark pattern; overwriting would drop the first
            self._pending_state = {**(getattr(self, "_pending_state", None)
                                      or {}), **entries}
            return self
        return self._install_state_entries(entries)

    def _install_state_entries(self, entries, tree=None):
        hit = set()

        def walk(t):
            if isinstance(t, dict):
                for k in list(t):
                    if k in entries and hasattr(t[k], "shape"):
                        want = tuple(t[k].shape)
                        got = tuple(entries[k].shape)
                        if want != got:
                            raise ValueError(
                                f"set_state_entries: shape {got} != "
                                f"expected {want} for '{k}'")
                        t[k] = jnp.asarray(entries[k])
                        hit.add(k)
                    else:
                        walk(t[k])
            elif isinstance(t, (tuple, list)):
                for v in t:
                    walk(v)
        walk(self._state if tree is None else tree)
        missing = set(entries) - hit
        if missing:
            raise ValueError(f"set_state_entries: no state leaves named "
                             f"{sorted(missing)}")
        return self

    def _ensure_built(self, input: Activity):
        if not self.is_built():
            self.build(spec_of(input))

    def forward(self, input: Activity) -> Activity:
        """Reference: AbstractModule.forward (AbstractModule.scala:255)."""
        self._ensure_built(input)
        self._last_rng = RNG.next_key() if self.train_mode else None
        self.output, self._state = self.apply(
            self._params, self._state, input,
            training=self.train_mode, rng=self._last_rng,
        )
        return self.output

    def backward(self, input: Activity, grad_output: Activity) -> Activity:
        """updateGradInput + accGradParameters fused via jax.vjp.

        Reference: AbstractModule.backward (AbstractModule.scala:282).
        Gradients accumulate into the module until zero_grad_parameters(),
        matching accGradParameters semantics.
        """
        self._ensure_built(input)
        rng, training = self._last_rng, self.train_mode

        def f(p, x):
            y, _ = self.apply(p, self._state, x, training=training, rng=rng)
            return y

        _, vjp = jax.vjp(f, self._params, input)
        gparams, ginput = vjp(grad_output)
        self._grads = tree_add(self._grads, gparams)
        self.grad_input = ginput
        return ginput

    def parameters(self) -> Tuple[Params, Params]:
        """(weights, gradWeights) pytrees (reference: parameters(), :347)."""
        if self._grads is None and self._params is not None:
            self._grads = jax.tree.map(jnp.zeros_like, self._params)
        return self._params, self._grads

    def set_parameters(self, params: Params):
        self._params = params

    # weight-list accessors (reference: Layer.get_weights/set_weights in
    # pyspark/bigdl/nn/layer.py:478-508 -- flat [weight, bias, ...] arrays
    # in layer traversal order)
    def _weight_leaves(self, tree=None):
        """[(dict, key)] of param leaves, weight-before-bias per dict."""
        order = {"weight": 0, "bias": 1}
        found = []

        def walk(t):
            if isinstance(t, dict):
                for k in sorted(t, key=lambda k: (order.get(k, 2), k)):
                    v = t[k]
                    if isinstance(v, (dict, tuple, list)):
                        walk(v)
                    elif hasattr(v, "shape"):
                        found.append((t, k))
            elif isinstance(t, (tuple, list)):
                for v in t:
                    walk(v)
        walk(self._params if tree is None else tree)
        return found

    def get_weights(self):
        if not self.is_built():
            return []
        import numpy as np

        return [np.asarray(d[k]) for d, k in self._weight_leaves()]

    def set_weights(self, weights):
        """Install a flat weight list.  Before build, the arrays are kept
        pending and installed when build() runs (the pyspark API sets
        weights on eagerly-constructed layers)."""
        import numpy as np

        if not self.is_built():
            self._pending_weights = [np.asarray(w) for w in weights]
            return self
        return self._install_weight_list(weights)

    def _install_weight_list(self, weights, tree=None):
        leaves = self._weight_leaves(tree)
        if len(leaves) != len(weights):
            raise ValueError(
                f"set_weights: {len(weights)} arrays for {len(leaves)} "
                f"parameter tensors")
        import numpy as np

        # the (dict, key) handles returned above are the live dicts
        for (d, k), w in zip(leaves, weights):
            w = np.asarray(w, np.float32)
            want = tuple(d[k].shape)
            if w.shape != want:
                raise ValueError(
                    f"set_weights: shape {w.shape} != expected {want} "
                    f"for '{k}'")
            d[k] = jnp.asarray(w)
        return self

    def get_parameters(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Flat (weights, grads) 1-D views (reference: getParameters).

        Unlike the reference there is no storage aliasing -- these are packed
        copies (SURVEY.md: don't replicate strided aliasing).
        """
        from jax.flatten_util import ravel_pytree

        p, g = self.parameters()
        flat_p, _ = ravel_pytree(p)
        flat_g, _ = ravel_pytree(g)
        return flat_p, flat_g

    def zero_grad_parameters(self):
        if self._params is not None:
            self._grads = jax.tree.map(jnp.zeros_like, self._params)

    def update_parameters(self, learning_rate: float):
        """In-place ``p -= lr * gradP`` over the accumulated facade
        gradients (reference: AbstractModule.updateParameters /
        pyspark Layer.update_parameters)."""
        if self._params is None:
            raise ValueError("update_parameters() before build()")
        params, grads = self.parameters()
        self._params = jax.tree.map(
            lambda p, g: p - learning_rate * g, params, grads)
        return self

    def reset(self):
        """Re-initialise weights from the recorded build spec with a fresh
        RNG draw (reference: AbstractModule.reset)."""
        if self._build_spec is None:
            raise ValueError("reset() before build()")
        return self.build(self._build_spec)

    def set_name(self, name: str) -> "Module":
        """Reference: pyspark Layer.set_name (also AbstractModule.setName)."""
        self.name = _Name(name)
        return self

    def set_seed(self, seed: int = 123) -> "Module":
        """Seed the global init RNG (reference: pyspark Layer.set_seed ->
        RandomGenerator.RNG.setSeed)."""
        RNG.set_seed(seed)
        return self

    def is_training(self) -> bool:
        return self.train_mode

    def is_with_weights(self) -> bool:
        """Whether this (built) module carries any weights
        (reference: pyspark Layer.is_with_weights)."""
        return self._params is not None and bool(jax.tree.leaves(self._params))

    def freeze(self, names=None) -> "Module":
        """Stop parameter updates (reference: AbstractModule.freeze /
        pyspark Layer.freeze).  With ``names``, freezes the matching
        descendant modules; without, freezes this whole module.  Honored
        by ``make_train_step`` (gradients zeroed AND parameters restored
        after the optimizer update, so weight decay cannot leak in)."""
        if names is None:
            self._frozen = True
        else:
            self._freeze_named(set(names), True)
        return self

    def unfreeze(self, names=None) -> "Module":
        """With ``names``, explicitly marks those modules trainable — this
        OVERRIDES a frozen ancestor (tri-state: True=frozen, False=pinned
        trainable, unset=inherit), matching the reference's
        freeze-all-then-unfreeze-the-head fine-tune pattern.  Without
        ``names``, clears every mark below (and on) this module."""
        if names is None:
            self._frozen = None
            for m in self.children():
                m.unfreeze()
        else:
            self._freeze_named(set(names), False)
        return self

    def _freeze_named(self, names, value):
        found = []

        def walk(m):
            if str(m.name) in names:
                m._frozen = value
                found.append(str(m.name))
            for c in m.children():
                walk(c)

        walk(self)
        missing = names - set(found)
        if missing:
            raise ValueError(f"freeze: no modules named {sorted(missing)}")

    def _param_child_items(self, params):
        """[(params key, child module)] aligning this container's params
        dict with its children for the frozen-mask walk.  Sequential-style
        containers key children by index; Graph/MapTable override."""
        return [(str(i), c) for i, c in enumerate(self.children())]

    def training(self) -> "Module":
        self.train_mode = True
        for m in self.children():
            m.training()
        return self

    def evaluate(self) -> "Module":
        self.train_mode = False
        for m in self.children():
            m.evaluate()
        return self

    def quantize(self) -> "Module":
        """Rewrite this built model for int8 inference (reference:
        AbstractModule.scala:919 ``quantize()`` -> Quantizer): Linear and
        convolution layers swap to their int8 twins with weights
        quantized in place; returns self in eval mode."""
        from bigdl_tpu.nn.quantized import quantize as _quantize
        return _quantize(self)

    def set_regularizer(self, w=None, b=None, u=None):
        """Attach per-layer weight/bias/recurrent regularizers (reference:
        wRegularizer/bRegularizer/uRegularizer params on layer
        constructors, optim/Regularizer.scala).  Consumed by the train
        step's loss; ``u`` applies to recurrent (hidden-to-hidden)
        weights -- param keys named weight_hh."""
        if w is not None:
            self.w_regularizer = w
        if b is not None:
            self.b_regularizer = b
        if u is not None:
            self.u_regularizer = u
        return self

    def children(self):
        return []

    def state(self) -> State:
        return self._state

    def set_state(self, state: State):
        self._state = state

    def save(self, path: str):
        """Persist architecture + weights (reference: AbstractModule.save /
        saveModule)."""
        from bigdl_tpu.utils.serializer import save_module

        save_module(self, path)
        return self

    @staticmethod
    def load(path: str) -> "Module":
        """Reference: Module.load / ModuleLoader.loadFromFile."""
        from bigdl_tpu.utils.serializer import load_module

        return load_module(path)

    def save_weights(self, path: str):
        from bigdl_tpu.utils.serializer import save_weights

        save_weights(self, path)
        return self

    def load_weights(self, path: str):
        from bigdl_tpu.utils.serializer import load_weights

        return load_weights(self, path)

    def predict(self, data, batch_size: int = 128):
        """Batch inference sugar (reference: AbstractModule.predict :637)."""
        from bigdl_tpu.optim.predictor import Predictor

        return Predictor(self, batch_size).predict(data)

    def predict_class(self, data, batch_size: int = 128):
        from bigdl_tpu.optim.predictor import Predictor

        return Predictor(self, batch_size).predict_class(data)

    # pyspark Layer facade spellings (reference: pyspark/bigdl/nn/layer.py
    # predict_local :372 / predict_distributed :426 and the _class
    # variants).  The Predictor behind predict() already consumes local
    # arrays, Samples, DataSets AND partitioned sources, so local /
    # distributed collapse to the same call here.
    def predict_local(self, X, batch_size: int = 128):
        import numpy as np

        return np.stack(self.predict(X, batch_size))

    def predict_class_local(self, X, batch_size: int = 128):
        import numpy as np

        return np.asarray(self.predict_class(X, batch_size))

    predict_distributed = predict
    predict_class_distributed = predict_class

    def predict_image(self, image_frame, output_layer=None,
                      share_buffer=False, batch_per_partition=4,
                      predict_key="predict"):
        """Run inference over an ImageFrame, storing each output under
        ``predict_key`` on its ImageFeature (reference: pyspark
        Layer.predict_image :451 -> ImageFrame predict).  ``output_layer``
        / ``share_buffer`` are JVM execution details with no analogue
        here (one fused XLA program; buffers are XLA-owned)."""
        samples = image_frame.to_samples()
        outs = self.predict(samples, batch_size=batch_per_partition)
        for feature, out in zip(image_frame.features, outs):
            feature[predict_key] = out
        return image_frame

    def save_caffe(self, prototxt_path, model_path, use_v2=True,
                   overwrite=False):
        """Reference: pyspark Layer.save_caffe -> CaffePersister.  The
        input shape comes from the recorded build spec."""
        import os as _os

        if self._build_spec is None:
            raise ValueError("save_caffe() requires a built model")
        if not overwrite and (_os.path.exists(prototxt_path)
                              or _os.path.exists(model_path)):
            raise FileExistsError(
                f"{prototxt_path} / {model_path} exist (overwrite=False)")
        from bigdl_tpu.interop.caffe import save_caffe as _save

        shape = getattr(self._build_spec, "shape", None)
        _save(self, prototxt_path, model_path, shape)
        return self

    def save_tensorflow(self, inputs, path, byte_order="little_endian",
                        data_format="nhwc"):
        """Reference: pyspark Layer.save_tensorflow -> TensorflowSaver.
        ``inputs`` is the reference's [(name, shape)] list; the first
        entry names the graph input."""
        if byte_order != "little_endian":
            raise ValueError("only little_endian byte order is supported")
        if data_format != "nhwc":
            raise ValueError("exported graphs are NHWC (TPU-native layout)")
        from bigdl_tpu.interop.tensorflow import save_tf

        (input_name, input_shape) = inputs[0]
        save_tf(self, path, tuple(input_shape), input_name=input_name)
        return self

    def evaluate_on(self, dataset, methods, compute_dtype=None):
        """Run validation methods over a dataset
        (reference: AbstractModule.evaluate :855; named evaluate_on because
        evaluate() toggles eval mode, as in the reference)."""
        from bigdl_tpu.optim.predictor import evaluate

        return evaluate(self, dataset, methods, compute_dtype)

    # Graph building: calling a module on Node(s) creates a new graph node
    # (reference: ModuleNode / Graph, nn/Graph.scala:72).
    def __call__(self, *args):
        from bigdl_tpu.nn.graph import Node

        if args and all(isinstance(a, Node) for a in args):
            return Node(self, list(args))
        if len(args) == 1:
            return self.forward(args[0])
        raise TypeError(
            "Module(...) expects graph Nodes (to build a Graph) or a single "
            "activity (to run forward)."
        )

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"


class Container(Module):
    """Base for modules that own sub-modules (reference: nn/Container.scala:40)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.modules = []

    def add(self, module: Module) -> "Container":
        self.modules.append(module)
        return self

    def children(self):
        return list(self.modules)

    def training(self):
        self.train_mode = True
        for m in self.modules:
            m.training()
        return self

    def evaluate(self):
        self.train_mode = False
        for m in self.modules:
            m.evaluate()
        return self


def has_frozen(module: Module) -> bool:
    """True if this module or any descendant was froze()n."""
    if getattr(module, "_frozen", None) is True:
        return True
    return any(has_frozen(c) for c in module.children())


def frozen_param_mask(module: Module, params=None):
    """Pytree parallel to ``params`` with a python-bool leaf per array:
    True = trainable, False = under a frozen module.

    Alignment of param subtrees to child modules goes through each
    container's ``_param_child_items`` (Sequential-style containers key
    by child index; Graph keys by topo index; MapTable's params ARE the
    shared child's), so freeze() works on every container family.  The
    frozen mark is tri-state: an explicit ``unfreeze(names)`` (False)
    overrides a frozen ancestor.  Static (python bools), so using the
    mask inside a jitted step costs nothing at runtime.
    """
    if params is None:
        params = module.parameters()[0]

    def walk(m, p, inherited):
        own = getattr(m, "_frozen", None)
        frozen = inherited if own is None else own
        items = m._param_child_items(p)
        if len(items) == 1 and items[0][0] is None:
            # the whole subtree belongs to one shared child (MapTable)
            return walk(items[0][1], p, frozen)
        if items and isinstance(p, dict):
            by_key = dict(items)
            out = {}
            for k in p:
                if k in by_key:
                    out[k] = walk(by_key[k], p[k], frozen)
                else:
                    out[k] = jax.tree.map(lambda _: not frozen, p[k])
            return out
        return jax.tree.map(lambda _: not frozen, p)

    return walk(module, params, False)


class Criterion:
    """Loss base (reference: AbstractCriterion.scala).

    Core: pure ``apply(input, target) -> scalar loss``.  Facade ``forward`` /
    ``backward`` mirror the reference; backward is ``jax.grad`` wrt input.
    """

    size_average: bool = True

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if "__init__" in cls.__dict__:
            _record_init(cls)

    def apply(self, input: Activity, target: Activity) -> jnp.ndarray:
        raise NotImplementedError(type(self).__name__)

    def forward(self, input: Activity, target: Activity):
        self.output = self.apply(input, target)
        return self.output

    def backward(self, input: Activity, target: Activity):
        self.grad_input = jax.grad(lambda x: self.apply(x, target))(input)
        return self.grad_input

    def __call__(self, input, target):
        return self.forward(input, target)


class Identity(Module):
    """Reference: nn/Identity.scala."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input, state
