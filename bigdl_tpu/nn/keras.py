"""Keras-style training facade: compile / fit / evaluate / predict.

Reference: nn/keras/Topology.scala:35-165 (KerasModel.compile/fit/evaluate/
predict wrapping the Optimizer machinery; Sequential:262, Model:165).

These wrap any bigdl_tpu module (not just keras-defined ones), matching the
reference where KerasModel delegates to Local/Distri optimizers.
"""

from typing import List, Optional, Union

import numpy as np

import jax

from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.nn.criterion import (ClassNLLCriterion, CrossEntropyCriterion,
                                    MSECriterion, AbsCriterion, BCECriterion)
from bigdl_tpu.nn.module import Criterion
from bigdl_tpu.optim.local_optimizer import LocalOptimizer
from bigdl_tpu.optim.optim_method import (SGD, Adam, Adagrad, Adadelta,
                                          OptimMethod, RMSprop)
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import (Loss, Top1Accuracy, Top5Accuracy,
                                        ValidationMethod, MAE)

_OPTIMIZERS = {
    "sgd": lambda: SGD(learning_rate=0.01),
    "adam": Adam,
    "adagrad": Adagrad,
    "adadelta": Adadelta,
    "rmsprop": RMSprop,
}

_LOSSES = {
    "categorical_crossentropy": CrossEntropyCriterion,
    "sparse_categorical_crossentropy": CrossEntropyCriterion,
    "nll": ClassNLLCriterion,
    "mse": MSECriterion,
    "mean_squared_error": MSECriterion,
    "mae": AbsCriterion,
    "mean_absolute_error": AbsCriterion,
    "binary_crossentropy": BCECriterion,
}

_METRICS = {
    "accuracy": Top1Accuracy,
    "top1": Top1Accuracy,
    "top5": Top5Accuracy,
    "mae": MAE,
}


class _KerasMixin:
    """compile/fit/evaluate/predict (reference: KerasModel, Topology.scala:35)."""

    def compile(self, optimizer: Union[str, OptimMethod],
                loss: Union[str, Criterion],
                metrics: Optional[List[Union[str, ValidationMethod]]] = None):
        self._optim = (_OPTIMIZERS[optimizer.lower()]()
                       if isinstance(optimizer, str) else optimizer)
        self._loss = _LOSSES[loss.lower()]() if isinstance(loss, str) else loss
        self._metrics = [
            _METRICS[m.lower()]() if isinstance(m, str) else m
            for m in (metrics or [])
        ]
        return self

    def _to_dataset(self, x, y, batch_size) -> AbstractDataSet:
        if isinstance(x, AbstractDataSet):
            return x
        return array_dataset(np.asarray(x),
                             None if y is None else np.asarray(y)) >> \
            SampleToMiniBatch(batch_size)

    def fit(self, x, y=None, batch_size=32, nb_epoch=10,
            validation_data=None, distributed=False):
        """Reference: KerasModel.fit (Topology.scala:89)."""
        assert getattr(self, "_optim", None) is not None, "call compile() first"
        dataset = self._to_dataset(x, y, batch_size)
        if distributed:
            from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

            opt = DistriOptimizer(self, dataset, self._loss, self._optim)
        else:
            opt = LocalOptimizer(self, dataset, self._loss, self._optim)
        opt.set_end_when(Trigger.max_epoch(nb_epoch))
        if validation_data is not None:
            vx, vy = validation_data
            methods = self._metrics or [Loss(self._loss)]
            opt.set_validation(Trigger.every_epoch(),
                               self._to_dataset(vx, vy, batch_size), methods)
        opt.optimize()
        return self

    def evaluate(self, x=None, y=None, batch_size=32):
        """Keras-style evaluate; with no args, flips eval mode like the base
        Module.evaluate() (reference behaviour is the latter)."""
        if x is None:
            return super().evaluate()
        methods = self._metrics or [Loss(self._loss)]
        res = self.evaluate_on(self._to_dataset(x, y, batch_size), methods)
        return [r.result()[0] for r in res]

    def predict(self, x, batch_size=32, distributed=False):
        """Reference: KerasModel.predict (Topology.scala:127)."""
        if isinstance(x, AbstractDataSet):
            return super().predict(x, batch_size)
        from bigdl_tpu.dataset.minibatch import Sample

        samples = [Sample(np.asarray(f)) for f in x]
        return np.stack(super().predict(samples, batch_size))


def __getattr__(name):
    # Sequential/Model live in bigdl_tpu.keras.topology (the shape-inferring
    # versions); this lazy alias keeps the historical import path
    # ``from bigdl_tpu.nn.keras import Sequential, Model`` working without
    # maintaining a second, diverging pair of classes (round-2 VERDICT Weak #7).
    if name in ("Sequential", "Model"):
        from bigdl_tpu.keras import topology

        return getattr(topology, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
