"""Element-wise activation / math layers.

Reference files: nn/ReLU.scala, nn/Tanh.scala, nn/Sigmoid.scala,
nn/SoftMax.scala, nn/LogSoftMax.scala, nn/HardTanh.scala, nn/ELU.scala,
nn/SoftPlus.scala, nn/SoftSign.scala, nn/LeakyReLU.scala, nn/ReLU6.scala,
nn/Threshold.scala, nn/HardSigmoid.scala, nn/LogSigmoid.scala,
nn/TanhShrink.scala, nn/SoftShrink.scala, nn/HardShrink.scala,
nn/Power.scala, nn/Square.scala, nn/Sqrt.scala, nn/Abs.scala, nn/Clamp.scala,
nn/Exp.scala, nn/Log.scala, nn/Negative.scala, nn/MulConstant.scala,
nn/AddConstant.scala, nn/PReLU.scala.

All are stateless jnp expressions; XLA fuses them into neighbouring matmuls,
which is the TPU-native replacement for MKL VML calls
(tensor/TensorNumeric.scala:100-115) and MKL-DNN eltwise post-op fusion
(nn/mkldnn/Fusion.scala).
"""

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.initialization import ConstInitMethod
from bigdl_tpu.nn.module import Module


class _Elementwise(Module):
    def fn(self, x):
        raise NotImplementedError

    def apply(self, params, state, input, *, training=False, rng=None):
        return jax.tree.map(self.fn, input), state


class ReLU(_Elementwise):
    def fn(self, x):
        return jax.nn.relu(x)


class Tanh(_Elementwise):
    def fn(self, x):
        return jnp.tanh(x)


class Sigmoid(_Elementwise):
    def fn(self, x):
        return jax.nn.sigmoid(x)


class SoftMax(Module):
    """Softmax over ``axis`` (default last; reference: nn/SoftMax.scala)."""

    def __init__(self, axis=-1, name=None):
        super().__init__(name)
        self.axis = axis

    def apply(self, params, state, input, *, training=False, rng=None):
        return jax.nn.softmax(input, axis=self.axis), state


class SoftMin(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jax.nn.softmax(-input, axis=-1), state


class LogSoftMax(Module):
    """Log-softmax over the last dimension (reference: nn/LogSoftMax.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return jax.nn.log_softmax(input, axis=-1), state


class HardTanh(_Elementwise):
    def __init__(self, min_value=-1.0, max_value=1.0, name=None):
        super().__init__(name)
        self.min_value, self.max_value = min_value, max_value

    def fn(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class Clamp(HardTanh):
    def __init__(self, min_value, max_value, name=None):
        super().__init__(min_value, max_value, name)


class ReLU6(HardTanh):
    def __init__(self, name=None):
        super().__init__(0.0, 6.0, name)


class ELU(_Elementwise):
    def __init__(self, alpha=1.0, name=None):
        super().__init__(name)
        self.alpha = alpha

    def fn(self, x):
        return jax.nn.elu(x, self.alpha)


class SoftPlus(_Elementwise):
    def __init__(self, beta=1.0, name=None):
        super().__init__(name)
        self.beta = beta

    def fn(self, x):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(_Elementwise):
    def fn(self, x):
        return x / (1.0 + jnp.abs(x))


class LeakyReLU(_Elementwise):
    def __init__(self, negval=0.01, name=None):
        super().__init__(name)
        self.negval = negval

    def fn(self, x):
        return jax.nn.leaky_relu(x, self.negval)


class Threshold(_Elementwise):
    def __init__(self, threshold=1e-6, value=0.0, name=None):
        super().__init__(name)
        self.threshold, self.value = threshold, value

    def fn(self, x):
        return jnp.where(x > self.threshold, x, self.value)


class HardSigmoid(_Elementwise):
    def fn(self, x):
        return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


class LogSigmoid(_Elementwise):
    def fn(self, x):
        return jax.nn.log_sigmoid(x)


class TanhShrink(_Elementwise):
    def fn(self, x):
        return x - jnp.tanh(x)


class SoftShrink(_Elementwise):
    def __init__(self, lam=0.5, name=None):
        super().__init__(name)
        self.lam = lam

    def fn(self, x):
        return jnp.where(
            x > self.lam, x - self.lam, jnp.where(x < -self.lam, x + self.lam, 0.0)
        )


class HardShrink(_Elementwise):
    def __init__(self, lam=0.5, name=None):
        super().__init__(name)
        self.lam = lam

    def fn(self, x):
        return jnp.where(jnp.abs(x) > self.lam, x, 0.0)


class Power(_Elementwise):
    """(shift + scale * x) ** power (reference: nn/Power.scala)."""

    def __init__(self, power, scale=1.0, shift=0.0, name=None):
        super().__init__(name)
        self.power, self.scale, self.shift = power, scale, shift

    def fn(self, x):
        return jnp.power(self.shift + self.scale * x, self.power)


class Square(_Elementwise):
    def fn(self, x):
        return jnp.square(x)


class Sqrt(_Elementwise):
    def fn(self, x):
        return jnp.sqrt(x)


class Abs(_Elementwise):
    def fn(self, x):
        return jnp.abs(x)


class Exp(_Elementwise):
    def fn(self, x):
        return jnp.exp(x)


class Log(_Elementwise):
    def fn(self, x):
        return jnp.log(x)


class Negative(_Elementwise):
    def fn(self, x):
        return -x


class MulConstant(_Elementwise):
    def __init__(self, scalar, name=None):
        super().__init__(name)
        self.scalar = scalar

    def fn(self, x):
        return x * self.scalar


class AddConstant(_Elementwise):
    def __init__(self, constant, name=None):
        super().__init__(name)
        self.constant = constant

    def fn(self, x):
        return x + self.constant


class GELU(_Elementwise):
    """Not in the reference (pre-transformer codebase); provided for the
    transformer/long-context stack."""

    def fn(self, x):
        return jax.nn.gelu(x)


class SiLU(_Elementwise):
    """SwiGLU building block for the transformer stack (not in the reference)."""

    def fn(self, x):
        return jax.nn.silu(x)


class PReLU(Module):
    """Learnable leaky slope (reference: nn/PReLU.scala).

    ``n_output_plane=0`` -> one shared slope; otherwise one per channel
    (channel = last axis, NHWC convention).
    """

    def __init__(self, n_output_plane=0, name=None):
        super().__init__(name)
        self.n_output_plane = n_output_plane

    def setup(self, rng, input_spec):
        n = self.n_output_plane if self.n_output_plane > 0 else 1
        return {"weight": jnp.full((n,), 0.25, jnp.float32)}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        w = params["weight"].astype(input.dtype)
        return jnp.where(input >= 0, input, w * input), state
