"""Table-valued layers (pairs/tuples of tensors as inputs).

Reference: nn/SplitTable.scala, BifurcateSplitTable.scala,
NarrowTable.scala, MixtureTable.scala, DotProduct.scala,
CosineDistance.scala, PairwiseDistance.scala, MM.scala, MV.scala,
CrossProduct.scala, Index.scala, Pack.scala, CAveTable.scala.
All dimension indices are 0-based (python idiom; reference is 1-based).
"""

import jax.numpy as jnp

from bigdl_tpu.nn.module import Module, child_rng


class SplitTable(Module):
    """Tensor -> tuple of slices along ``dimension``
    (reference: nn/SplitTable.scala)."""

    def __init__(self, dimension, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        n = input.shape[self.dimension]
        parts = tuple(
            jnp.squeeze(s, axis=self.dimension)
            for s in jnp.split(input, n, axis=self.dimension))
        return parts, state


class BifurcateSplitTable(Module):
    """Tensor -> (first half, second half) along ``dimension``
    (reference: nn/BifurcateSplitTable.scala)."""

    def __init__(self, dimension, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        n = input.shape[self.dimension]
        a, b = jnp.split(input, [n // 2], axis=self.dimension)
        return (a, b), state


class NarrowTable(Module):
    """Table -> sub-table [offset, offset+length)
    (reference: nn/NarrowTable.scala)."""

    def __init__(self, offset, length=1, name=None):
        super().__init__(name)
        self.offset = offset
        self.length = length

    def apply(self, params, state, input, *, training=False, rng=None):
        out = tuple(input[self.offset:self.offset + self.length])
        return out[0] if self.length == 1 else out, state


class MixtureTable(Module):
    """(gater (N, k), experts tuple/stacked tensor) -> sum_k g_k * expert_k
    (reference: nn/MixtureTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        gater, experts = input[0], input[1]
        if isinstance(experts, tuple):
            experts = jnp.stack(experts, axis=1)    # (N, k, ...)
        g = gater.reshape(gater.shape + (1,) * (experts.ndim - 2))
        return jnp.sum(g * experts, axis=1), state


class DotProduct(Module):
    """(a, b) -> rowwise dot (reference: nn/DotProduct.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        a, b = input
        return jnp.sum(a * b, axis=-1), state


class CosineDistance(Module):
    """(a, b) -> rowwise cosine similarity
    (reference: nn/CosineDistance.scala)."""

    def __init__(self, eps=1e-12, name=None):
        super().__init__(name)
        self.eps = eps

    def apply(self, params, state, input, *, training=False, rng=None):
        a, b = input
        na = jnp.maximum(jnp.linalg.norm(a, axis=-1), self.eps)
        nb = jnp.maximum(jnp.linalg.norm(b, axis=-1), self.eps)
        return jnp.sum(a * b, axis=-1) / (na * nb), state


class PairwiseDistance(Module):
    """(a, b) -> rowwise Lp distance (reference: nn/PairwiseDistance.scala)."""

    def __init__(self, norm=2, name=None):
        super().__init__(name)
        self.norm = norm

    def apply(self, params, state, input, *, training=False, rng=None):
        a, b = input
        d = jnp.abs(a - b) ** self.norm
        return jnp.sum(d, axis=-1) ** (1.0 / self.norm), state


class MM(Module):
    """(A, B) -> A @ B with optional transposes, batched
    (reference: nn/MM.scala)."""

    def __init__(self, trans_a=False, trans_b=False, name=None):
        super().__init__(name)
        self.trans_a = trans_a
        self.trans_b = trans_b

    def apply(self, params, state, input, *, training=False, rng=None):
        a, b = input
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b, state


class MV(Module):
    """(M, v) -> M @ v, batched (reference: nn/MV.scala)."""

    def __init__(self, trans=False, name=None):
        super().__init__(name)
        self.trans = trans

    def apply(self, params, state, input, *, training=False, rng=None):
        m, v = input
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v), state


class CrossProduct(Module):
    """Table of k tensors -> all pairwise dot products (N, k*(k-1)/2)
    (reference: nn/CrossProduct.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        outs = []
        k = len(input)
        for i in range(k):
            for j in range(i + 1, k):
                outs.append(jnp.sum(input[i] * input[j], axis=-1))
        return jnp.stack(outs, axis=-1), state


class Index(Module):
    """(tensor, indices) -> tensor indexed along ``dimension``
    (reference: nn/Index.scala)."""

    def __init__(self, dimension=0, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        t, idx = input
        return jnp.take(t, idx.astype(jnp.int32), axis=self.dimension), state


class Pack(Module):
    """Table of tensors -> stacked along a new ``dimension``
    (reference: nn/Pack.scala)."""

    def __init__(self, dimension=0, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        parts = input if isinstance(input, tuple) else (input,)
        return jnp.stack(parts, axis=self.dimension), state


class CAveTable(Module):
    """Elementwise average of table entries (reference: nn/CAveTable.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        total = input[0]
        for x in input[1:]:
            total = total + x
        return total / len(input), state


class Bottle(Module):
    """Apply ``module`` to an input with leading dims collapsed to
    ``n_input_dim`` dims, then restore (reference: nn/Bottle.scala)."""

    def __init__(self, module, n_input_dim=2, n_output_dim=None, name=None):
        super().__init__(name)
        self.module = module
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim or n_input_dim

    def setup(self, rng, input_spec):
        import jax
        shape = input_spec.shape
        lead = shape[:len(shape) - self.n_input_dim + 1]
        collapsed = (int(jnp.prod(jnp.asarray(lead))),) + \
            shape[len(shape) - self.n_input_dim + 1:]
        spec = jax.ShapeDtypeStruct(collapsed, input_spec.dtype)
        return self.module.setup(rng, spec)

    def apply(self, params, state, input, *, training=False, rng=None):
        shape = input.shape
        lead = shape[:len(shape) - self.n_input_dim + 1]
        rest = shape[len(shape) - self.n_input_dim + 1:]
        x = input.reshape((-1,) + rest)
        y, new_state = self.module.apply(params, state, x,
                                         training=training, rng=rng)
        return y.reshape(lead + y.shape[1:]), new_state


class SparseJoinTable(Module):
    """Concatenate 2-D SparseTensors column-wise
    (reference: nn/SparseJoinTable.scala:36)."""

    def __init__(self, dimension=1, name=None):
        super().__init__(name)
        assert dimension == 1, "reference supports the column dim only"

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn.sparse import sparse_join
        return sparse_join(list(input)), state
