"""Loss functions.

Reference: the ~30 criterions under nn/ (ClassNLLCriterion.scala,
CrossEntropyCriterion.scala, MSECriterion.scala, AbsCriterion.scala,
BCECriterion.scala, SmoothL1Criterion.scala, DistKLDivCriterion.scala,
MarginCriterion.scala, MultiCriterion.scala, ParallelCriterion.scala,
TimeDistributedCriterion.scala, MultiLabelSoftMarginCriterion.scala,
CosineEmbeddingCriterion.scala, HingeEmbeddingCriterion.scala,
L1Cost.scala, KullbackLeiblerDivergenceCriterion.scala).

Class labels are 0-based integers (the reference uses 1-based Torch
convention).  ``size_average=True`` averages over the batch, else sums.
"""

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Criterion


def _reduce(loss_per_sample, size_average):
    return jnp.mean(loss_per_sample) if size_average else jnp.sum(loss_per_sample)


class ClassNLLCriterion(Criterion):
    """Negative log-likelihood over log-probabilities (reference: nn/ClassNLLCriterion.scala).

    ``input``: (N, C) log-probs (pair with LogSoftMax); ``target``: (N,) int.
    Optional per-class ``weights``; ``padding_value`` rows contribute 0 loss
    (the reference uses paddingValue=-1 to mask).
    """

    def __init__(self, weights=None, size_average=True, padding_value=None):
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average
        self.padding_value = padding_value

    def apply(self, input, target):
        target = target.astype(jnp.int32)
        safe_t = jnp.clip(target, 0, input.shape[-1] - 1)
        nll = -jnp.take_along_axis(input, safe_t[..., None], axis=-1)[..., 0]
        w = jnp.ones_like(nll)
        if self.weights is not None:
            w = self.weights[safe_t].astype(nll.dtype)
        if self.padding_value is not None:
            w = jnp.where(target == self.padding_value, 0.0, w)
        total = jnp.sum(nll * w)
        if self.size_average:
            denom = jnp.maximum(jnp.sum(w), 1e-8)
            return total / denom
        return total


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (reference: nn/CrossEntropyCriterion.scala).

    ``input``: (N, C) raw logits.
    """

    def __init__(self, weights=None, size_average=True):
        self.inner = ClassNLLCriterion(weights, size_average)
        self.size_average = size_average

    def apply(self, input, target):
        return self.inner.apply(jax.nn.log_softmax(input, axis=-1), target)


class FusedSoftmaxCrossEntropyCriterion(Criterion):
    """CrossEntropyCriterion backed by the Pallas blockwise kernel
    (ops/cross_entropy.py) -- for large vocabularies where materialising
    log_softmax costs an (N, V) HBM round-trip.  Falls back to the plain
    formulation for small/ragged class counts where the kernel's block
    shapes don't pay; wrap in TimeDistributedCriterion for (B, T, V) LM
    heads.
    """

    def __init__(self, size_average=True, min_classes=512,
                 interpret=False):
        self.size_average = size_average
        self.min_classes = min_classes
        #: interpret=True runs the kernel in the Pallas interpreter (tests);
        #: otherwise off-TPU backends use the plain formulation
        self.interpret = interpret

    def apply(self, input, target):
        import jax as _jax

        on_tpu = _jax.default_backend() == "tpu"
        if (input.ndim != 2 or input.shape[1] < self.min_classes
                or input.shape[0] % 8 or not (on_tpu or self.interpret)):
            return CrossEntropyCriterion(
                size_average=self.size_average).apply(input, target)
        from bigdl_tpu.ops.cross_entropy import fused_softmax_cross_entropy

        n, v = input.shape
        block_n = n if n < 128 else 128
        while n % block_n:
            block_n //= 2
        # clip like ClassNLLCriterion so out-of-range/ignore markers give
        # identical losses on every backend
        y = jnp.clip(target.astype(jnp.int32), 0, v - 1)
        losses = fused_softmax_cross_entropy(
            input, y, block_n, 512, self.interpret)
        return losses.mean() if self.size_average else losses.sum()


class MSECriterion(Criterion):
    """Mean squared error (reference: nn/MSECriterion.scala).

    sizeAverage divides by the *element* count, matching the reference.
    """

    def __init__(self, size_average=True):
        self.size_average = size_average

    def apply(self, input, target):
        se = jnp.square(input - target)
        return jnp.mean(se) if self.size_average else jnp.sum(se)


class AbsCriterion(Criterion):
    """Mean absolute error (reference: nn/AbsCriterion.scala)."""

    def __init__(self, size_average=True):
        self.size_average = size_average

    def apply(self, input, target):
        ae = jnp.abs(input - target)
        return jnp.mean(ae) if self.size_average else jnp.sum(ae)


class BCECriterion(Criterion):
    """Binary cross-entropy over probabilities (reference: nn/BCECriterion.scala)."""

    def __init__(self, weights=None, size_average=True, eps=1e-12):
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average
        self.eps = eps

    def apply(self, input, target):
        x = jnp.clip(input, self.eps, 1.0 - self.eps)
        ce = -(target * jnp.log(x) + (1.0 - target) * jnp.log(1.0 - x))
        if self.weights is not None:
            ce = ce * self.weights
        return jnp.mean(ce) if self.size_average else jnp.sum(ce)


class BCEWithLogitsCriterion(Criterion):
    """Numerically-stable sigmoid + BCE (TPU-friendly fused form)."""

    def __init__(self, size_average=True):
        self.size_average = size_average

    def apply(self, input, target):
        ce = jnp.maximum(input, 0) - input * target + jnp.log1p(jnp.exp(-jnp.abs(input)))
        return jnp.mean(ce) if self.size_average else jnp.sum(ce)


class SmoothL1Criterion(Criterion):
    """Huber loss with delta=1 (reference: nn/SmoothL1Criterion.scala)."""

    def __init__(self, size_average=True):
        self.size_average = size_average

    def apply(self, input, target):
        d = jnp.abs(input - target)
        loss = jnp.where(d < 1.0, 0.5 * jnp.square(d), d - 0.5)
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class DistKLDivCriterion(Criterion):
    """KL divergence, input = log-probs, target = probs
    (reference: nn/DistKLDivCriterion.scala)."""

    def __init__(self, size_average=True):
        self.size_average = size_average

    def apply(self, input, target):
        kl = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-30)) - input), 0.0)
        total = jnp.sum(kl)
        return total / input.shape[0] if self.size_average else total


class MarginCriterion(Criterion):
    """Hinge loss max(0, margin - y*x) (reference: nn/MarginCriterion.scala)."""

    def __init__(self, margin=1.0, size_average=True, squared=False):
        self.margin = margin
        self.size_average = size_average
        self.squared = squared

    def apply(self, input, target):
        h = jnp.maximum(0.0, self.margin - input * target)
        if self.squared:
            h = jnp.square(h)
        return jnp.mean(h) if self.size_average else jnp.sum(h)


class HingeEmbeddingCriterion(Criterion):
    """Reference: nn/HingeEmbeddingCriterion.scala (target in {1, -1})."""

    def __init__(self, margin=1.0, size_average=True):
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        loss = jnp.where(target > 0, input, jnp.maximum(0.0, self.margin - input))
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class L1Cost(Criterion):
    """Sum of |input| (reference: nn/L1Cost.scala; target ignored)."""

    def apply(self, input, target=None):
        return jnp.sum(jnp.abs(input))


class CosineEmbeddingCriterion(Criterion):
    """Reference: nn/CosineEmbeddingCriterion.scala.

    ``input``: table (x1, x2); ``target``: (N,) in {1, -1}.
    """

    def __init__(self, margin=0.0, size_average=True):
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        x1, x2 = input
        cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12
        )
        loss = jnp.where(target > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class KullbackLeiblerDivergenceCriterion(Criterion):
    """Probabilities-in variant (reference: nn/KullbackLeiblerDivergenceCriterion.scala)."""

    def apply(self, input, target):
        x = jnp.clip(input, 1e-7, 1.0)
        t = jnp.clip(target, 1e-7, 1.0)
        return jnp.mean(jnp.sum(t * jnp.log(t / x), axis=-1))


class MultiLabelSoftMarginCriterion(Criterion):
    """Reference: nn/MultiLabelSoftMarginCriterion.scala."""

    def __init__(self, weights=None, size_average=True):
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def apply(self, input, target):
        ce = jnp.maximum(input, 0) - input * target + jnp.log1p(jnp.exp(-jnp.abs(input)))
        if self.weights is not None:
            ce = ce * self.weights
        per_sample = jnp.mean(ce, axis=-1)
        return jnp.mean(per_sample) if self.size_average else jnp.sum(per_sample)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target)
    (reference: nn/MultiCriterion.scala)."""

    def __init__(self):
        self.criterions = []
        self.cweights = []

    def add(self, criterion, weight=1.0):
        self.criterions.append(criterion)
        self.cweights.append(weight)
        return self

    def apply(self, input, target):
        return sum(
            w * c.apply(input, target)
            for w, c in zip(self.cweights, self.criterions)
        )


class ParallelCriterion(Criterion):
    """criterion[i] applied to (input[i], target[i]), weighted sum
    (reference: nn/ParallelCriterion.scala)."""

    def __init__(self, repeat_target=False):
        self.criterions = []
        self.cweights = []
        self.repeat_target = repeat_target

    def add(self, criterion, weight=1.0):
        self.criterions.append(criterion)
        self.cweights.append(weight)
        return self

    def apply(self, input, target):
        total = 0.0
        for i, (w, c) in enumerate(zip(self.cweights, self.criterions)):
            t = target if self.repeat_target else target[i]
            total = total + w * c.apply(input[i], t)
        return total


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every timestep of (N, T, ...) input
    (reference: nn/TimeDistributedCriterion.scala)."""

    def __init__(self, criterion, size_average=True):
        self.criterion = criterion
        self.size_average = size_average

    def apply(self, input, target):
        n, t = input.shape[0], input.shape[1]
        flat_in = input.reshape((n * t,) + input.shape[2:])
        flat_t = target.reshape((n * t,) + target.shape[2:])
        loss = self.criterion.apply(flat_in, flat_t)
        if self.size_average:
            return loss  # inner criterion already averages over N*T
        return loss
