"""Int8 quantized inference.

Reference: nn/quantized/Quantizer.scala:27 (graph rewrite swapping
Linear/SpatialConvolution for int8 variants), tensor/QuantizedTensor.scala
(int8 storage + per-window scales, BigQuant JNI kernels).

TPU-native: int8 x int8 -> int32 matmul/conv is native on the MXU
(``preferred_element_type=jnp.int32``); no JNI, no descriptors.  Weights are
quantized per output channel (symmetric, like BigQuant); activations are
quantized dynamically per tensor at run time (the reference's runtime
min/max behaviour).  Expected wins match the reference whitepaper
(docs/whitepaper.md:192): ~4x model size, up to ~2x inference speed,
<1% accuracy loss.

Two rewrite paths share the kernels below:

- ``quantize_model(model) -> (qmodel, qparams)`` -- the SERVING path
  (docs/performance.md, "Int8 inference").  A pure params-level rewrite:
  matmul/conv weight leaves are replaced by ``weight_q`` (int8) +
  ``scale`` (fp32 per output channel) pairs that the float layers'
  quantization-aware ``apply`` consumes (``nn/linear.py``,
  ``nn/conv.py``, ``nn/attention.py``), and the returned model is a
  lightweight structural view holding the quantized tree -- the fp32
  original is NOT mutated, so it keeps serving while the int8 twin
  stages.  Because the rewrite is keyed off the module tree (via each
  container's ``_param_child_items`` alignment), one walk covers
  Sequential-style containers, ``Graph`` DAGs, and ``TransformerLM`` in
  BOTH param layouts -- unrolled ``"block{i}"`` keys and the
  scan-stacked ``"blocks"`` layout (stacked leaves quantize per layer x
  per output channel and slice cleanly inside ``lax.scan``).
  Embedding tables (``jnp.take`` consumers), the LM head, layernorms
  and biases stay fp32 by default; ``select=`` narrows further.

- ``quantize(model)`` -- the legacy REFERENCE path
  (AbstractModule.quantize): mutates a Sequential-style model in place,
  swapping ``Linear``/``SpatialConvolution`` children for their
  ``QuantizedLinear``/``QuantizedSpatialConvolution`` twins.  This is
  the path the protobuf serializer round-trips
  (interop/bigdl_format.py: weights stored quantized, never
  re-quantized on load).
"""

import copy
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.conv import SpatialConvolution, SpatialDilatedConvolution
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import Container, Module


def quantize_weights_per_channel(w, channel_axis: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 per-output-channel quantization -> (w_int8, scale).

    ``scale`` keeps the reduced axes as size-1 dims (broadcastable
    against ``w``); the serving-path rewrite uses
    :func:`quantize_channelwise` which squeezes them instead."""
    reduce_axes = tuple(a for a in range(w.ndim) if a != channel_axis)
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    w_q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return w_q, scale.astype(jnp.float32)


def quantize_channelwise(w, channel_axis: int, lead_axes: int = 0
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-output-channel int8 quantization with stacked leading axes.

    ``lead_axes`` leading dims are per-instance (the scan-stacked layer
    axis of ``nn.ScanLayers`` params): each [lead x channel] slice gets
    its own absmax scale, so a stacked tree quantizes exactly as the N
    per-layer trees would.  Returns ``(w_q int8, scale fp32)`` with
    ``scale.shape = lead dims + (channels,)`` -- the squeezed layout the
    quantization-aware applies consume.
    """
    assert 0 <= lead_axes <= channel_axis < w.ndim, (w.shape, channel_axis)
    reduce_axes = tuple(a for a in range(w.ndim)
                        if a >= lead_axes and a != channel_axis)
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    w_q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return w_q, jnp.squeeze(scale, axis=reduce_axes).astype(jnp.float32)


def _quantize_activation(x):
    """Dynamic symmetric per-tensor activation quant -> (x_int8, scale)."""
    x32 = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(x32)), 1e-8) / 127.0
    x_q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return x_q, scale


def int8_matmul(x, w_q, scale):
    """``deq(quant(x)) @ deq(w).T`` with the contraction on the MXU in
    int8: ``x (..., in)`` float, ``w_q (out, in)`` int8, ``scale
    (out,)`` -- returns fp32 ``(..., out)`` (bias/cast are the
    caller's).  Shared by ``QuantizedLinear`` and the quantization-aware
    ``Linear``/``MultiHeadAttention`` applies."""
    x_q, x_scale = _quantize_activation(x)
    acc = lax.dot_general(
        x_q, w_q,
        (((x_q.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (scale * x_scale)


def int8_conv(x_nhwc, w_q, scale, *, stride, padding, dilation, groups):
    """Int8 NHWC conv: ``x`` float, ``w_q`` HWIO int8, ``scale`` (out,)
    -> fp32 NHWC accumulation scaled back to real units."""
    x_q, x_scale = _quantize_activation(x_nhwc)
    acc = lax.conv_general_dilated(
        x_q, w_q,
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (scale * x_scale)


class QuantizedLinear(Module):
    """Int8 linear (reference: nn/quantized/Linear.scala).

    Construct from a trained float layer (``QuantizedLinear(linear,
    params)``) or from pre-quantized arrays (the deserialization path --
    reference: nn/quantized/QuantSerializer.scala)."""

    def __init__(self, linear: Linear = None, params=None, *,
                 output_size=None, with_bias=True, weight_q=None,
                 scale=None, bias=None, name=None):
        if linear is not None:
            super().__init__(name or linear.name + "_int8")
            self.output_size = linear.output_size
            self.with_bias = linear.with_bias
            w_q, s = quantize_weights_per_channel(params["weight"], 0)
            self._params = {"weight_q": w_q, "scale": s[:, 0]}
            if self.with_bias:
                self._params["bias"] = params["bias"]
        else:
            super().__init__(name)
            self.output_size = output_size
            self.with_bias = with_bias
            self._params = {"weight_q": jnp.asarray(weight_q, jnp.int8),
                            "scale": jnp.asarray(scale, jnp.float32)}
            if with_bias:
                self._params["bias"] = jnp.asarray(bias, jnp.float32)
        self._state = ()

    def setup(self, rng, input_spec):
        return self._params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        y = int8_matmul(input, params["weight_q"], params["scale"])
        if self.with_bias:
            y = y + params["bias"]
        return y.astype(input.dtype), state


class QuantizedSpatialConvolution(Module):
    """Int8 conv (reference: nn/quantized/SpatialConvolution.scala).

    Weight HWIO quantized per output channel (axis 3).
    """

    def __init__(self, conv: SpatialConvolution, params=None, *,
                 weight_q=None, scale=None, bias=None, name=None):
        super().__init__(name or conv.name + "_int8")
        self.conv = conv
        if params is not None:
            w_q, s = quantize_weights_per_channel(params["weight"], 3)
            self._params = {"weight_q": w_q, "scale": s.reshape(-1)}
            if conv.with_bias:
                self._params["bias"] = params["bias"]
        else:                              # pre-quantized (deserialization)
            self._params = {"weight_q": jnp.asarray(weight_q, jnp.int8),
                            "scale": jnp.asarray(scale, jnp.float32)}
            if conv.with_bias:
                self._params["bias"] = jnp.asarray(bias, jnp.float32)
        self._state = ()

    def setup(self, rng, input_spec):
        return self._params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        c = self.conv
        x = input
        if c.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        y = int8_conv(x, params["weight_q"], params["scale"],
                      stride=c.stride, padding=c._padding(),
                      dilation=c.dilation, groups=c.n_group)
        if c.with_bias:
            y = y + params["bias"]
        y = y.astype(input.dtype)
        if c.data_format == "NCHW":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y, state


def quantize(model: Module) -> Module:
    """Rewrite a built model for int8 inference
    (reference: nn/quantized/Quantizer.scala Quantizer.quantize).

    Walks Sequential-style containers (children keyed "0".."n") and swaps
    every Linear / SpatialConvolution for its int8 twin, quantizing the
    trained weights in place.  Returns the model (mutated), in eval mode.

    For the non-mutating serving path (Graph / TransformerLM coverage,
    allow/deny predicate, fp32 original kept intact) use
    :func:`quantize_model`.
    """
    if not model.is_built():
        raise ValueError("quantize() expects a built (trained/loaded) model")
    undo = []
    try:
        _quantize_children(model, undo)
    except BaseException:
        # the in-place rewrite must be ALL-OR-NOTHING: a failure halfway
        # through (bad weights in one layer, an interrupt) must not
        # leave the model half-quantized -- replay the swaps backwards
        # so the caller keeps the exact pre-call model
        for fn in reversed(undo):
            fn()
        raise
    return model.evaluate()


def _swap_child(module, i, key, q, undo):
    # capture the params DICT itself: at undo time a nested container's
    # temporary ``_params`` binding has already been restored, so a
    # late ``module._params[key]`` lookup would miss the rewritten tree
    params = module._params
    old_child, old_params = module.modules[i], params[key]

    def revert(m=module, i=i, k=key, p=params, oc=old_child, op=old_params):
        m.modules[i] = oc
        p[k] = op

    undo.append(revert)
    module.modules[i] = q
    params[key] = q._params


def _quantize_children(module: Module, undo):
    if not isinstance(module, Container):
        return
    params = module._params
    for i, child in enumerate(module.modules):
        key = str(i)
        child_params = params.get(key) if isinstance(params, dict) else None
        if isinstance(child, Linear) and child_params:
            _swap_child(module, i, key,
                        QuantizedLinear(child, child_params), undo)
        elif child_params and type(child) in (SpatialConvolution,
                                             SpatialDilatedConvolution):
            # dilated variant included: the int8 conv carries rhs_dilation
            # (reference: nn/quantized/SpatialDilatedConvolution.scala)
            _swap_child(module, i, key,
                        QuantizedSpatialConvolution(child, child_params),
                        undo)
        elif isinstance(child, Container):
            # push params down so nested containers rewrite their dicts;
            # the child's own binding (None for a container inside a
            # built parent, or its live tree if it was built standalone)
            # is restored even when a nested rewrite raises -- the old
            # unconditional `child._params = None` corrupted a
            # standalone-built child's binding, and a mid-walk exception
            # left the borrowed subtree bound
            sub_params = params.get(key) if isinstance(params, dict) else None
            if isinstance(sub_params, dict):
                prev = child._params
                child._params = sub_params
                try:
                    _quantize_children(child, undo)
                finally:
                    child._params = prev


# --------------------------------------------------------------------------- #
# The general (non-mutating) post-training quantizer: the serving path.
# --------------------------------------------------------------------------- #

#: params-key layout of a quantized MultiHeadAttention: fused qkv and
#: output projections ride the MXU in int8; biases stay fp32
_MHA_SITES = (("qkv_weight", "qkv_weight_q", "qkv_scale"),
              ("out_weight", "out_weight_q", "out_scale"))


def _quantize_linear_params(params, lead):
    out = dict(params)
    w_q, s = quantize_channelwise(params["weight"], lead + 0, lead)
    del out["weight"]
    out["weight_q"], out["scale"] = w_q, s
    return out


def _quantize_conv_params(params, lead):
    out = dict(params)
    w_q, s = quantize_channelwise(params["weight"], lead + 3, lead)
    del out["weight"]
    out["weight_q"], out["scale"] = w_q, s
    return out


def _quantize_mha_params(params, lead):
    out = dict(params)
    for fp_key, q_key, s_key in _MHA_SITES:
        w_q, s = quantize_channelwise(params[fp_key], lead + 0, lead)
        del out[fp_key]
        out[q_key], out[s_key] = w_q, s
    return out


def quantize_params(model: Module, params=None,
                    select: Optional[Callable] = None):
    """Post-training weight quantization of a param tree -> a NEW tree.

    Walks ``model``'s module structure in parallel with ``params``
    (default: the model's own) via each container's
    ``_param_child_items`` alignment and rewrites every quantizable
    site's weight leaf to ``weight_q`` (int8, per-output-channel
    symmetric) + ``scale`` (fp32).  Quantizable sites:

    - ``Linear`` (weight ``(out, in)``, channel axis 0),
    - ``SpatialConvolution`` / ``SpatialDilatedConvolution`` (HWIO,
      channel axis 3; exact types only -- subclasses like
      ``SpaceToDepthStem`` restructure the weight inside ``apply``),
    - ``MultiHeadAttention`` (fused ``qkv_weight`` and ``out_weight``,
      channel axis 0).

    Everything else -- embedding tables, positional tables, the LM
    head, layernorm gains, biases -- passes through fp32 unchanged.
    Inside ``nn.ScanLayers`` the stacked subtree quantizes with a
    per-layer leading axis, so scan-compiled ``TransformerLM``
    checkpoints quantize without unstacking.

    ``select(path, module) -> bool`` is the allow/deny predicate over
    quantizable sites (path like ``"block0.fc1"`` or ``"blocks.attn"``;
    return False to keep that site fp32).  The input tree is never
    mutated.
    """
    from bigdl_tpu.nn.attention import MultiHeadAttention
    from bigdl_tpu.nn.containers import ScanLayers

    if params is None:
        if not model.is_built():
            raise ValueError(
                "quantize_params() expects a built model or an explicit "
                "params tree")
        params = model.parameters()[0]

    def walk(m, p, path, lead):
        if isinstance(p, dict):
            if type(m) is Linear and "weight" in p:
                if select is None or select(path, m):
                    return _quantize_linear_params(p, lead)
                return p
            if type(m) in (SpatialConvolution, SpatialDilatedConvolution) \
                    and "weight" in p:
                if select is None or select(path, m):
                    return _quantize_conv_params(p, lead)
                return p
            if type(m) is MultiHeadAttention and "qkv_weight" in p:
                if select is None or select(path, m):
                    return _quantize_mha_params(p, lead)
                return p
        if not isinstance(m, Container) or not isinstance(p, dict):
            return p
        items = m._param_child_items(p)
        if len(items) == 1 and items[0][0] is None:
            # the whole subtree belongs to one shared child: MapTable
            # (shared params, same rank) or ScanLayers (layer-stacked
            # leaves -- one more leading per-layer axis below here)
            return walk(items[0][1], p, path,
                        lead + (1 if isinstance(m, ScanLayers) else 0))
        by_key = dict(items)
        out = {}
        for k, v in p.items():
            child = by_key.get(k)
            if child is None:
                out[k] = v          # the container's OWN leaves stay fp32
            else:
                out[k] = walk(child, v, f"{path}.{k}" if path else k, lead)
        return out

    return walk(model, params, "", 0)


def quantize_model(model: Module, params=None,
                   select: Optional[Callable] = None):
    """Post-training quantization for serving -> a NEW ``(qmodel,
    qparams)`` pair; the fp32 original is untouched and keeps serving
    while the int8 twin stages (docs/performance.md, "Int8 inference").

    ``qparams`` is :func:`quantize_params` applied to ``params``
    (default: the model's current weights).  ``qmodel`` is a
    lightweight structural view of ``model`` bound to ``qparams``: the
    module tree (and eval state) is shared -- the quantization-aware
    ``apply`` of Linear/conv/attention consumes the int8 leaves -- but
    the compiled-eval-step cache is NOT shared, so the int8 executables
    never mix with (or evict) the fp32 model's.
    """
    if not model.is_built():
        raise ValueError("quantize_model() expects a built model")
    qparams = quantize_params(model, params, select)
    qmodel = copy.copy(model)
    qmodel._params = qparams
    qmodel._grads = None
    qmodel.train_mode = False
    # each model owns its executables (validation.compiled_eval_step
    # caches ON the instance); sharing would key int8 and fp32 steps
    # into one bound.  The serving step caches are worse than an
    # eviction hazard: copy.copy shares the DICT OBJECT, and the
    # compiled closures inside capture the fp32 original -- a shared
    # cache would hand the twin fp32 executables outright (the
    # speculative drafter would silently verify itself)
    for slot in [k for k in qmodel.__dict__ if k.startswith("_compiled_")]:
        qmodel.__dict__.pop(slot, None)
    return qmodel, qparams


def quantized_leaf_count(params) -> int:
    """Number of int8 leaves in a tree (0 = nothing quantized)."""
    return sum(1 for l in jax.tree.leaves(params)
               if getattr(l, "dtype", None) == jnp.int8)


def model_bytes(params) -> int:
    return sum(leaf.size * leaf.dtype.itemsize
               for leaf in jax.tree.leaves(params))
