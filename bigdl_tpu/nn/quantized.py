"""Int8 quantized inference.

Reference: nn/quantized/Quantizer.scala:27 (graph rewrite swapping
Linear/SpatialConvolution for int8 variants), tensor/QuantizedTensor.scala
(int8 storage + per-window scales, BigQuant JNI kernels).

TPU-native: int8 x int8 -> int32 matmul/conv is native on the MXU
(``preferred_element_type=jnp.int32``); no JNI, no descriptors.  Weights are
quantized per output channel (symmetric, like BigQuant); activations are
quantized dynamically per tensor at run time (the reference's runtime
min/max behaviour).  Expected wins match the reference whitepaper
(docs/whitepaper.md:192): ~4x model size, up to ~2x inference speed,
<1% accuracy loss.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.conv import SpatialConvolution, SpatialDilatedConvolution
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import Container, Module


def quantize_weights_per_channel(w, channel_axis: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 per-output-channel quantization -> (w_int8, scale)."""
    reduce_axes = tuple(a for a in range(w.ndim) if a != channel_axis)
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    w_q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return w_q, scale.astype(jnp.float32)


def _quantize_activation(x):
    """Dynamic symmetric per-tensor activation quant -> (x_int8, scale)."""
    x32 = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(x32)), 1e-8) / 127.0
    x_q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return x_q, scale


class QuantizedLinear(Module):
    """Int8 linear (reference: nn/quantized/Linear.scala).

    Construct from a trained float layer (``QuantizedLinear(linear,
    params)``) or from pre-quantized arrays (the deserialization path --
    reference: nn/quantized/QuantSerializer.scala)."""

    def __init__(self, linear: Linear = None, params=None, *,
                 output_size=None, with_bias=True, weight_q=None,
                 scale=None, bias=None, name=None):
        if linear is not None:
            super().__init__(name or linear.name + "_int8")
            self.output_size = linear.output_size
            self.with_bias = linear.with_bias
            w_q, s = quantize_weights_per_channel(params["weight"], 0)
            self._params = {"weight_q": w_q, "scale": s[:, 0]}
            if self.with_bias:
                self._params["bias"] = params["bias"]
        else:
            super().__init__(name)
            self.output_size = output_size
            self.with_bias = with_bias
            self._params = {"weight_q": jnp.asarray(weight_q, jnp.int8),
                            "scale": jnp.asarray(scale, jnp.float32)}
            if with_bias:
                self._params["bias"] = jnp.asarray(bias, jnp.float32)
        self._state = ()

    def setup(self, rng, input_spec):
        return self._params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        x_q, x_scale = _quantize_activation(input)
        acc = lax.dot_general(
            x_q, params["weight_q"],
            (((x_q.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (params["scale"] * x_scale)
        if self.with_bias:
            y = y + params["bias"]
        return y.astype(input.dtype), state


class QuantizedSpatialConvolution(Module):
    """Int8 conv (reference: nn/quantized/SpatialConvolution.scala).

    Weight HWIO quantized per output channel (axis 3).
    """

    def __init__(self, conv: SpatialConvolution, params=None, *,
                 weight_q=None, scale=None, bias=None, name=None):
        super().__init__(name or conv.name + "_int8")
        self.conv = conv
        if params is not None:
            w_q, s = quantize_weights_per_channel(params["weight"], 3)
            self._params = {"weight_q": w_q, "scale": s.reshape(-1)}
            if conv.with_bias:
                self._params["bias"] = params["bias"]
        else:                              # pre-quantized (deserialization)
            self._params = {"weight_q": jnp.asarray(weight_q, jnp.int8),
                            "scale": jnp.asarray(scale, jnp.float32)}
            if conv.with_bias:
                self._params["bias"] = jnp.asarray(bias, jnp.float32)
        self._state = ()

    def setup(self, rng, input_spec):
        return self._params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        c = self.conv
        x = input
        if c.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        x_q, x_scale = _quantize_activation(x)
        acc = lax.conv_general_dilated(
            x_q, params["weight_q"],
            window_strides=c.stride,
            padding=c._padding(),
            rhs_dilation=c.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c.n_group,
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (params["scale"] * x_scale)
        if c.with_bias:
            y = y + params["bias"]
        y = y.astype(input.dtype)
        if c.data_format == "NCHW":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y, state


def quantize(model: Module) -> Module:
    """Rewrite a built model for int8 inference
    (reference: nn/quantized/Quantizer.scala Quantizer.quantize).

    Walks Sequential-style containers (children keyed "0".."n") and swaps
    every Linear / SpatialConvolution for its int8 twin, quantizing the
    trained weights in place.  Returns the model (mutated), in eval mode.
    """
    if not model.is_built():
        raise ValueError("quantize() expects a built (trained/loaded) model")
    _quantize_children(model)
    return model.evaluate()


def _quantize_children(module: Module):
    if not isinstance(module, Container):
        return
    params = module._params
    for i, child in enumerate(module.modules):
        key = str(i)
        child_params = params.get(key) if isinstance(params, dict) else None
        if isinstance(child, Linear) and child_params:
            q = QuantizedLinear(child, child_params)
            module.modules[i] = q
            params[key] = q._params
        elif child_params and type(child) in (SpatialConvolution,
                                             SpatialDilatedConvolution):
            # dilated variant included: the int8 conv carries rhs_dilation
            # (reference: nn/quantized/SpatialDilatedConvolution.scala)
            q = QuantizedSpatialConvolution(child, child_params)
            module.modules[i] = q
            params[key] = q._params
        elif isinstance(child, Container):
            # push params down so nested containers rewrite their dicts
            sub_params = params.get(key) if isinstance(params, dict) else None
            if isinstance(sub_params, dict):
                child._params = sub_params
                _quantize_children(child)
                child._params = None


def model_bytes(params) -> int:
    return sum(leaf.size * leaf.dtype.itemsize
               for leaf in jax.tree.leaves(params))
