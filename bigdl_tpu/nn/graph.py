"""Graph container: build arbitrary DAGs by calling modules on nodes.

Reference: nn/Graph.scala:72 (ModuleNode DAG + buildBackwardGraph),
nn/StaticGraph.scala:38 (topological-order execution),
utils/DirectedGraph.scala:36 (topologySort).

TPU-native: the graph is traced once in topological order inside ``apply``;
XLA sees one flat program and fuses across node boundaries, so there is no
scheduler / FrameManager analogue (nn/DynamicGraph.scala) -- data-dependent
control flow belongs in lax.cond/scan inside a module instead.

Usage (mirrors the reference)::

    inp = Input()
    h = Linear(10, 20)(inp)
    a = ReLU()(h)
    b = Tanh()(h)
    out = CAddTable()(a, b)
    model = Graph([inp], [out])
"""

from typing import List

from bigdl_tpu.nn.module import Container, Module, child_rng


class Node:
    """A module applied to the outputs of other nodes (reference: ModuleNode)."""

    def __init__(self, module, inputs: List["Node"]):
        self.module = module
        self.inputs = inputs


def Input(name=None) -> Node:
    """Placeholder node (reference: nn/Graph.scala Input())."""
    return Node(None, [])


class Graph(Container):
    """Static DAG executed in topological order (reference: nn/StaticGraph.scala:38)."""

    def __init__(self, inputs, outputs, name=None, allow_unused=False):
        super().__init__(name)
        self.input_nodes = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.output_nodes = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        self.allow_unused = allow_unused
        self._topo = self._topo_sort()
        for node in self._topo:
            if node.module is not None:
                self.add(node.module)

    def _topo_sort(self) -> List[Node]:
        """DFS post-order topological sort (reference: DirectedGraph.topologySort)."""
        order, seen, on_stack = [], set(), set()

        def visit(node):
            if id(node) in seen:
                return
            if id(node) in on_stack:
                raise ValueError("Graph contains a cycle")
            on_stack.add(id(node))
            for parent in node.inputs:
                visit(parent)
            on_stack.discard(id(node))
            seen.add(id(node))
            order.append(node)

        for out in self.output_nodes:
            visit(out)
        for inp in self.input_nodes:
            if id(inp) not in seen and not self.allow_unused:
                raise ValueError("An input node is not connected to any output")
        return order

    def _gather(self, node, values):
        ins = [values[id(p)] for p in node.inputs]
        return ins[0] if len(ins) == 1 else tuple(ins)

    def _param_child_items(self, params):
        # params are keyed by TOPO index (module-less Input nodes consume
        # indices), not by child-list position -- align accordingly for
        # the frozen-mask walk
        return [(str(i), node.module) for i, node in enumerate(self._topo)
                if node.module is not None]

    def setup(self, rng, input_spec):
        specs = {}
        in_specs = (
            [input_spec] if len(self.input_nodes) == 1 else list(input_spec)
        )
        for node, spec in zip(self.input_nodes, in_specs):
            specs[id(node)] = spec
        params, state = {}, {}
        for i, node in enumerate(self._topo):
            if node.module is None:
                continue
            node_in = self._gather(node, specs)
            p, s = node.module.setup(child_rng(rng, i), node_in)
            params[str(i)], state[str(i)] = p, s
            specs[id(node)] = node.module.output_spec(p, s, node_in)
        return params, state

    def apply(self, params, state, input, *, training=False, rng=None):
        values = {}
        ins = [input] if len(self.input_nodes) == 1 else list(input)
        for node, x in zip(self.input_nodes, ins):
            values[id(node)] = x
        new_state = dict(state)
        for i, node in enumerate(self._topo):
            if node.module is None:
                continue
            y, s = node.module.apply(
                params[str(i)], state[str(i)], self._gather(node, values),
                training=training, rng=child_rng(rng, i),
            )
            values[id(node)] = y
            new_state[str(i)] = s
        outs = [values[id(n)] for n in self.output_nodes]
        return (outs[0] if len(outs) == 1 else tuple(outs)), new_state
