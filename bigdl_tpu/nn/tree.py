"""Tree-structured LSTM (reference: nn/BinaryTreeLSTM.scala:40).

The reference builds one leaf-module / composer clone per tree node and
recurses over the parse tree in Scala (recursiveForward,
BinaryTreeLSTM.scala:265).  TPU-native redesign: trees are data, not
control flow -- every sweep computes leaf states AND composed states for
ALL nodes of ALL trees in one batched matmul, reading children states from
a node-state buffer; after ``depth`` sweeps (bounded by node count) every
node has its fixed point.  The whole thing is `lax.fori_loop` over sweeps,
so a batch of ragged trees is one static-shape XLA program.

Tree encoding matches TensorTree (BinaryTreeLSTM.scala:513): trees
(B, nNodes, 3) rows [leftChild, rightChild, marker] with 1-based node ids;
marker > 0 = leaf holding 1-based word position, marker -1 = root flag,
children 0 = absent. Output (B, nNodes, hidden) of per-node h states.
"""

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.initialization import RandomUniform
from bigdl_tpu.nn.module import Module, child_rng


class BinaryTreeLSTM(Module):
    """Binary tree LSTM for e.g. constituency-parse sentiment.

    Input: (embeddings (B, seq, input_size), trees (B, nNodes, 3)).
    Output: (B, nNodes, hidden_size) node hidden states.
    """

    def __init__(self, input_size, hidden_size, gate_output=True,
                 max_depth=None, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.gate_output = gate_output
        self.max_depth = max_depth

    def setup(self, rng, input_spec):
        init = RandomUniform()
        h, i = self.hidden_size, self.input_size
        k = iter(range(100))
        params = {
            # leaf module (createLeafModuleWithGraph, BinaryTreeLSTM.scala:63)
            "leaf_c_w": init.init(child_rng(rng, next(k)), (h, i), i, h),
            "leaf_c_b": jnp.zeros((h,), jnp.float32),
            # composer: 4or5 gates, each Linear(lh)+Linear(rh)
            # (createComposerWithGraph, BinaryTreeLSTM.scala:82)
            "comp_l_w": init.init(child_rng(rng, next(k)), (5 * h, h), h, h),
            "comp_l_b": jnp.zeros((5 * h,), jnp.float32),
            "comp_r_w": init.init(child_rng(rng, next(k)), (5 * h, h), h, h),
            "comp_r_b": jnp.zeros((5 * h,), jnp.float32),
        }
        if self.gate_output:
            params["leaf_o_w"] = init.init(child_rng(rng, next(k)), (h, i), i, h)
            params["leaf_o_b"] = jnp.zeros((h,), jnp.float32)
        return params, ()

    @staticmethod
    def root_hidden(output, trees):
        """Gather each tree's ROOT hidden state: (B, nNodes, H) + trees ->
        (B, H).  The root is the node whose marker column is -1."""
        marker = trees[..., 2].astype(jnp.int32)
        root = jnp.argmax(marker == -1, axis=-1)            # (B,)
        return jnp.take_along_axis(
            output, root[:, None, None], axis=1)[:, 0]

    def _leaf_states(self, params, emb):
        """emb (..., input_size) -> (c, h)"""
        dt = emb.dtype
        c = emb @ params["leaf_c_w"].astype(dt).T + params["leaf_c_b"].astype(dt)
        if self.gate_output:
            o = jax.nn.sigmoid(
                emb @ params["leaf_o_w"].astype(dt).T
                + params["leaf_o_b"].astype(dt))
            h = o * jnp.tanh(c)
        else:
            h = jnp.tanh(c)
        return c, h

    def _compose(self, params, lc, lh, rc, rh):
        dt = lh.dtype
        gates = (lh @ params["comp_l_w"].astype(dt).T
                 + params["comp_l_b"].astype(dt)
                 + rh @ params["comp_r_w"].astype(dt).T
                 + params["comp_r_b"].astype(dt))
        i, lf, rf, update, o = jnp.split(gates, 5, axis=-1)
        c = (jax.nn.sigmoid(i) * jnp.tanh(update)
             + jax.nn.sigmoid(lf) * lc + jax.nn.sigmoid(rf) * rc)
        if self.gate_output:
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
        else:
            h = jnp.tanh(c)
        return c, h

    @staticmethod
    def _height_bound(trees, n_nodes):
        """Sweep count needed for the fixed point = max tree height.

        With concrete (non-traced) trees the exact height is computed on the
        host, so the compose loop is O(N * height) instead of O(N^2); under a
        tracer fall back to the safe N-sweeps bound (or set ``max_depth``).
        """
        import numpy as np
        from jax.core import Tracer
        if isinstance(trees, Tracer):
            return n_nodes
        t = np.asarray(trees)
        left, right = t[..., 0], t[..., 1]
        # height[i] = 1 for leaves; 1 + max(children) for internal; iterate
        # to fixed point (bounded by true height)
        height = np.ones(t.shape[:2], np.int64)
        for _ in range(n_nodes):
            lh = np.where(left > 0, np.take_along_axis(
                height, np.maximum(left - 1, 0), axis=1), 0)
            rh = np.where(right > 0, np.take_along_axis(
                height, np.maximum(right - 1, 0), axis=1), 0)
            new = np.where(left > 0, 1 + np.maximum(lh, rh), 1)
            if (new == height).all():
                break
            height = new
        return max(int(height.max()), 1)

    def apply(self, params, state, input, *, training=False, rng=None):
        emb, trees = input
        trees = trees.astype(jnp.int32)
        b, n_nodes = trees.shape[0], trees.shape[1]
        h_dim = self.hidden_size
        depth = self.max_depth or self._height_bound(trees, n_nodes)

        left = trees[..., 0]                       # (B, N) 1-based, 0 = none
        right = trees[..., 1]
        marker = trees[..., 2]
        is_leaf = marker > 0
        is_internal = left > 0
        # leaf embeddings: word position is 1-based into the sequence
        word = jnp.clip(marker - 1, 0, emb.shape[1] - 1)
        leaf_emb = jnp.take_along_axis(
            emb, word[..., None], axis=1)          # (B, N, input)
        leaf_c, leaf_h = self._leaf_states(params, leaf_emb)
        zero = jnp.zeros((b, 1, h_dim), emb.dtype)  # slot 0 = absent child

        def sweep(_, bufs):
            cbuf, hbuf = bufs                       # (B, N+1, H), slot 0 zeros

            def child(buf, idx):
                return jnp.take_along_axis(buf, idx[..., None], axis=1)

            lc, lh = child(cbuf, left), child(hbuf, left)
            rc, rh = child(cbuf, right), child(hbuf, right)
            comp_c, comp_h = self._compose(params, lc, lh, rc, rh)
            new_c = jnp.where(is_leaf[..., None], leaf_c,
                              jnp.where(is_internal[..., None], comp_c, 0.0))
            new_h = jnp.where(is_leaf[..., None], leaf_h,
                              jnp.where(is_internal[..., None], comp_h, 0.0))
            return (jnp.concatenate([zero, new_c], axis=1),
                    jnp.concatenate([zero, new_h], axis=1))

        init = (jnp.zeros((b, n_nodes + 1, h_dim), emb.dtype),
                jnp.zeros((b, n_nodes + 1, h_dim), emb.dtype))
        _, hbuf = lax.fori_loop(0, depth, sweep, init)
        return hbuf[:, 1:], state
