"""Sparse tensors and sparse layers, TPU-native.

Reference surface:
  tensor/SparseTensor.scala        -- COO-ish sparse tensor (1463 LoC)
  nn/LookupTableSparse.scala:47    -- embedding_lookup_sparse (sum/mean/sqrtn)
  nn/SparseLinear.scala:45         -- Linear on sparse input
  nn/SparseJoinTable.scala:36      -- concat sparse tensors on dim 2
  nn/DenseToSparse.scala           -- conversion layer
  dataset/MiniBatch.scala:588      -- SparseMiniBatch

TPU-native redesign: XLA wants static shapes, so :class:`SparseTensor` is a
*padded* COO — `indices (cap, ndim)`, `values (cap,)` and a validity count,
where `cap` is a fixed capacity (the analogue of the reference's nnz, but
padded so the same compiled program serves every batch).  Invalid slots
carry index 0 / value 0 and are masked.  Sparse ops become
`jax.ops.segment_sum` over the row coordinate — a scatter-add that XLA
lowers natively — instead of the reference's scalar CSR loops.  The class
is a pytree, so sparse activities flow through jit/grad like any array.
"""

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.initialization import RandomNormal


@jax.tree_util.register_pytree_node_class
class SparseTensor:
    """Padded-COO sparse tensor (reference: tensor/SparseTensor.scala).

    indices: (cap, ndim) int32, row-major sorted by construction;
    values:  (cap,) — float or int;
    nnz:     scalar int32, number of valid leading entries;
    shape:   static dense shape tuple.
    """

    def __init__(self, indices, values, shape: Tuple[int, ...], nnz=None):
        self.indices = jnp.asarray(indices, jnp.int32)
        self.values = jnp.asarray(values)
        self.shape = tuple(int(s) for s in shape)
        self.nnz = jnp.asarray(
            self.values.shape[0] if nnz is None else nnz, jnp.int32)

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.indices, self.values, self.nnz), self.shape

    @classmethod
    def tree_unflatten(cls, shape, leaves):
        indices, values, nnz = leaves
        obj = cls.__new__(cls)
        obj.indices, obj.values, obj.nnz, obj.shape = indices, values, nnz, shape
        return obj

    # -- construction ------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.values.shape[0]

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def valid_mask(self):
        return jnp.arange(self.capacity) < self.nnz

    @staticmethod
    def from_dense(x, capacity: Optional[int] = None) -> "SparseTensor":
        """Densify host-side into padded COO (row-major entry order)."""
        x = np.asarray(x)
        idx = np.argwhere(x != 0)
        vals = x[tuple(idx.T)] if idx.size else np.zeros((0,), x.dtype)
        nnz = idx.shape[0]
        cap = capacity or max(nnz, 1)
        assert cap >= nnz, f"capacity {cap} < nnz {nnz}"
        pad = cap - nnz
        idx = np.concatenate([idx, np.zeros((pad, x.ndim), np.int64)])
        vals = np.concatenate([vals, np.zeros((pad,), x.dtype)])
        return SparseTensor(idx, vals, x.shape, nnz)

    @staticmethod
    def coo(indices, values, shape, nnz=None) -> "SparseTensor":
        return SparseTensor(indices, values, shape, nnz)

    def to_dense(self):
        mask = self.valid_mask()
        flat_idx = jnp.zeros((self.capacity,), jnp.int32)
        stride = 1
        for d in range(self.ndim - 1, -1, -1):
            flat_idx = flat_idx + self.indices[:, d] * stride
            stride *= self.shape[d]
        flat_idx = jnp.where(mask, flat_idx, stride)  # park invalid out of range
        dense = jnp.zeros((int(np.prod(self.shape)) + 1,), self.values.dtype)
        dense = dense.at[flat_idx].add(jnp.where(mask, self.values, 0))
        return dense[:-1].reshape(self.shape)

    def n_nonzero_by_row(self):
        """(rows,) count of valid entries per leading index
        (reference: SparseTensor.numNonZeroByRow)."""
        rows = self.shape[0]
        seg = jnp.where(self.valid_mask(), self.indices[:, 0], rows)
        return jax.ops.segment_sum(
            jnp.ones((self.capacity,), jnp.int32), seg, num_segments=rows + 1
        )[:rows]

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, cap={self.capacity})")


def sparse_join(tensors: Sequence[SparseTensor]) -> SparseTensor:
    """Concatenate 2-D sparse tensors along dim 2 (column-wise).

    Reference: nn/SparseJoinTable.scala:36.  Entry order becomes
    (tensor-major within row) which densifies identically.
    """
    assert all(t.ndim == 2 for t in tensors)
    rows = tensors[0].shape[0]
    assert all(t.shape[0] == rows for t in tensors)
    col_off = np.cumsum([0] + [t.shape[1] for t in tensors])
    parts_idx, parts_val = [], []
    for off, t in zip(col_off, tensors):
        mask = t.valid_mask()
        idx = t.indices + jnp.asarray([0, off], jnp.int32)
        # park invalid entries at row `rows` so a final sort groups them last
        idx = jnp.where(mask[:, None], idx, jnp.asarray([rows, 0], jnp.int32))
        parts_idx.append(idx)
        parts_val.append(jnp.where(mask, t.values, 0))
    indices = jnp.concatenate(parts_idx)
    values = jnp.concatenate(parts_val)
    # stable row-major sort so rows stay contiguous
    order = jnp.argsort(indices[:, 0], stable=True)
    nnz = sum(t.nnz for t in tensors)
    return SparseTensor(
        indices[order], values[order], (rows, int(col_off[-1])), nnz)


def sparse_stack(samples: Sequence[np.ndarray], capacity=None) -> SparseTensor:
    """Stack dense host rows into one batched SparseTensor — the
    SparseMiniBatch batching path (reference: dataset/MiniBatch.scala:588).

    Default capacity is the batch's full dense element count, so every
    same-shaped batch yields identical array shapes and reuses one compiled
    program regardless of its nnz."""
    batch = np.stack([np.asarray(s) for s in samples])
    if capacity is None:
        capacity = int(np.prod(batch.shape))
    return SparseTensor.from_dense(batch, capacity)


class DenseToSparse(Module):
    """Conversion layer (reference: nn/DenseToSparse.scala). Capacity is the
    full element count, keeping shapes static under jit."""

    def apply(self, params, state, input, *, training=False, rng=None):
        x = jnp.asarray(input)
        flat = x.reshape(-1)
        mask = flat != 0
        # stable order of original positions, valid entries first
        order = jnp.argsort(~mask, stable=True)
        idx = jnp.stack(jnp.unravel_index(order, x.shape), axis=1)
        values = flat[order] * mask[order]
        idx = jnp.where(mask[order][:, None], idx, 0)
        return SparseTensor.coo(idx, values, x.shape, jnp.sum(mask)), state


class LookupTableSparse(Module):
    """embedding_lookup_sparse (reference: nn/LookupTableSparse.scala:47).

    Input: a 2-D :class:`SparseTensor` of positive integer ids (1-based like
    the reference), or a (ids, weights) tuple of SparseTensors with matching
    sparsity. Output: (batch, n_output) combined embeddings.

    combiner: 'sum' | 'mean' | 'sqrtn'; max_norm: l2-clip each embedding
    row before combining.  The combine is one segment_sum over rows.
    """

    def __init__(self, n_index, n_output, combiner="sum", max_norm=-1.0,
                 weight_init=None, name=None):
        super().__init__(name)
        assert combiner in ("sum", "mean", "sqrtn"), combiner
        self.n_index = n_index
        self.n_output = n_output
        self.combiner = combiner
        self.max_norm = max_norm
        self.weight_init = weight_init or RandomNormal(0.0, 1.0)

    def setup(self, rng, input_spec):
        w = self.weight_init.init(
            rng, (self.n_index, self.n_output), self.n_index, self.n_output)
        return {"weight": w}, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        if isinstance(input, (tuple, list)):
            ids_sp, w_sp = input
            sp_weights = w_sp.values.astype(jnp.float32)
        else:
            ids_sp, sp_weights = input, None
        mask = ids_sp.valid_mask()
        rows = ids_sp.indices[:, 0]
        batch = ids_sp.shape[0]
        ids = jnp.clip(ids_sp.values.astype(jnp.int32) - 1, 0, self.n_index - 1)

        w = params["weight"]
        emb = jnp.take(w, ids, axis=0)                      # (cap, D)
        if self.max_norm > 0:
            norms = jnp.linalg.norm(emb, axis=-1, keepdims=True)
            emb = emb * jnp.minimum(1.0, self.max_norm / jnp.maximum(norms, 1e-12))
        sw = sp_weights if sp_weights is not None else jnp.ones_like(
            emb[:, 0])
        sw = jnp.where(mask, sw, 0.0)

        seg = jnp.where(mask, rows, batch)
        summed = jax.ops.segment_sum(
            emb * sw[:, None], seg, num_segments=batch + 1)[:batch]
        if self.combiner == "sum":
            return summed, state
        if self.combiner == "mean":
            denom = jax.ops.segment_sum(sw, seg, num_segments=batch + 1)[:batch]
        else:  # sqrtn
            denom = jnp.sqrt(
                jax.ops.segment_sum(sw * sw, seg, num_segments=batch + 1)[:batch])
        return summed / jnp.maximum(denom, 1e-12)[:, None], state


class SparseLinear(Linear):
    """Linear over a 2-D SparseTensor input (reference: nn/SparseLinear.scala:45).

    y[b] = sum over entries (b, c, v) of v * W[:, c] + bias — a gather of
    weight columns plus one segment_sum; the backward to W is the matching
    scatter, derived by autodiff.
    """

    def apply(self, params, state, input, *, training=False, rng=None):
        if not isinstance(input, SparseTensor):
            return super().apply(params, state, input, training=training, rng=rng)
        assert input.ndim == 2, "SparseLinear input must be 2-D"
        w = params["weight"]                     # (out, in)
        mask = input.valid_mask()
        rows = jnp.where(mask, input.indices[:, 0], input.shape[0])
        cols = input.indices[:, 1]
        vals = jnp.where(mask, input.values.astype(w.dtype), 0)
        contrib = vals[:, None] * w.T[cols]      # (cap, out)
        y = jax.ops.segment_sum(
            contrib, rows, num_segments=input.shape[0] + 1)[: input.shape[0]]
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state

    def setup(self, rng, input_spec):
        if isinstance(input_spec, SparseTensor) or hasattr(input_spec, "shape"):
            shape = getattr(input_spec, "shape", None)
            if self.input_size is None and shape is not None:
                self.input_size = shape[-1]
        return super().setup(rng, _DenseSpec((1, self.input_size)))


class _DenseSpec:
    def __init__(self, shape):
        self.shape = shape


def sparse_recommender(n_ids, n_classes=5, embed_dim=16, hidden=32):
    """The MovieLens recommender of the second-workload drill
    (docs/robustness.md, "Continuous deployment"): dense ``(N, 2)``
    1-based id features (``dataset.movielens.to_id_pairs`` /
    ``to_id_features``) re-sparsify INSIDE the jitted step
    (``DenseToSparse``, static capacity), sum user+item embeddings
    (``LookupTableSparse``) and classify the rating -- so the whole
    model is this module's sparse path end-to-end, servable through
    ``ServingEngine`` with ordinary batch-bucket padding (a padded
    zero row has no valid sparse entries and contributes nothing).

    ``n_ids``: the shared id space size (``n_users + n_items``)."""
    from bigdl_tpu.nn.activations import ReLU
    from bigdl_tpu.nn.containers import Sequential

    return (Sequential()
            .add(DenseToSparse())
            .add(LookupTableSparse(n_ids, embed_dim, combiner="sum"))
            .add(Linear(embed_dim, hidden))
            .add(ReLU())
            .add(Linear(hidden, n_classes)))
