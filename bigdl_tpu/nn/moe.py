"""Mixture-of-Experts layers (Switch/Mixtral-style top-k routing).

No reference analogue (SURVEY.md section 2.4: expert parallelism absent) --
built the canonical TPU way: expert parameters are *stacked* on a leading
expert dimension and the dispatch/compute/combine path is three dense
einsums with a static capacity, so the whole layer is MXU-shaped with no
dynamic shapes.  Sharding the expert dimension over an ``expert`` mesh axis
(parallel/ep.py) turns the dispatch/combine einsums into XLA all-to-alls
over ICI -- expert parallelism falls out of GSPMD annotations.

Routing: top-k gating with softmax probs, capacity ``C = ceil(T/E * cf)``
per expert; overflowing tokens are dropped (standard Switch behaviour) and
the load-balancing auxiliary loss (Shazeer et al.) keeps the router honest.
"""

import math

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.initialization import Xavier
from bigdl_tpu.nn.module import Module, child_rng
from bigdl_tpu.nn.normalization import LayerNorm


class MoE(Module):
    """Top-k routed expert MLP: (N, T, D) -> (N, T, D).

    apply() returns ``(out, {"aux_loss": scalar})`` -- the train step adds
    ``aux_weight * aux_loss`` to the task loss.
    """

    def __init__(self, hidden_size: int, num_experts: int, k: int = 2,
                 mlp_ratio: int = 4, capacity_factor: float = 1.25,
                 name=None):
        super().__init__(name)
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.k = min(k, num_experts)
        self.mlp_ratio = mlp_ratio
        self.capacity_factor = capacity_factor

    def setup(self, rng, input_spec):
        d, f, e = (self.hidden_size, self.mlp_ratio * self.hidden_size,
                   self.num_experts)
        init = Xavier()
        w1 = jnp.stack([init.init(child_rng(rng, 2 + i), (d, f), d, f)
                        for i in range(e)])
        w2 = jnp.stack([init.init(child_rng(rng, 100 + i), (f, d), f, d)
                        for i in range(e)])
        return {
            "gate": init.init(child_rng(rng, 0), (d, e), d, e),
            "w1": w1,                      # (E, D, F) expert-stacked
            "b1": jnp.zeros((e, f), jnp.float32),
            "w2": w2,                      # (E, F, D)
            "b2": jnp.zeros((e, d), jnp.float32),
        }, ()

    def _capacity(self, tokens: int) -> int:
        # k*tokens routing assignments share E expert slots
        return max(
            self.k,
            int(math.ceil(
                self.k * tokens / self.num_experts * self.capacity_factor)))

    def apply(self, params, state, input, *, training=False, rng=None):
        n, t, d = input.shape
        e, k = self.num_experts, self.k
        tokens = n * t
        cap = self._capacity(tokens)
        x = input.reshape(tokens, d)

        logits = (x @ params["gate"].astype(x.dtype)).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)              # (T, E)
        gate_vals, expert_idx = jax.lax.top_k(probs, k)       # (T, k)
        gate_vals = gate_vals / jnp.clip(
            gate_vals.sum(-1, keepdims=True), 1e-9)

        # position of each (token, choice) within its expert's capacity
        sel = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (T, k, E)
        # rank within expert: cumulative count over (token, choice) pairs in
        # routing priority order (choice-major so 1st choices beat 2nd)
        flat_sel = sel.transpose(1, 0, 2).reshape(k * tokens, e)
        pos = jnp.cumsum(flat_sel, axis=0) - flat_sel          # (k*T, E)
        pos = (pos * flat_sel).sum(-1)                         # (k*T,)
        fits = pos < cap
        pos = pos.reshape(k, tokens).transpose(1, 0)           # (T, k)
        fits = fits.reshape(k, tokens).transpose(1, 0)

        gate_vals = gate_vals * fits.astype(jnp.float32)
        # dispatch/combine tensors (T, E, C)
        combine = jnp.einsum(
            "tk,tke,tkc->tec", gate_vals, sel,
            jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32) *
            fits[..., None].astype(jnp.float32))
        dispatch = (combine > 0).astype(x.dtype)

        # expert compute, all MXU einsums over the stacked expert dim
        ex_in = jnp.einsum("tec,td->ecd", dispatch, x)
        h = jnp.einsum("ecd,edf->ecf", ex_in,
                       params["w1"].astype(x.dtype))
        h = h + params["b1"][:, None, :].astype(x.dtype)
        h = jax.nn.gelu(h)
        h = jnp.einsum("ecf,efd->ecd", h, params["w2"].astype(x.dtype))
        h = h + params["b2"][:, None, :].astype(x.dtype)
        out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), h)

        # load-balance aux loss: E * mean(fraction_routed) . mean(prob)
        frac = sel[:, 0, :].mean(0)            # first-choice assignment share
        mean_prob = probs.mean(0)
        aux = (frac * mean_prob).sum() * e
        return out.reshape(n, t, d), {"aux_loss": aux}


class MoETransformerBlock(Module):
    """Pre-LN block with MoE in place of the dense MLP."""

    def __init__(self, hidden_size, num_heads, num_experts, k=2,
                 mlp_ratio=4, capacity_factor=1.25, causal=True, name=None):
        super().__init__(name)
        from bigdl_tpu.nn.attention import MultiHeadAttention
        self.ln1 = LayerNorm(hidden_size)
        self.attn = MultiHeadAttention(hidden_size, num_heads, causal)
        self.ln2 = LayerNorm(hidden_size)
        self.moe = MoE(hidden_size, num_experts, k, mlp_ratio,
                       capacity_factor)

    def setup(self, rng, input_spec):
        params = {}
        for i, (key, m) in enumerate([("ln1", self.ln1), ("attn", self.attn),
                                      ("ln2", self.ln2), ("moe", self.moe)]):
            p, _ = m.setup(child_rng(rng, i), input_spec)
            params[key] = p
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        h, _ = self.ln1.apply(params["ln1"], (), input)
        a, _ = self.attn.apply(params["attn"], (), h, training=training,
                               rng=child_rng(rng, 0))
        x = input + a
        h, _ = self.ln2.apply(params["ln2"], (), x)
        h, st = self.moe.apply(params["moe"], (), h, training=training)
        return x + h, st


class MoETransformerLM(Module):
    """Decoder-only MoE LM; apply() -> (logits, {"aux_loss": total})."""

    def __init__(self, vocab_size, hidden_size, num_heads, num_layers,
                 num_experts, k=2, max_len=2048, mlp_ratio=4,
                 capacity_factor=1.25, name=None):
        super().__init__(name)
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.max_len = max_len
        self.blocks = [
            MoETransformerBlock(hidden_size, num_heads, num_experts, k,
                                mlp_ratio, capacity_factor)
            for _ in range(num_layers)]
        self.ln_f = LayerNorm(hidden_size)

    def setup(self, rng, input_spec):
        d = self.hidden_size
        params = {
            "wte": 0.02 * jax.random.normal(child_rng(rng, 0),
                                            (self.vocab_size, d)),
            "wpe": 0.01 * jax.random.normal(child_rng(rng, 1),
                                            (self.max_len, d)),
            "head": 0.02 * jax.random.normal(child_rng(rng, 2),
                                             (self.vocab_size, d)),
        }
        hid_spec = jax.ShapeDtypeStruct(
            (input_spec.shape[0], input_spec.shape[1], d), jnp.float32)
        for i, b in enumerate(self.blocks):
            params[f"block{i}"], _ = b.setup(child_rng(rng, 3 + i), hid_spec)
        params["ln_f"], _ = self.ln_f.setup(child_rng(rng, 99), hid_spec)
        return params, ()

    def apply(self, params, state, input, *, training=False, rng=None):
        t = input.shape[1]
        x = jnp.take(params["wte"], input.astype(jnp.int32), axis=0)
        x = x + params["wpe"][:t][None]
        aux = jnp.float32(0.0)
        for i, b in enumerate(self.blocks):
            x, st = b.apply(params[f"block{i}"], (), x, training=training,
                            rng=child_rng(rng, i))
            aux = aux + st["aux_loss"]
        x, _ = self.ln_f.apply(params["ln_f"], (), x)
        logits = x @ params["head"].astype(x.dtype).T
        return logits, {"aux_loss": aux}
