"""TF-style forward-only operations.

Reference: nn/ops/Operation.scala:32 (Operation = forward-only module whose
backward raises) + the 71-file op zoo under nn/ops/ (arithmetic, comparison,
logical, array, reduction ops) and nn/tf/ stateless ops.

Each op is a thin Module over the matching jnp/lax primitive -- XLA fuses
them; there is no per-op kernel to manage.  All are usable inside Graph /
Sequential like any layer.
"""

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class Operation(Module):
    """Forward-only marker (reference: nn/ops/Operation.scala:32)."""

    def backward(self, input, grad_output):
        raise RuntimeError("Operation does not support backward "
                           "(reference semantics)")


class _Binary(Operation):
    def fn(self, a, b):
        raise NotImplementedError

    def apply(self, params, state, input, *, training=False, rng=None):
        a, b = input
        return self.fn(a, b), state


class Add(_Binary):
    def fn(self, a, b):
        return a + b


class Subtract(_Binary):
    def fn(self, a, b):
        return a - b


class Multiply(_Binary):
    def fn(self, a, b):
        return a * b


class Divide(_Binary):
    def fn(self, a, b):
        return a / b


class FloorDiv(_Binary):
    def fn(self, a, b):
        return jnp.floor_divide(a, b)


class Mod(_Binary):
    def fn(self, a, b):
        return jnp.mod(a, b)


class Maximum(_Binary):
    def fn(self, a, b):
        return jnp.maximum(a, b)


class Minimum(_Binary):
    def fn(self, a, b):
        return jnp.minimum(a, b)


class Pow(_Binary):
    def fn(self, a, b):
        return jnp.power(a, b)


class Greater(_Binary):
    def fn(self, a, b):
        return a > b


class GreaterEqual(_Binary):
    def fn(self, a, b):
        return a >= b


class Less(_Binary):
    def fn(self, a, b):
        return a < b


class LessEqual(_Binary):
    def fn(self, a, b):
        return a <= b


class Equal(_Binary):
    def fn(self, a, b):
        return a == b


class NotEqual(_Binary):
    def fn(self, a, b):
        return a != b


class LogicalAnd(_Binary):
    def fn(self, a, b):
        return jnp.logical_and(a, b)


class LogicalOr(_Binary):
    def fn(self, a, b):
        return jnp.logical_or(a, b)


class LogicalNot(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.logical_not(input), state


class _Reduce(Operation):
    def __init__(self, axis=None, keep_dims=False, name=None):
        super().__init__(name)
        self.axis = axis
        self.keep_dims = keep_dims

    def fn(self, x):
        raise NotImplementedError

    def apply(self, params, state, input, *, training=False, rng=None):
        return self.fn(input), state


class ReduceSum(_Reduce):
    def fn(self, x):
        return jnp.sum(x, axis=self.axis, keepdims=self.keep_dims)


class ReduceMean(_Reduce):
    def fn(self, x):
        return jnp.mean(x, axis=self.axis, keepdims=self.keep_dims)


class ReduceMax(_Reduce):
    def fn(self, x):
        return jnp.max(x, axis=self.axis, keepdims=self.keep_dims)


class ReduceMin(_Reduce):
    def fn(self, x):
        return jnp.min(x, axis=self.axis, keepdims=self.keep_dims)


class ReduceProd(_Reduce):
    def fn(self, x):
        return jnp.prod(x, axis=self.axis, keepdims=self.keep_dims)


class All(_Reduce):
    def fn(self, x):
        return jnp.all(x, axis=self.axis, keepdims=self.keep_dims)


class Any(_Reduce):
    def fn(self, x):
        return jnp.any(x, axis=self.axis, keepdims=self.keep_dims)


class ArgMax(Operation):
    def __init__(self, axis=-1, name=None):
        super().__init__(name)
        self.axis = axis

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.argmax(input, axis=self.axis), state


class TopK(Operation):
    """-> (values, indices) table (reference: nn/ops/TopK.scala)."""

    def __init__(self, k, name=None):
        super().__init__(name)
        self.k = k

    def apply(self, params, state, input, *, training=False, rng=None):
        vals, idx = jax.lax.top_k(input, self.k)
        return (vals, idx), state


class OneHot(Operation):
    def __init__(self, depth, on_value=1.0, off_value=0.0, name=None):
        super().__init__(name)
        self.depth = depth
        self.on_value, self.off_value = on_value, off_value

    def apply(self, params, state, input, *, training=False, rng=None):
        oh = jax.nn.one_hot(input.astype(jnp.int32), self.depth)
        return oh * (self.on_value - self.off_value) + self.off_value, state


class Cast(Operation):
    def __init__(self, dtype, name=None):
        super().__init__(name)
        self.dtype = dtype

    def apply(self, params, state, input, *, training=False, rng=None):
        return input.astype(self.dtype), state


class Floor(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.floor(input), state


class Ceil(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.ceil(input), state


class Round(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.round(input), state


class Sign(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.sign(input), state


class Select(Operation):
    """(cond, x, y) -> where(cond, x, y) (reference: nn/ops/Select.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        cond, x, y = input
        return jnp.where(cond, x, y), state


class Tile(Operation):
    def __init__(self, multiples, name=None):
        super().__init__(name)
        self.multiples = tuple(multiples)

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.tile(input, self.multiples), state


class Gather(Operation):
    """(params_array, indices) -> gathered (reference: nn/ops/Gather.scala)."""

    def __init__(self, axis=0, name=None):
        super().__init__(name)
        self.axis = axis

    def apply(self, params, state, input, *, training=False, rng=None):
        arr, idx = input
        return jnp.take(arr, idx.astype(jnp.int32), axis=self.axis), state


class Slice(Operation):
    def __init__(self, begin, size, name=None):
        super().__init__(name)
        self.begin, self.size = begin, size

    def apply(self, params, state, input, *, training=False, rng=None):
        # size == -1 takes the remainder of the axis (TF tf.slice convention)
        idx = tuple(slice(b, None if s == -1 else b + s)
                    for b, s in zip(self.begin, self.size))
        return input[idx], state


class Rank(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.asarray(input.ndim), state


class Shape(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.asarray(input.shape), state


class IsNan(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.isnan(input), state


class IsInf(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.isinf(input), state
