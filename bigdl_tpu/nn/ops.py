"""TF-style forward-only operations.

Reference: nn/ops/Operation.scala:32 (Operation = forward-only module whose
backward raises) + the 71-file op zoo under nn/ops/ (arithmetic, comparison,
logical, array, reduction ops) and nn/tf/ stateless ops.

Each op is a thin Module over the matching jnp/lax primitive -- XLA fuses
them; there is no per-op kernel to manage.  All are usable inside Graph /
Sequential like any layer.
"""

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class Operation(Module):
    """Forward-only marker (reference: nn/ops/Operation.scala:32)."""

    def backward(self, input, grad_output):
        raise RuntimeError("Operation does not support backward "
                           "(reference semantics)")


class _Binary(Operation):
    def fn(self, a, b):
        raise NotImplementedError

    def apply(self, params, state, input, *, training=False, rng=None):
        a, b = input
        return self.fn(a, b), state


class Add(_Binary):
    def fn(self, a, b):
        return a + b


class Subtract(_Binary):
    def fn(self, a, b):
        return a - b


class Multiply(_Binary):
    def fn(self, a, b):
        return a * b


class Divide(_Binary):
    def fn(self, a, b):
        return a / b


class FloorDiv(_Binary):
    def fn(self, a, b):
        return jnp.floor_divide(a, b)


class Mod(_Binary):
    def fn(self, a, b):
        return jnp.mod(a, b)


class Maximum(_Binary):
    def fn(self, a, b):
        return jnp.maximum(a, b)


class Minimum(_Binary):
    def fn(self, a, b):
        return jnp.minimum(a, b)


class Pow(_Binary):
    def fn(self, a, b):
        return jnp.power(a, b)


class Greater(_Binary):
    def fn(self, a, b):
        return a > b


class GreaterEqual(_Binary):
    def fn(self, a, b):
        return a >= b


class Less(_Binary):
    def fn(self, a, b):
        return a < b


class LessEqual(_Binary):
    def fn(self, a, b):
        return a <= b


class Equal(_Binary):
    def fn(self, a, b):
        return a == b


class NotEqual(_Binary):
    def fn(self, a, b):
        return a != b


class LogicalAnd(_Binary):
    def fn(self, a, b):
        return jnp.logical_and(a, b)


class LogicalOr(_Binary):
    def fn(self, a, b):
        return jnp.logical_or(a, b)


class LogicalNot(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.logical_not(input), state


class _Reduce(Operation):
    def __init__(self, axis=None, keep_dims=False, name=None):
        super().__init__(name)
        self.axis = axis
        self.keep_dims = keep_dims

    def fn(self, x):
        raise NotImplementedError

    def apply(self, params, state, input, *, training=False, rng=None):
        return self.fn(input), state


class ReduceSum(_Reduce):
    def fn(self, x):
        return jnp.sum(x, axis=self.axis, keepdims=self.keep_dims)


class ReduceMean(_Reduce):
    def fn(self, x):
        return jnp.mean(x, axis=self.axis, keepdims=self.keep_dims)


class ReduceMax(_Reduce):
    def fn(self, x):
        return jnp.max(x, axis=self.axis, keepdims=self.keep_dims)


class ReduceMin(_Reduce):
    def fn(self, x):
        return jnp.min(x, axis=self.axis, keepdims=self.keep_dims)


class ReduceProd(_Reduce):
    def fn(self, x):
        return jnp.prod(x, axis=self.axis, keepdims=self.keep_dims)


class All(_Reduce):
    def fn(self, x):
        return jnp.all(x, axis=self.axis, keepdims=self.keep_dims)


class Any(_Reduce):
    def fn(self, x):
        return jnp.any(x, axis=self.axis, keepdims=self.keep_dims)


class ArgMax(Operation):
    def __init__(self, axis=-1, name=None):
        super().__init__(name)
        self.axis = axis

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.argmax(input, axis=self.axis), state


class TopK(Operation):
    """-> (values, indices) table (reference: nn/ops/TopK.scala)."""

    def __init__(self, k, name=None):
        super().__init__(name)
        self.k = k

    def apply(self, params, state, input, *, training=False, rng=None):
        vals, idx = jax.lax.top_k(input, self.k)
        return (vals, idx), state


class OneHot(Operation):
    def __init__(self, depth, on_value=1.0, off_value=0.0, name=None):
        super().__init__(name)
        self.depth = depth
        self.on_value, self.off_value = on_value, off_value

    def apply(self, params, state, input, *, training=False, rng=None):
        oh = jax.nn.one_hot(input.astype(jnp.int32), self.depth)
        return oh * (self.on_value - self.off_value) + self.off_value, state


class Cast(Operation):
    def __init__(self, dtype, name=None):
        super().__init__(name)
        self.dtype = dtype

    def apply(self, params, state, input, *, training=False, rng=None):
        return input.astype(self.dtype), state


class Floor(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.floor(input), state


class Ceil(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.ceil(input), state


class Round(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.round(input), state


class Sign(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.sign(input), state


class Select(Operation):
    """(cond, x, y) -> where(cond, x, y) (reference: nn/ops/Select.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        cond, x, y = input
        return jnp.where(cond, x, y), state


class Tile(Operation):
    def __init__(self, multiples, name=None):
        super().__init__(name)
        self.multiples = tuple(multiples)

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.tile(input, self.multiples), state


class Gather(Operation):
    """(params_array, indices) -> gathered (reference: nn/ops/Gather.scala)."""

    def __init__(self, axis=0, name=None):
        super().__init__(name)
        self.axis = axis

    def apply(self, params, state, input, *, training=False, rng=None):
        arr, idx = input
        return jnp.take(arr, idx.astype(jnp.int32), axis=self.axis), state


class Slice(Operation):
    def __init__(self, begin, size, name=None):
        super().__init__(name)
        self.begin, self.size = begin, size

    def apply(self, params, state, input, *, training=False, rng=None):
        # size == -1 takes the remainder of the axis (TF tf.slice convention)
        idx = tuple(slice(b, None if s == -1 else b + s)
                    for b, s in zip(self.begin, self.size))
        return input[idx], state


class Rank(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.asarray(input.ndim), state


class Shape(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.asarray(input.shape), state


class IsNan(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.isnan(input), state


class IsInf(Operation):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.isinf(input), state


# --------------------------------------------------------------------------- #
# Math/array op breadth (reference: nn/ops/ remaining files)
# --------------------------------------------------------------------------- #


class ApproximateEqual(_Binary):
    """|a - b| < tolerance (reference: nn/ops/ApproximateEqual.scala)."""

    def __init__(self, tolerance=1e-5, name=None):
        super().__init__(name)
        self.tolerance = tolerance

    def fn(self, a, b):
        return jnp.abs(a - b) < self.tolerance


class BatchMatMul(_Binary):
    """Batched matmul with optional adjoints
    (reference: nn/ops/BatchMatMul.scala)."""

    def __init__(self, adj_x=False, adj_y=False, name=None):
        super().__init__(name)
        self.adj_x, self.adj_y = adj_x, adj_y

    def fn(self, a, b):
        if self.adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if self.adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b


Compare = _Binary      # reference: nn/ops/Compare.scala (abstract base)


class _Elementwise(Operation):
    def fn(self, x):
        raise NotImplementedError

    def apply(self, params, state, input, *, training=False, rng=None):
        return self.fn(input), state


class CrossEntropy(Operation):
    """Softmax cross-entropy with logits, per row
    (reference: nn/ops/CrossEntropy.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        logits, labels = input
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(labels * logp, axis=-1), state


class DepthwiseConv2D(Operation):
    """(x NHWC, filter (kh, kw, cin, mult)) -> depthwise conv
    (reference: nn/ops/DepthwiseConv2D.scala)."""

    def __init__(self, stride_w=1, stride_h=1, pad_w=-1, pad_h=-1,
                 data_format="NHWC", name=None):
        super().__init__(name)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)

    def apply(self, params, state, input, *, training=False, rng=None):
        from jax import lax
        x, w = input
        kh, kw, cin, mult = w.shape
        pad = ("SAME" if self.pad == (-1, -1)
               else [(self.pad[0], self.pad[0]), (self.pad[1], self.pad[1])])
        y = lax.conv_general_dilated(
            x, w.reshape(kh, kw, 1, cin * mult).astype(x.dtype),
            self.stride, pad, feature_group_count=cin,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y, state


class Dilation2D(Operation):
    """Grayscale morphological dilation: max over window of (x + filter)
    (reference: nn/ops/Dilation2D.scala)."""

    def __init__(self, strides, rates, padding="SAME", name=None):
        super().__init__(name)
        self.strides = tuple(strides)
        self.rates = tuple(rates)
        self.padding = padding

    def apply(self, params, state, input, *, training=False, rng=None):
        from jax import lax
        x, w = input                       # x NHWC, w (kh, kw, C)
        kh, kw, c = w.shape
        patches = lax.conv_general_dilated_patches(
            x, (kh, kw), (self.strides[1], self.strides[2]), self.padding,
            rhs_dilation=(self.rates[1], self.rates[2]),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        n, oh, ow, _ = patches.shape
        # patches feature dim is (C, kh, kw) channel-major
        patches = patches.reshape(n, oh, ow, c, kh * kw)
        wt = w.transpose(2, 0, 1).reshape(c, kh * kw).astype(x.dtype)
        return jnp.max(patches + wt[None, None, None], axis=-1), state


class Digamma(_Elementwise):
    def fn(self, x):
        return jax.scipy.special.digamma(x)


class Erf(_Elementwise):
    def fn(self, x):
        return jax.scipy.special.erf(x)


class Erfc(_Elementwise):
    def fn(self, x):
        return jax.scipy.special.erfc(x)


class Expm1(_Elementwise):
    def fn(self, x):
        return jnp.expm1(x)


class Lgamma(_Elementwise):
    def fn(self, x):
        return jax.scipy.special.gammaln(x)


class Rint(_Elementwise):
    def fn(self, x):
        return jnp.rint(x)


class Inv(_Elementwise):
    def fn(self, x):
        return 1.0 / x


class IsFinite(_Elementwise):
    def fn(self, x):
        return jnp.isfinite(x)


class FloorMod(_Binary):
    def fn(self, a, b):
        return jnp.mod(a, b)


class TruncateDiv(_Binary):
    def fn(self, a, b):
        return jnp.trunc(a / b).astype(a.dtype)


class SquaredDifference(_Binary):
    def fn(self, a, b):
        return jnp.square(a - b)


class InTopK(Operation):
    """(predictions (N, C), targets (N,)) -> bool: target within top k
    (reference: nn/ops/InTopK.scala)."""

    def __init__(self, k, name=None):
        super().__init__(name)
        self.k = k

    def apply(self, params, state, input, *, training=False, rng=None):
        pred, tgt = input
        t = tgt.astype(jnp.int32)
        x_t = jnp.take_along_axis(pred, t[:, None], axis=1)[:, 0]
        rank = jnp.sum(pred > x_t[:, None], axis=1)
        return rank < self.k, state


class L2Loss(Operation):
    """sum(x^2) / 2 (reference: nn/ops/L2Loss.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.sum(jnp.square(input)) / 2.0, state


class Pad(Operation):
    """(x, paddings (ndim, 2)) -> padded (reference: nn/ops/Pad ops)."""

    def __init__(self, mode="CONSTANT", constant_value=0.0, name=None):
        super().__init__(name)
        self.mode = mode
        self.constant_value = constant_value

    def apply(self, params, state, input, *, training=False, rng=None):
        x, pads = input
        import numpy as np
        cfg = [tuple(int(v) for v in row) for row in np.asarray(pads)]
        if self.mode == "CONSTANT":
            return jnp.pad(x, cfg, constant_values=self.constant_value), \
                state
        return jnp.pad(x, cfg, mode=self.mode.lower()), state


class Prod(Operation):
    """Product over an axis (reference: nn/ops/Prod.scala)."""

    def __init__(self, axis=0, keep_dims=False, name=None):
        super().__init__(name)
        self.axis = axis
        self.keep_dims = keep_dims

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.prod(input, axis=self.axis,
                        keepdims=self.keep_dims), state


class RangeOps(Operation):
    """(start, limit, delta) -> arange (reference: nn/ops/RangeOps.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        start, limit, delta = [int(v) for v in input]
        return jnp.arange(start, limit, delta), state


class SegmentSum(Operation):
    """(data, segment_ids) -> per-segment sums
    (reference: nn/ops/SegmentSum.scala)."""

    def __init__(self, num_segments=None, name=None):
        super().__init__(name)
        self.num_segments = num_segments

    def apply(self, params, state, input, *, training=False, rng=None):
        data, ids = input
        ids = ids.astype(jnp.int32)
        n = self.num_segments
        if n is None:
            if isinstance(ids, jax.core.Tracer):
                raise ValueError("pass num_segments= for jit use")
            n = int(jnp.max(ids)) + 1
        return jax.ops.segment_sum(data, ids, num_segments=n), state


class TruncatedNormal(Operation):
    """Shape -> truncated-normal sample
    (reference: nn/ops/TruncatedNormal.scala)."""

    def __init__(self, mean=0.0, stddev=1.0, seed=0, name=None):
        super().__init__(name)
        self.mean, self.stddev, self.seed = mean, stddev, seed

    def apply(self, params, state, input, *, training=False, rng=None):
        import numpy as np
        shape = tuple(int(v) for v in np.asarray(input))
        key = rng if rng is not None else jax.random.key(self.seed)
        x = jax.random.truncated_normal(key, -2.0, 2.0, shape)
        return self.mean + self.stddev * x, state


class ModuleToOperation(Operation):
    """Mark any module as forward-only
    (reference: nn/ops/ModuleToOperation.scala)."""

    def __init__(self, module, name=None):
        super().__init__(name)
        self.module = module

    def setup(self, rng, input_spec):
        return self.module.setup(rng, input_spec)

    def apply(self, params, state, input, *, training=False, rng=None):
        return self.module.apply(params, state, input, training=training,
                                 rng=rng)


class TensorOp(Operation):
    """Arbitrary tensor transform from a python fn
    (reference: nn/ops/TensorOp.scala's composable op)."""

    def __init__(self, fn=None, name=None):
        super().__init__(name)
        self._fn = fn or (lambda x: x)

    def apply(self, params, state, input, *, training=False, rng=None):
        return self._fn(input), state


# --------------------------------------------------------------------------- #
# Feature-column ops (reference: nn/ops/CategoricalCol*.scala, CrossCol.scala,
# BucketizedCol.scala, IndicatorCol.scala, MkString.scala, Kv2Tensor.scala).
# String-typed ops run eagerly on host numpy (TPU has no string dtype); the
# numeric outputs they produce feed the device pipeline, mirroring the
# reference where these ops run inside the Spark ingest stage.
# --------------------------------------------------------------------------- #


def _stable_hash(s: str) -> int:
    import hashlib
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "little")


class _HostOp(Operation):
    """String-typed op: runs on host numpy; bypass spec-based build (JAX has
    no string dtype)."""

    def _ensure_built(self, input):
        if not self.is_built():
            self._params, self._state = (), ()


class BucketizedCol(Operation):
    """Numeric -> bucket index by boundaries
    (reference: nn/ops/BucketizedCol.scala)."""

    def __init__(self, boundaries, name=None):
        super().__init__(name)
        self.boundaries = jnp.asarray(boundaries)

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.searchsorted(self.boundaries, input, side="right"), state


class CategoricalColHashBucket(_HostOp):
    """String column -> stable hash bucket id
    (reference: nn/ops/CategoricalColHashBucket.scala)."""

    def __init__(self, hash_bucket_size, strict=True, name=None):
        super().__init__(name)
        self.hash_bucket_size = hash_bucket_size

    def apply(self, params, state, input, *, training=False, rng=None):
        import numpy as np
        vals = np.asarray(
            [[_stable_hash(str(v)) % self.hash_bucket_size]
             for v in np.asarray(input).ravel()], np.int32)
        return jnp.asarray(vals), state


class CategoricalColVocaList(_HostOp):
    """String column -> vocabulary id (OOV -> hash buckets after the vocab
    or default) (reference: nn/ops/CategoricalColVocaList.scala)."""

    def __init__(self, voca_list, strict=True, num_oov_buckets=0,
                 default=-1, name=None):
        super().__init__(name)
        self.vocab = {v: i for i, v in enumerate(voca_list)}
        self.num_oov = num_oov_buckets
        self.default = default
        self.strict = strict

    def apply(self, params, state, input, *, training=False, rng=None):
        import numpy as np
        out = []
        for v in np.asarray(input).ravel():
            s = str(v)
            if s in self.vocab:
                out.append(self.vocab[s])
            elif self.strict:
                raise ValueError(f"token {s!r} not in vocabulary")
            elif self.num_oov > 0:
                out.append(len(self.vocab)
                           + _stable_hash(s) % self.num_oov)
            else:
                out.append(self.default)
        return jnp.asarray(np.asarray(out, np.int32)[:, None]), state


class CrossCol(_HostOp):
    """Cross multiple string columns -> hashed id per row
    (reference: nn/ops/CrossCol.scala)."""

    def __init__(self, hash_bucket_size, name=None):
        super().__init__(name)
        self.hash_bucket_size = hash_bucket_size

    def apply(self, params, state, input, *, training=False, rng=None):
        import numpy as np
        cols = [np.asarray(c).ravel() for c in input]
        out = [[_stable_hash("_X_".join(str(c[i]) for c in cols))
                % self.hash_bucket_size] for i in range(len(cols[0]))]
        return jnp.asarray(np.asarray(out, np.int32)), state


class IndicatorCol(Operation):
    """Categorical ids -> multi-hot indicator vector
    (reference: nn/ops/IndicatorCol.scala)."""

    def __init__(self, feature_num, is_count=True, name=None):
        super().__init__(name)
        self.feature_num = feature_num
        self.is_count = is_count

    def apply(self, params, state, input, *, training=False, rng=None):
        ids = input.astype(jnp.int32)
        onehot = jax.nn.one_hot(ids, self.feature_num)
        multi = jnp.sum(onehot, axis=-2) if onehot.ndim > 2 else onehot
        if not self.is_count:
            multi = (multi > 0).astype(multi.dtype)
        return multi, state


class MkString(_HostOp):
    """Join each row's entries into one string (host-side)
    (reference: nn/ops/MkString.scala)."""

    def __init__(self, str_delimiter=",", name=None):
        super().__init__(name)
        self.delim = str_delimiter

    def apply(self, params, state, input, *, training=False, rng=None):
        import numpy as np
        arr = np.asarray(input)
        out = np.asarray([self.delim.join(str(v) for v in row)
                          for row in arr.reshape(arr.shape[0], -1)])
        return out, state


class Kv2Tensor(_HostOp):
    """Rows of "k:v,k:v" strings -> dense (N, item_num) tensor
    (reference: nn/ops/Kv2Tensor.scala)."""

    def __init__(self, kv_delimiter=",", item_delimiter=":", item_num=0,
                 name=None):
        super().__init__(name)
        self.kv_delimiter = kv_delimiter
        self.item_delimiter = item_delimiter
        self.item_num = item_num

    def apply(self, params, state, input, *, training=False, rng=None):
        import numpy as np
        rows = np.asarray(input).ravel()
        out = np.zeros((len(rows), self.item_num), np.float32)
        for i, row in enumerate(rows):
            for kv in str(row).split(self.kv_delimiter):
                if not kv:
                    continue
                k, v = kv.split(self.item_delimiter)
                out[i, int(k)] = float(v)
        return jnp.asarray(out), state


class Substr(_HostOp):
    """(strings, pos, len) -> substrings (host-side)
    (reference: nn/ops/Substr.scala)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        import numpy as np
        s, pos, length = input
        pos, length = int(pos), int(length)
        return np.asarray([str(v)[pos:pos + length]
                           for v in np.asarray(s).ravel()]), state
