"""Torch-style NN module zoo, TPU-native.

Reference surface: spark/dl/src/main/scala/com/intel/analytics/bigdl/nn/.
"""

from bigdl_tpu.nn.module import Module, Container, Criterion, Identity, child_rng
from bigdl_tpu.nn.containers import (
    Sequential, Concat, ConcatTable, ParallelTable, MapTable,
    CAddTable, CMulTable, CSubTable, CDivTable, CMaxTable, CMinTable,
    JoinTable, SelectTable, FlattenTable, Remat, ScanLayers,
    checkpoint_policy_names, resolve_checkpoint_policy,
    stack_layer_trees, unstack_layer_trees,
)
from bigdl_tpu.nn.graph import Graph, Node, Input
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.quantized import (
    QuantizedLinear, QuantizedSpatialConvolution, model_bytes,
    quantize_model, quantize_params,
)
from bigdl_tpu.nn.conv import (
    SpatialConvolution, SpatialDilatedConvolution, SpatialFullConvolution,
    TemporalConvolution, Conv1D, SpaceToDepthStem, SpatialConvolutionMap,
)
from bigdl_tpu.nn.pooling import (
    SpatialMaxPooling, SpatialAveragePooling,
    GlobalAveragePooling2D, GlobalMaxPooling2D,
)
from bigdl_tpu.nn.normalization import (
    BatchNormalization, SpatialBatchNormalization, LayerNorm, RMSNorm,
    Dropout, SpatialCrossMapLRN, Normalize,
)
from bigdl_tpu.nn.attention import (
    MultiHeadAttention, TransformerBlock, TransformerLM,
    stack_block_params, unstack_block_params,
)
from bigdl_tpu.nn.activations import (
    ReLU, Tanh, Sigmoid, SoftMax, SoftMin, LogSoftMax, HardTanh, Clamp,
    ReLU6, ELU, SoftPlus, SoftSign, LeakyReLU, Threshold, HardSigmoid,
    LogSigmoid, TanhShrink, SoftShrink, HardShrink, Power, Square, Sqrt,
    Abs, Exp, Log, Negative, MulConstant, AddConstant, GELU, SiLU, PReLU,
)
from bigdl_tpu.nn.reshape import (
    Reshape, View, InferReshape, Flatten, Squeeze, Unsqueeze, Transpose,
    Permute, Select, Narrow, Contiguous, Padding, Replicate, Tile,
)
from bigdl_tpu.nn.embedding import LookupTable
from bigdl_tpu.nn.recurrent import (
    Cell, RnnCell, LSTM, GRU, MultiRNNCell, Recurrent, BiRecurrent,
    RecurrentDecoder, TimeDistributed, LSTMPeephole, ConvLSTMPeephole,
    ConvLSTMPeephole3D,
)
from bigdl_tpu.nn.tree import BinaryTreeLSTM
from bigdl_tpu.nn.sparse import (
    SparseTensor, DenseToSparse, LookupTableSparse, SparseLinear,
    sparse_join, sparse_stack, sparse_recommender,
)
from bigdl_tpu.nn.detection import (
    PriorBox, Anchor, Proposal, Nms, NormalizeScale,
    DetectionOutputSSD, DetectionOutputFrcnn,
    bbox_transform_inv, clip_boxes, decode_boxes, nms,
)
from bigdl_tpu.nn.criterion import (
    ClassNLLCriterion, CrossEntropyCriterion,
    FusedSoftmaxCrossEntropyCriterion, MSECriterion, AbsCriterion,
    BCECriterion, BCEWithLogitsCriterion, SmoothL1Criterion,
    DistKLDivCriterion, MarginCriterion, HingeEmbeddingCriterion, L1Cost,
    CosineEmbeddingCriterion, KullbackLeiblerDivergenceCriterion,
    MultiLabelSoftMarginCriterion, MultiCriterion, ParallelCriterion,
    TimeDistributedCriterion,
)
from bigdl_tpu.nn.table_ops import (
    SplitTable, BifurcateSplitTable, NarrowTable, MixtureTable, DotProduct,
    CosineDistance, PairwiseDistance, MM, MV, CrossProduct, Index, Pack,
    CAveTable, Bottle, SparseJoinTable,
)
from bigdl_tpu.nn.simple_layers import (
    Add, CAdd, CMul, Mul, Scale, Bilinear, Cosine, Euclidean, Maxout, Highway,
    LocallyConnected1D, LocallyConnected2D, RReLU, SReLU, BinaryThreshold,
    GaussianDropout, GaussianNoise, GradientReversal, Masking, MaskedSelect,
    L1Penalty, ActivityRegularization, NegativeEntropyPenalty, Echo,
    SpatialDropout1D, SpatialDropout2D, SpatialDropout3D, Sum, Mean, Max,
    Min, Reverse, GaussianSampler,
)
from bigdl_tpu.nn.spatial_extras import (
    SpatialZeroPadding, Cropping2D, Cropping3D, UpSampling1D, UpSampling2D,
    UpSampling3D, ResizeBilinear, SpatialShareConvolution,
    SpatialSeparableConvolution, SpatialWithinChannelLRN,
    SpatialSubtractiveNormalization, SpatialDivisiveNormalization,
    SpatialContrastiveNormalization, RoiPooling, TemporalMaxPooling,
    VolumetricConvolution, VolumetricFullConvolution, VolumetricMaxPooling,
    VolumetricAveragePooling,
)
from bigdl_tpu.nn.criterion_extras import (
    SmoothL1CriterionWithWeights, SoftmaxWithCriterion, PGCriterion,
    CategoricalCrossEntropy, CosineDistanceCriterion,
    CosineProximityCriterion, DiceCoefficientCriterion, DotProductCriterion,
    L1HingeEmbeddingCriterion, MarginRankingCriterion,
    MeanAbsolutePercentageCriterion, MeanSquaredLogarithmicCriterion,
    MultiLabelMarginCriterion, MultiMarginCriterion, PoissonCriterion,
    SoftMarginCriterion, KLDCriterion, GaussianCriterion,
    TransformerCriterion, TimeDistributedMaskCriterion,
    ClassSimplexCriterion,
)

from bigdl_tpu.nn.control_flow import (  # noqa: E402
    DynamicGraph, Merge, Switch, WhileLoop, on_branch,
)
from bigdl_tpu.nn.multibox_loss import MultiBoxCriterion  # noqa: E402

# reference-name aliases (the underlying class covers the same surface)
from bigdl_tpu.nn.recurrent import RnnCell as RNN  # noqa: E402
from bigdl_tpu.nn.graph import Graph as StaticGraph  # noqa: E402
