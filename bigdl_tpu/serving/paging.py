"""Host-side paged KV-cache allocation: block tables, refcounted
prefix sharing, and copy-on-write.

PR 15's decode cache is a contiguous ``slots x max_len`` pool: memory
scales with the WORST-CASE sequence length regardless of what requests
actually use, so concurrency is capped by memory long before compute.
This module virtualizes that cache the way an OS virtualizes RAM: the
device holds one fixed pool of ``num_blocks`` blocks of ``block_size``
positions each (``nn``'s ``init_paged_cache``), and every sequence owns
a host-side BLOCK TABLE -- a list of physical block ids its logical
positions map through.  The compiled steps stay fixed-shape (the
TVM-stance restructuring of PR 7/15, arxiv 1802.04799): block tables
pad to ``max_blocks_per_seq`` with a TRASH block id, so sequences of
any length share one decode executable and join/leave without a
recompile.

On top of the tables, three properties the contiguous pool cannot have:

- **prefix caching** -- a FULL block's content hash (chained over its
  prefix, so equal hashes imply equal token histories) is registered
  after prefill computes it; a later request whose prompt starts with
  the same tokens maps the shared physical block into its own table
  (refcount++) and skips both the block's prefill compute and its
  memory.  Blocks whose refcount drops to zero stay cached in an LRU
  until the pool needs them back, so "millions of users share the
  system prompt" keeps paying off across non-overlapping requests.
- **copy-on-write** -- a write landing in a block someone else also
  maps first detaches: the writer gets a private copy (the device-side
  copy is one fixed-shape jitted op) and the shared original stays
  intact.  The normal flow never triggers it (prefix matches are capped
  below the prompt's last token, so writes target private blocks), but
  the allocator enforces it anyway -- a refcount bug must corrupt
  nobody.
- **typed exhaustion** -- a request the pool cannot hold sheds with
  ``BlockPoolExhausted`` at ADMISSION (its worst-case block need is
  reserved up front), never by silently stealing a neighbour's block
  mid-decode.

All of this is pure host-side bookkeeping (no jax imports): the device
only ever sees index arrays.  See docs/performance.md, "Paged KV
cache".
"""

import collections
import hashlib
import threading


class BlockPoolExhausted(RuntimeError):
    """The block pool cannot hold this sequence: admission is REFUSED
    (typed, so a fleet/engine can shed or retry elsewhere) instead of
    evicting or corrupting a live neighbour's cache."""


def chain_hash(parent, tokens):
    """Content hash of one full block given its prefix's hash: equal
    hashes mean equal (prefix + block) token histories, which is what
    makes a hash hit safe to map into another sequence's table."""
    h = hashlib.blake2b(digest_size=16)
    h.update(parent.encode() if parent else b"\x00")
    h.update(bytes(str(list(int(t) for t in tokens)), "utf-8"))
    return h.hexdigest()


class _Seq:
    __slots__ = ("table", "pending")

    def __init__(self):
        self.table = []          # logical block index -> physical id
        self.pending = {}        # logical block index -> hash to
        #                          register once prefill fills it


class BlockAllocator:
    """Physical block ids are ``[0, num_blocks)``; ``trash`` is the
    extra id ``num_blocks`` the device pool allocates on top -- padding
    rows and inactive decode rows scatter there, it is never owned.

    Thread-safe (one internal lock): the scheduler's dispatcher thread
    allocates/frees while an engine thread may ``flush_cached()`` on a
    weight swap (cached K/V computed under the OLD weights must not
    serve new prompts)."""

    def __init__(self, num_blocks: int, block_size: int,
                 kv_dtype: str = "fp32", bytes_per_block=None):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need num_blocks >= 1 and block_size >= 1, got "
                f"{num_blocks}/{block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        #: what the device pool actually stores per position -- "fp32"
        #: or "int8" (int8 payload + fp32 scales).  Pure metadata here
        #: (no jax in this module): it namespaces the prefix-cache
        #: hashes so quantized and full-precision block contents can
        #: NEVER satisfy each other's matches, and it travels through
        #: stats() so observability cites the real storage format.
        self.kv_dtype = str(kv_dtype)
        #: device bytes behind ONE addressable block across every pool
        #: leaf (int8 payloads AND their scale tensors), measured by the
        #: scheduler from the pool it allocated -- this module has no
        #: jax to measure it itself.  None until a pool owner sets it.
        self.bytes_per_block = None if bytes_per_block is None \
            else int(bytes_per_block)
        self.trash = self.num_blocks
        self._lock = threading.Lock()
        self._free = collections.deque(range(self.num_blocks))
        self._ref = {}                       # physical id -> refcount
        self._hash_of = {}                   # physical id -> content hash
        self._by_hash = {}                   # content hash -> physical id
        #: ref-0 registered blocks, LRU order: reusable as prefix hits
        #: until the pool needs the frames back
        self._cached = collections.OrderedDict()   # hash -> physical id
        self._seqs = {}                      # seq id -> _Seq
        # lifetime counters (telemetry deltas are the caller's job)
        self.prefix_hits = 0                 # blocks served from cache
        self.prefix_hit_tokens = 0
        self.cow_copies = 0
        self.sheds = 0

    # ----- pool accounting --------------------------------------------------- #
    def stats(self):
        with self._lock:
            used = len(self._ref)
            cached = len(self._cached)
            pb = self.bytes_per_block
            return {"blocks_total": self.num_blocks,
                    "blocks_used": used,
                    "blocks_cached": cached,
                    "blocks_free": self.num_blocks - used - cached,
                    "sequences": len(self._seqs),
                    "kv_dtype": self.kv_dtype,
                    # allocator-reported bytes (ROADMAP item 3's rule:
                    # obs_report and the bench cite these, never
                    # hand-computed dtype math); None until the pool
                    # owner measured the device tree
                    "bytes_per_block": pb,
                    "pool_bytes": None if pb is None
                    else pb * self.num_blocks,
                    "prefix_hits": self.prefix_hits,
                    "prefix_hit_tokens": self.prefix_hit_tokens,
                    "cow_copies": self.cow_copies,
                    "sheds": self.sheds}

    def _alloc_block(self):
        """One free physical block, evicting the LRU cached (ref-0)
        block if the free list is dry.  Caller holds the lock."""
        if self._free:
            b = self._free.popleft()
        elif self._cached:
            _h, b = self._cached.popitem(last=False)      # LRU out
            self._hash_of.pop(b, None)
            self._by_hash.pop(_h, None)
        else:
            raise BlockPoolExhausted(
                f"KV block pool exhausted ({self.num_blocks} blocks of "
                f"{self.block_size} positions, all referenced by live "
                f"sequences); raise kv_blocks or shed load")
        self._ref[b] = 1
        return b

    @property
    def _hash_root(self):
        """Root parent for every sequence's hash chain.  fp32 pools
        keep the original ``""`` root; any narrower storage namespaces
        its chains, so an int8 pool's registered blocks can never
        answer an fp32 pool's match even if registries were merged or
        serialized across processes."""
        return "" if self.kv_dtype == "fp32" else f"kv:{self.kv_dtype}"

    # ----- sequence lifecycle ------------------------------------------------ #
    def begin_sequence(self, seq_id, prompt, max_positions: int,
                       kv_dtype=None) -> int:
        """Admit one sequence: match its prompt's full blocks against
        the prefix cache, then RESERVE enough fresh blocks to cover
        ``max_positions`` (prompt + the whole token budget) so decode
        can never hit exhaustion mid-flight.  Returns ``cached_len`` --
        how many leading prompt positions need NO prefill compute.

        Matching is capped below the prompt's LAST token: the final
        position must always be computed (its logits produce the first
        generated token), so a fully-cached prompt still runs a 1+
        token prefill -- which also guarantees prefill writes only ever
        target this sequence's private blocks.

        On ``BlockPoolExhausted`` nothing is retained (the typed shed
        leaves every neighbour's table untouched).

        ``kv_dtype`` (optional) declares the storage format the caller
        expects its prefix hits to hold; a mismatch with this pool's
        format is refused legibly -- an fp32 request must never read
        int8 blocks as if they were full-precision K/V (and vice
        versa)."""
        if kv_dtype is not None and str(kv_dtype) != self.kv_dtype:
            raise ValueError(
                f"KV-dtype mismatch: this block pool stores "
                f"{self.kv_dtype} blocks but sequence {seq_id!r} "
                f"expects {kv_dtype}; prefix-cache contents do not "
                f"convert across storage formats -- serve the request "
                f"from a pool built with kv_cache_dtype={kv_dtype!r}")
        bs = self.block_size
        prompt = [int(t) for t in prompt]
        matchable = max(0, (len(prompt) - 1) // bs)   # full blocks only,
        #                                               last token excluded
        with self._lock:
            if seq_id in self._seqs:
                raise ValueError(f"sequence {seq_id!r} already admitted")
            seq = _Seq()
            parent, matched = self._hash_root, 0
            try:
                for i in range(matchable):
                    h = chain_hash(parent, prompt[i * bs:(i + 1) * bs])
                    b = self._by_hash.get(h)
                    if b is None:
                        # first miss ends the match; remember the hash so
                        # commit_full_blocks can register it post-prefill
                        seq.pending[i] = h
                        parent = h
                        continue
                    if i != matched:
                        break                 # only a LEADING run shares
                    if b in self._cached.values():
                        self._cached.pop(self._hash_of[b], None)
                        self._ref[b] = 1
                    else:
                        self._ref[b] += 1
                    seq.table.append(b)
                    matched += 1
                    parent = h
                # chain hashes for the unmatched full blocks (including
                # any skipped above) -- recompute cleanly from the last
                # MATCHED parent so pending hashes stay a pure chain
                seq.pending = {}
                parent = self._hash_of.get(seq.table[-1], "") \
                    if seq.table else self._hash_root
                for i in range(matched, matchable):
                    h = chain_hash(parent, prompt[i * bs:(i + 1) * bs])
                    seq.pending[i] = h
                    parent = h
                need = -(-int(max_positions) // bs)
                while len(seq.table) < need:
                    seq.table.append(self._alloc_block())
            except BlockPoolExhausted:
                self.sheds += 1
                for b in seq.table:
                    self._release_block(b)
                raise
            self._seqs[seq_id] = seq
            self.prefix_hits += matched
            self.prefix_hit_tokens += matched * bs
            return matched * bs

    def _release_block(self, b):
        """Drop one reference; a ref-0 block returns to the free list,
        unless it is hash-registered -- then it parks in the LRU cache,
        still answering prefix matches until evicted.  Lock held."""
        self._ref[b] -= 1
        if self._ref[b] > 0:
            return
        del self._ref[b]
        h = self._hash_of.get(b)
        if h is not None and self._by_hash.get(h) == b:
            self._cached[h] = b
            self._cached.move_to_end(h)
        else:
            self._hash_of.pop(b, None)
            self._free.append(b)

    def free_sequence(self, seq_id):
        """Release every block the sequence maps (refcount--); shared
        prefix blocks survive for their other readers / the LRU."""
        with self._lock:
            seq = self._seqs.pop(seq_id, None)
            if seq is None:
                return
            for b in seq.table:
                self._release_block(b)

    def commit_full_blocks(self, seq_id, filled_positions: int):
        """Register the content hashes of this sequence's now-FULL
        prefill blocks (``filled_positions`` prompt positions hold real
        K/V) so later admissions can share them.  A hash already
        registered by a concurrent twin keeps ITS block (ours stays
        private -- registration is first-writer-wins, never a content
        swap: two executables' bit-identical-in-theory outputs are not
        worth betting a shared cache on)."""
        bs = self.block_size
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is None:
                return
            for i in sorted(list(seq.pending)):
                if (i + 1) * bs > int(filled_positions):
                    break
                h = seq.pending.pop(i)
                b = seq.table[i]
                if h not in self._by_hash and b not in self._hash_of:
                    self._by_hash[h] = b
                    self._hash_of[b] = h

    def ensure_writable(self, seq_id, position: int):
        """Copy-on-write guard before a K/V write at ``position``:

        - the target block is SHARED (refcount > 1): detach -- allocate
          a private block, remap the table, return ``(src, dst)`` so
          the caller issues the device-side block copy;
        - the target block is this sequence's own but hash-REGISTERED
          (a future request could still map it): unregister instead of
          copying (cheaper, same safety), return ``None``;
        - plain private block: return ``None``.
        """
        bs = self.block_size
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is None:
                raise KeyError(f"unknown sequence {seq_id!r}")
            idx = int(position) // bs
            if idx >= len(seq.table):
                raise IndexError(
                    f"position {position} beyond the reserved table "
                    f"({len(seq.table)} blocks) for sequence {seq_id!r}")
            b = seq.table[idx]
            if self._ref[b] > 1:
                dst = self._alloc_block()
                seq.table[idx] = dst
                self._ref[b] -= 1
                self.cow_copies += 1
                return b, dst
            h = self._hash_of.pop(b, None)
            if h is not None and self._by_hash.get(h) == b:
                del self._by_hash[h]
            return None

    def flush_cached(self):
        """Drop the prefix cache: LRU blocks return to the free list
        and every hash registration is forgotten.  Called on a weight
        swap -- cached K/V computed under the old weights must not
        serve new prompts (live sequences keep their mapped blocks and
        finish on mixed weights, the same documented trade as PR 15's
        mid-flight refresh)."""
        with self._lock:
            for h, b in list(self._cached.items()):
                self._hash_of.pop(b, None)
                self._free.append(b)
            self._cached.clear()
            self._by_hash.clear()
            # live sequences' pending registrations would now chain off
            # stale parents; drop them too
            for seq in self._seqs.values():
                seq.pending.clear()

    def table_row(self, seq_id, max_blocks: int):
        """The sequence's block table padded to ``max_blocks`` with the
        trash id -- the fixed-shape row the compiled steps consume."""
        with self._lock:
            seq = self._seqs.get(seq_id)
            table = list(seq.table) if seq is not None else []
        if len(table) > max_blocks:
            raise ValueError(
                f"sequence {seq_id!r} maps {len(table)} blocks but the "
                f"compiled step holds {max_blocks}")
        return table + [self.trash] * (max_blocks - len(table))
