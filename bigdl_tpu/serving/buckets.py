"""Bucketed shape padding for inference serving.

XLA compiles one executable per input shape, so a serving workload with
ragged request counts (1, 3, 7, ...) would compile an executable per
distinct batch size -- each "new" size a multi-second stall on the
request path.  A bucket ladder closes the shape set: batch sizes round
up to a fixed geometric ladder (1/2/4/.../max by default), padded rows
ride along as zeros and are discarded on return, and the executable
cache holds at most ``len(ladder)`` entries -- all warmable up front
(``ServingEngine.precompile``).

The same mechanism serves sequence models on the TIME axis: a length
ladder pads the stacked batch's axis 1 up to the next rung, so mixed
request lengths hit a closed (batch-bucket x length-bucket) key set.

Within one bucket shape the padded rows cannot perturb the real rows:
eval-mode layers are batch-row-independent (BN uses running stats), and
XLA's reduction blocking is fixed per shape, so a sample's logits are
BIT-EXACT whether it shares the bucket with 1 or ``bucket - 1`` other
requests (pinned by tests/test_serving.py).  Across DIFFERENT bucket
shapes XLA may pick different GEMM blockings, so logits agree only to
float rounding -- see docs/performance.md, "Inference serving".
"""

import bisect
import threading
from typing import Iterable, Iterator, List, Optional

import numpy as np


class BucketLadder:
    """A sorted set of allowed sizes; ``bucket_for(n)`` rounds up.

    ``align`` forces every rung to a multiple (the sharded predict path
    needs batch buckets divisible by the mesh's data-axis size);
    ``growth`` is the geometric step between rungs (2 by default, so
    pad waste is bounded by ~2x on any rung).

    Thread-safe: the engine's dispatcher thread can grow the ladder
    (an over-max length in ``pad_length_axis``) while caller threads
    read it (``predict_at``, ``precompile``), so lookups/mutation take
    a lock and iteration walks a snapshot.
    """

    def __init__(self, max_size: int, min_size: int = 1, growth: int = 2,
                 align: int = 1):
        if min_size < 1 or max_size < min_size:
            raise ValueError(
                f"need 1 <= min_size <= max_size, got {min_size}/{max_size}")
        if growth < 2:
            raise ValueError(f"growth must be >= 2, got {growth}")
        self.align = max(1, int(align))
        self._lock = threading.Lock()
        rungs = set()
        b = int(min_size)
        while b < max_size:
            rungs.add(self._aligned(b))
            b *= growth
        rungs.add(self._aligned(int(max_size)))
        self.rungs: List[int] = sorted(rungs)

    def _aligned(self, n: int) -> int:
        return -(-n // self.align) * self.align

    @property
    def max(self) -> int:
        return self.rungs[-1]

    @property
    def min(self) -> int:
        return self.rungs[0]

    def bucket_for(self, n: int) -> Optional[int]:
        """Smallest rung >= n, or None when n exceeds the ladder."""
        with self._lock:
            i = bisect.bisect_left(self.rungs, n)
            return self.rungs[i] if i < len(self.rungs) else None

    def add(self, n: int) -> int:
        """Insert (the aligned) ``n`` as a rung; returns the rung."""
        n = self._aligned(int(n))
        with self._lock:
            i = bisect.bisect_left(self.rungs, n)
            if i == len(self.rungs) or self.rungs[i] != n:
                self.rungs.insert(i, n)
        return n

    def copy(self) -> "BucketLadder":
        """An independent ladder with the same rungs and alignment.
        Consumers that grow their ladder (``add`` on over-max sizes)
        copy at construction, so a ladder shared between consumers
        never accumulates rungs another consumer added -- each keeps
        its own closed, warmable shape set."""
        new = BucketLadder.__new__(BucketLadder)
        new.align = self.align
        new._lock = threading.Lock()
        with self._lock:
            new.rungs = list(self.rungs)
        return new

    def __iter__(self) -> Iterator[int]:
        with self._lock:
            return iter(list(self.rungs))

    def __len__(self) -> int:
        with self._lock:
            return len(self.rungs)

    def __contains__(self, n) -> bool:
        with self._lock:
            return n in self.rungs

    def __repr__(self):
        return f"BucketLadder({self.rungs}, align={self.align})"


def _pad0(a, target: int):
    a = np.asarray(a)
    if a.shape[0] == target:
        return a
    if a.shape[0] > target:
        raise ValueError(f"batch {a.shape[0]} exceeds bucket {target}")
    out = np.zeros((target,) + a.shape[1:], a.dtype)
    out[: a.shape[0]] = a
    return out


def pad_batch_axis(tree, target: int):
    """Zero-pad every leaf's batch axis (0) up to ``target`` rows.
    Nested tuple/list activities are walked like the MiniBatch pytree."""
    if isinstance(tree, (tuple, list)):
        return type(tree)(pad_batch_axis(e, target) for e in tree)
    return _pad0(tree, target)


def walk_length_leaves(tree, select, leaf_fn, batched: bool = True):
    """THE depth-first walk behind length bucketing: apply ``leaf_fn``
    to every leaf eligible for time-axis bucketing, pass the others
    through.  ``pad_length_axis`` (traffic, batched-rank leaves) and
    ``ServingEngine.precompile`` (warmup, sample-rank leaves,
    ``batched=False``) share this ONE walker so their leaf numbering,
    rank gate, and ``select`` semantics can never drift apart -- the
    ``select`` predicate always sees the leaf at batched rank either
    way."""
    counter = [0]
    min_rank = 2 if batched else 1

    def walk(t):
        if isinstance(t, (tuple, list)):
            return type(t)(walk(e) for e in t)
        a = np.asarray(t)
        i = counter[0]
        counter[0] += 1
        if a.ndim < min_rank:
            return a
        if select is not None and not select(i, a if batched else a[None]):
            return a
        return leaf_fn(a)

    return walk(tree)


def pad_length_axis(tree, ladder: BucketLadder, select=None):
    """Round every rank>=2 leaf's TIME axis (1) up to the length
    ladder (sequence models: tokens beyond the true length are zero
    padding the model must already mask, exactly as in training).

    ``select``: optional ``(leaf_index, leaf) -> bool`` choosing which
    rank>=2 leaves get their axis 1 bucketed (leaves are numbered in
    depth-first order over the whole tree; the leaf is always passed
    at batched rank, here and in ``ServingEngine.precompile``).  Default pads ALL of them,
    which is wrong for a multi-input model with a fixed-width rank>=2
    side input -- its feature dimension would be padded to a rung and
    break the layer expecting it; exclude such leaves here (the
    ``ServingEngine(length_select=)`` knob)."""

    def pad(a):
        target = ladder.bucket_for(a.shape[1])
        if target is None:
            # over-max length: grow the ladder (like the batch path's
            # ladder.add) so the new rung is REUSED -- otherwise every
            # distinct over-max length would compile its own executable
            target = ladder.add(a.shape[1])
        if target == a.shape[1]:
            return a
        out = np.zeros((a.shape[0], target) + a.shape[2:], a.dtype)
        out[:, : a.shape[1]] = a
        return out

    return walk_length_leaves(tree, select, pad, batched=True)


def slice_batch_axis(tree, n: int):
    """Inverse of ``pad_batch_axis``: keep the first ``n`` (real) rows."""
    if isinstance(tree, (tuple, list)):
        return type(tree)(slice_batch_axis(e, n) for e in tree)
    return tree[:n]


def ladder_or_default(ladder: Optional[BucketLadder], max_size: int,
                      align: int = 1) -> BucketLadder:
    """A COPY of the caller-supplied ladder (validated against
    ``align``) or the default geometric one covering [align, max_size].
    The copy keeps the consumer's own rung growth (``add``) from
    leaking into a ladder the caller shares with other consumers."""
    if ladder is None:
        return BucketLadder(max_size, min_size=1, align=align)
    bad = [r for r in ladder if r % align]
    if bad:
        raise ValueError(
            f"ladder rungs {bad} not divisible by the device alignment "
            f"{align} (sharded predict splits the batch axis evenly)")
    return ladder.copy()
