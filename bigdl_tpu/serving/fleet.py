"""Fleet-grade serving: a replicated engine pool with health-aware
routing, per-replica circuit breakers, deadline-budgeted retries,
tail-latency hedging, load shedding and graceful drains.

One ``ServingEngine`` is one failure domain -- "millions of users"
(ROADMAP item 5's remaining gap) need N of them, and the fleet must
survive one dying MID-REQUEST.  The reference got worker-failure
tolerance for free from Spark lineage and task re-execution (BigDL,
arxiv 1804.05839 section 3); this module rebuilds that explicitly for
the serving tier:

- ``ServingFleet`` -- the front end.  ``predict()`` routes through
  least-loaded balancing over the replicas whose lifecycle state is
  ``serving`` AND whose ``CircuitBreaker`` admits traffic (closed ->
  open after ``breaker_failures`` consecutive failures, half-open
  probe after ``breaker_reset_s``, closed again on a probe success).
  A failed attempt retries on another replica under capped exponential
  backoff + jitter (``optim/recovery.capped_backoff`` -- the same
  formula the training supervisor sleeps), all bounded by ONE request
  deadline.  Optional hedging re-issues a still-pending request to a
  second replica after a p99-derived delay (first result wins, the
  loser is cancelled/abandoned) -- the classic tail-latency move.
  Admission is bounded: past ``admission_limit`` in-flight requests the
  fleet sheds with a fast ``FleetOverloadedError`` (the 503) instead of
  collapsing under a backlog it can never drain.  ``generate()`` routes
  autoregressive generation requests through the same admission/
  routing/breaker/retry machinery onto the replicas' decode-slot
  schedulers -- with hedging OFF by design (see the method docstring:
  a multi-token request holds a decode slot for its lifetime, so
  duplication double-books the scarcest serving resource).
- Replicas come in two kinds behind one verb set: ``InProcessReplica``
  (an engine in this process) and ``SubprocessReplica`` (a
  ``serving/worker.py`` process spoken to over the length-prefixed
  socket protocol, so a replica crash is a PROCESS death).  Both
  support the rolling-deploy verbs ``drain``/``undrain``/``stage``/
  ``gate``/``commit``/``release`` that ``serving/deploy.py``'s fleet
  rollout drives replica-by-replica.
- ``FleetSupervisor`` -- restarts dead subprocess replicas (the
  ``RunSupervisor`` pattern: capped, jittered backoff + a max-restarts
  budget); a restarted worker boots from the registry's COMMITTED
  version (``worker.boot_from_registry``) and rejoins bit-for-bit.

Everything is observable: per-replica ``bigdl_fleet_*`` metrics
(state one-hot gauges, retries/hedges/sheds/breaker-transition
counters), durable ``kind: "fleet"`` telemetry events for every
lifecycle/breaker edge, and an obs_report "Fleet" section.  Full story
+ the chaos drill (``tools/serve_fleet.py``): docs/robustness.md,
"Serving fleets".

No jax at module top: a supervisor-side router importing this to watch
subprocess workers needs no accelerator.
"""

import logging
import os
import threading
import time
from collections import deque

from bigdl_tpu.observability.profiling import percentile
from bigdl_tpu.observability.tracing import (HeadSampler, RequestTrace,
                                             TraceContext)
from bigdl_tpu.optim.recovery import capped_backoff

log = logging.getLogger("bigdl_tpu.serving")

#: replica lifecycle states (docs/robustness.md, "Serving fleets"):
#: starting -> serving <-> draining -> drained -> serving, any ->
#: dead -> (supervisor restart) -> serving, terminal: closed
REPLICA_STATES = ("starting", "serving", "draining", "drained", "dead",
                  "closed")

#: circuit breaker states
BREAKER_STATES = ("closed", "open", "half_open")


class FleetOverloadedError(RuntimeError):
    """Load shed: the fleet's bounded admission window is full.  The
    503 of this stack -- deliberately raised FAST (no queueing, no
    retries) so callers back off instead of stacking work the fleet
    can never drain (docs/robustness.md, "Serving fleets")."""


class FleetUnavailableError(RuntimeError):
    """The retry budget / request deadline ran out without any replica
    producing a result (all dead, draining, circuit-open, or every
    attempt failed)."""


class CircuitBreaker:
    """Per-replica failure gate: closed -> open after
    ``failure_threshold`` CONSECUTIVE failures, half-open probe after
    ``reset_timeout_s`` (at most ``half_open_max_probes`` concurrent
    probes), closed again on a probe success, straight back to open on
    a probe failure.  ``clock`` is injectable; ``on_transition(frm,
    to)`` fires OUTSIDE the breaker lock for every state edge (the
    fleet turns these into durable telemetry)."""

    def __init__(self, failure_threshold=3, reset_timeout_s=2.0,
                 half_open_max_probes=1, clock=time.monotonic,
                 on_transition=None):
        if int(failure_threshold) < 1:
            raise ValueError(f"failure_threshold must be >= 1, got "
                             f"{failure_threshold}")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_max_probes = int(half_open_max_probes)
        self.clock = clock
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self.state = "closed"
        self._consecutive = 0
        self._opened_at = None
        self._probes = 0

    def _move(self, to, fired):
        if self.state != to:
            fired.append((self.state, to))
            self.state = to

    def _fire(self, fired):
        if self.on_transition is None:
            return
        for frm, to in fired:
            try:
                self.on_transition(frm, to)
            except Exception:
                log.exception("breaker transition callback failed")

    def acquire(self):
        """May a request be routed here right now?  A True answer in
        the half-open state RESERVES one probe slot -- every acquired
        attempt must end in exactly one ``record_success`` /
        ``record_failure`` / ``record_cancel``."""
        fired = []
        with self._lock:
            if self.state == "open":
                if self._opened_at is not None and \
                        self.clock() - self._opened_at \
                        >= self.reset_timeout_s:
                    self._move("half_open", fired)
                    self._probes = 0
                else:
                    self._fire(fired)
                    return False
            if self.state == "closed":
                ok = True
            else:                             # half_open: bounded probes
                ok = self._probes < self.half_open_max_probes
                if ok:
                    self._probes += 1
        self._fire(fired)
        return ok

    def record_success(self):
        fired = []
        with self._lock:
            self._consecutive = 0
            if self.state == "half_open":
                self._probes = max(0, self._probes - 1)
                self._move("closed", fired)
        self._fire(fired)

    def record_failure(self):
        fired = []
        with self._lock:
            self._consecutive += 1
            if self.state == "half_open":
                self._probes = max(0, self._probes - 1)
                self._move("open", fired)
                self._opened_at = self.clock()
            elif self.state == "closed" and \
                    self._consecutive >= self.failure_threshold:
                self._move("open", fired)
                self._opened_at = self.clock()
        self._fire(fired)

    def record_cancel(self):
        """An abandoned attempt (hedge loser, deadline): releases a
        half-open probe slot without judging the replica either way."""
        with self._lock:
            if self.state == "half_open":
                self._probes = max(0, self._probes - 1)

    def force_open(self):
        """The replica is KNOWN dead (supervisor observed the process
        exit): stop routing immediately, don't wait for three failed
        requests to find out."""
        fired = []
        with self._lock:
            self._move("open", fired)
            self._opened_at = self.clock()
        self._fire(fired)

    def reset(self):
        """A fresh process rejoined: back to closed with a clean
        failure count."""
        fired = []
        with self._lock:
            self._consecutive = 0
            self._probes = 0
            self._opened_at = None
            self._move("closed", fired)
        self._fire(fired)


# --------------------------------------------------------------------------- #
# Replicas: one verb set, two process models.
# --------------------------------------------------------------------------- #


class Replica:
    """Shared replica surface.  Routing: ``submit``/``abandon``/
    ``alive``.  Rolling-deploy verbs: ``drain``/``undrain``/``stage``/
    ``capture``/``gate``/``commit``/``release``/``set_version``.
    ``state``/``inflight``/``served``/``failed`` and the ``breaker``
    are owned by the fleet."""

    kind = "?"

    def __init__(self, rid=None):
        self.rid = rid
        self.state = "starting"
        self.inflight = 0
        self.served = 0
        self.failed = 0
        self.breaker = None            # attached at fleet registration

    def describe(self):
        return {"replica": self.rid, "kind": self.kind,
                "state": self.state, "inflight": self.inflight,
                "served": self.served, "failed": self.failed,
                "breaker": self.breaker.state if self.breaker else None}

    def memory_headroom(self):
        """This replica's ``ServingEngine.memory_headroom()`` capacity
        signal, or None where the replica kind cannot report one (a
        remote worker without the RPC)."""
        return None


class InProcessReplica(Replica):
    """A ``ServingEngine`` in this process -- the cheap replica kind
    (and the fleet's staged-exposure surface: shadow/canary run on the
    first in-process replica)."""

    kind = "in_process"

    def __init__(self, engine, rid=None):
        super().__init__(rid)
        self.engine = engine

    # -- routing -- #
    def submit(self, feature, timeout=None, admit_timeout=None,
               trace=None):
        # admit_timeout bounds QUEUE ADMISSION only; the result wait is
        # the fleet's, bounded by the request deadline (timeout)
        t = admit_timeout if admit_timeout is not None else timeout
        return self.engine.submit(feature, timeout=t, trace=trace)

    def submit_generate(self, req, timeout=None, admit_timeout=None,
                        trace=None):
        # req: {"prompt", "max_new_tokens", "eos_id"} plus optional
        # sampling knobs; returns the engine's streaming GenerateFuture
        # (result() -> token list)
        t = admit_timeout if admit_timeout is not None else timeout
        return self.engine.generate(
            req["prompt"], max_new_tokens=req.get("max_new_tokens", 16),
            eos_id=req.get("eos_id"), timeout=t,
            temperature=req.get("temperature", 0.0),
            top_k=req.get("top_k", 0), top_p=req.get("top_p", 1.0),
            seed=req.get("seed"), trace=trace)

    def abandon(self, fut):
        if hasattr(fut, "_t_submit"):          # a ServeFuture: free its
            self.engine._abandon(fut)          # queue slot too
        else:
            fut.cancel()

    def alive(self):
        return self.engine._running

    def memory_headroom(self):
        return self.engine.memory_headroom()

    # -- deploy verbs -- #
    def drain(self, timeout=None):
        return self.engine.drain(timeout=timeout)

    def undrain(self):
        self.engine.undrain()

    def capture(self):
        return self.engine.capture_staged()

    def stage(self, params=None, mstate=None, src_layout=None, path=None):
        if params is None:
            if path is None:
                raise ValueError("stage needs params= or a snapshot path=")
            from bigdl_tpu.parallel.reshard import read_snapshot_layout
            from bigdl_tpu.serving.engine import ServingEngine

            p = ServingEngine._resolve_snapshot(path)
            src_layout = read_snapshot_layout(p)
            params, mstate = self.engine._load_snapshot_weights(p,
                                                                src_layout)
        return self.engine.stage_weights(params, mstate,
                                         src_layout=src_layout)

    def gate(self, handle, probe_features, probe_bucket=None):
        """Per-replica deploy gate: the staged candidate's outputs on
        the probe batch must be finite (the cheap invariant a damaged
        staging always breaks); no probe configured passes trivially.
        THE one implementation (``worker.gate_staged``) -- the worker's
        ``gate`` op runs the same code, so the two replica kinds can
        never disagree about a candidate."""
        from bigdl_tpu.serving.worker import gate_staged

        return gate_staged(self.engine, handle, probe_features,
                           probe_bucket)

    def commit(self, handle, version=None, digest=None):
        self.engine.commit_staged(handle, version=version, digest=digest)

    def release(self, handle):
        pass                                   # GC owns in-process handles

    def set_version(self, version, digest=None):
        self.engine.set_serving_version(version, digest)

    def close(self):
        self.engine.close()


class SubprocessReplica(Replica):
    """A ``serving/worker.py`` process: requests travel the worker
    wire, so this replica's crash is a PROCESS death the
    ``FleetSupervisor`` observes and repairs.

    ``spawn(attempt) -> (Popen, port)`` must return a STARTED worker
    that is ready to serve (the CLI blocks on the worker's port file);
    it is called again -- with the attempt number -- on every
    supervisor restart.

    ``transport="binary"`` (default) keeps a capped
    ``transport.WirePool`` of persistent multiplexed connections to
    the worker (digest-auth handshake against ``token`` /
    ``BIGDL_RUN_TOKEN``; broken connections evicted and re-dialed
    under ``capped_backoff``); a respawned worker gets a fresh pool on
    its new port.  ``transport="pickle"`` is the PR 14
    connection-per-request escape hatch."""

    kind = "subprocess"

    def __init__(self, spawn, rid=None, host="127.0.0.1",
                 request_timeout_s=30.0, executor=None,
                 transport="binary", token=None, pool_size=2,
                 weight_wire="fp32"):
        super().__init__(rid)
        if transport not in ("binary", "pickle"):
            raise ValueError(f"unknown transport {transport!r}; "
                             f"expected 'binary' or 'pickle'")
        self._spawn = spawn
        self.host = host
        self.request_timeout_s = float(request_timeout_s)
        self._executor = executor              # attached by the fleet
        self.transport = transport
        self.token = token
        self.pool_size = int(pool_size)
        self.weight_wire = weight_wire
        self._wire_sink = None                 # attached by the fleet
        self._stage_wire = {}                  # token -> (bytes, wire)
        self._pool = None
        self.proc = None
        self.port = None

    def start(self, attempt=0):
        self.proc, self.port = self._spawn(attempt)
        self._reset_pool()
        return self

    def respawn(self, attempt):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
        self.proc, self.port = self._spawn(attempt)
        self._reset_pool()                     # new port, new pool
        return self

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    def _reset_pool(self):
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.close()

    def _ensure_pool(self):
        from bigdl_tpu.serving.transport import WirePool

        pool = self._pool
        if pool is None or pool.port != int(self.port):
            self._reset_pool()
            pool = self._pool = WirePool(self.host, self.port,
                                         token=self.token,
                                         size=self.pool_size,
                                         on_wire=self._note_wire)
        return pool

    def _note_wire(self, op, rtt_s, bytes_out, bytes_in):
        sink = self._wire_sink
        if sink is not None:
            try:
                sink(self.rid, op, rtt_s, bytes_out, bytes_in)
            except Exception:
                log.exception("wire stats sink failed")

    def _call(self, op, rpc_timeout=None, **kw):
        rpc = rpc_timeout or self.request_timeout_s
        if self.transport == "binary":
            result, out, inn = self._ensure_pool().request_ex(
                op, rpc_timeout=rpc, **kw)
            return result
        from bigdl_tpu.serving import worker

        t0 = time.perf_counter()
        result = worker.call(self.host, self.port, op, rpc_timeout=rpc,
                             transport="pickle", **kw)
        self._note_wire(op, time.perf_counter() - t0, 0, 0)
        return result

    # -- routing -- #
    def submit(self, feature, timeout=None, admit_timeout=None,
               trace=None):
        # the worker-side predict gets the request's REMAINING deadline
        # (admission and result are one RPC over there -- the fleet's
        # queue-admission bound must NOT cap the whole predict); the
        # socket gets a small margin on top
        if self._executor is None:
            raise RuntimeError("SubprocessReplica needs the fleet's "
                               "executor (register it with a "
                               "ServingFleet first)")
        rpc = self.request_timeout_s if timeout is None \
            else float(timeout) + 5.0
        kw = {"feature": feature, "timeout": timeout}
        if trace is not None:
            # the versioned wire form of the trace context: an OPTIONAL
            # request field a traceless (older) worker never reads
            kw["trace"] = trace.to_wire()
        return self._executor.submit(
            self._call, "predict", rpc_timeout=rpc, **kw)

    def submit_generate(self, req, timeout=None, admit_timeout=None,
                        trace=None):
        # one RPC per whole generation: the worker's engine streams
        # internally, the socket answers with the finished token list
        if self._executor is None:
            raise RuntimeError("SubprocessReplica needs the fleet's "
                               "executor (register it with a "
                               "ServingFleet first)")
        rpc = self.request_timeout_s if timeout is None \
            else float(timeout) + 5.0
        kw = {"prompt": [int(t) for t in req["prompt"]],
              "max_new_tokens": int(req.get("max_new_tokens", 16)),
              "eos_id": req.get("eos_id"), "timeout": timeout}
        # sampling knobs ride the wire only when non-greedy, so greedy
        # traffic against an older worker stays protocol-compatible
        if req.get("temperature", 0.0) > 0.0 or req.get("top_k", 0) > 0 \
                or req.get("top_p", 1.0) < 1.0 or req.get("seed") is not None:
            kw["temperature"] = float(req.get("temperature", 0.0))
            kw["top_k"] = int(req.get("top_k", 0))
            kw["top_p"] = float(req.get("top_p", 1.0))
            kw["seed"] = req.get("seed")
        if trace is not None:
            kw["trace"] = trace.to_wire()
        return self._executor.submit(
            self._call, "generate", rpc_timeout=rpc, **kw)

    def abandon(self, fut):
        fut.cancel()          # a running RPC finishes on the worker and
        #                       is dropped here; accounting rides the
        #                       done-callback either way

    # -- deploy verbs -- #
    def drain(self, timeout=None):
        # mirror engine.drain's contract: timeout=None waits the drain
        # out, so the SOCKET must not cap it at some arbitrary margin
        margin = None if timeout is None else float(timeout) + 5.0
        return self._call("drain", rpc_timeout=margin, timeout=timeout)

    def undrain(self):
        self._call("undrain")

    def capture(self):
        return self._call("capture")

    def stage(self, params=None, mstate=None, src_layout=None, path=None,
              weight_wire=None):
        if path is not None:
            return self._call("stage", path=str(path), rpc_timeout=120.0)
        if params is None:
            raise ValueError("stage needs a snapshot path or an "
                             "in-memory params tree")
        if self.transport != "binary":
            raise ValueError(
                "in-memory params cross the socket only on the binary "
                "transport (transport.quantize_tree_for_wire + raw "
                "tensor frames); the pickle escape hatch stages from "
                "a snapshot PATH")
        if src_layout is not None:
            raise ValueError(
                "stage(params=...) ships weights already in the "
                "serving layout; resharding snapshots cross as a PATH")
        from bigdl_tpu.serving.transport import quantize_tree_for_wire

        ww = weight_wire or self.weight_wire or "fp32"
        tree = quantize_tree_for_wire(params) if ww == "int8" else params
        ms = quantize_tree_for_wire(mstate) \
            if (ww == "int8" and mstate is not None) else mstate
        result, out, _ = self._ensure_pool().request_ex(
            "stage_tree", rpc_timeout=120.0, params=tree, mstate=ms,
            weight_wire=ww)
        # the commit will stamp what ACTUALLY crossed the wire onto
        # the worker's param_refresh audit event
        self._stage_wire[result] = (int(out), ww)
        if len(self._stage_wire) > 16:
            self._stage_wire.pop(next(iter(self._stage_wire)))
        return result

    def gate(self, handle, probe_features=None, probe_bucket=None):
        ok, reason = self._call("gate", token=handle)
        return bool(ok), reason

    def commit(self, handle, version=None, digest=None):
        kw = {}
        staged = self._stage_wire.pop(handle, None)
        if staged is not None:
            kw["wire_bytes"], kw["weight_wire"] = staged
        self._call("commit", token=handle, version=version,
                   digest=digest, **kw)

    def release(self, handle):
        try:
            self._call("release", token=handle, rpc_timeout=5.0)
        except Exception:
            pass                               # worker dead/restarted

    def set_version(self, version, digest=None):
        self._call("set_version", version=version, digest=digest)

    def health(self):
        return self._call("health", rpc_timeout=5.0)

    def probe(self, features=None, bucket=None):
        return self._call("probe", features=features, bucket=bucket)

    def close(self):
        try:
            if self.alive():
                self._call("stop", rpc_timeout=2.0)
        except Exception:
            pass
        self._reset_pool()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(5)
            except Exception:
                self.proc.kill()


# --------------------------------------------------------------------------- #
# The fleet.
# --------------------------------------------------------------------------- #


class ServingFleet:
    """Health-aware front end over N replicas.

    >>> fleet = ServingFleet([InProcessReplica(e) for e in engines],
    ...                      telemetry=tel, metrics=reg, hedge=True)
    >>> y = fleet.predict(feature)           # routed, retried, hedged
    >>> fleet.replica_states()               # who is serving what

    Routing: least-loaded over replicas in lifecycle state ``serving``
    whose breaker admits traffic.  A failed attempt (tick exception,
    dead worker socket, admission timeout) retries on another replica
    -- up to ``retry_limit`` retries under capped exponential backoff
    with ``retry_jitter`` (injectable ``rng``), all inside the one
    request deadline (``timeout=``/``default_timeout_s``).  With
    ``hedge=True`` a request still pending after the p99 of recent
    latencies (floored at ``hedge_min_delay_s``, armed once
    ``hedge_min_samples`` latencies are observed) is re-issued to a
    second replica; first result wins and the loser is abandoned.
    More than ``admission_limit`` concurrent ``predict`` calls shed
    immediately with ``FleetOverloadedError``.

    The fleet is also ``serving/deploy.py``'s rolling-deploy surface
    (``is_fleet``): staging fans out per replica, shadow/canary run on
    the first in-process replica, and the controller walks
    ``drain_replica`` -> ``commit_replica`` -> ``undrain_replica``
    one replica at a time so capacity never reaches zero.

    ``metrics`` (a ``MetricsRegistry``; defaults to the telemetry's
    attached one) receives the request-path counters directly
    (requests/retries/hedges/sheds, per-replica inflight); lifecycle
    and breaker edges are durable ``kind: "fleet"`` telemetry events,
    bridged to ``bigdl_fleet_*`` series by
    ``MetricsRegistry.observe_event``.
    """

    is_fleet = True

    def __init__(self, replicas, telemetry=None, metrics=None,
                 admission_limit=128, retry_limit=3,
                 retry_backoff_s=0.02, retry_backoff_max_s=0.5,
                 retry_jitter=0.25, default_timeout_s=30.0,
                 submit_timeout_s=1.0, hedge=False,
                 hedge_min_delay_s=0.02, hedge_percentile=99.0,
                 hedge_min_samples=20, breaker_failures=3,
                 breaker_reset_s=2.0, probe_features=None,
                 probe_bucket=None, rng=None, clock=time.monotonic,
                 sleep=time.sleep, executor_workers=None,
                 trace_sample=None, wire_flush_every=200):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        if int(admission_limit) < 1:
            raise ValueError(f"admission_limit must be >= 1, got "
                             f"{admission_limit}")
        self.replicas = list(replicas)
        self.telemetry = telemetry
        self.metrics = metrics if metrics is not None \
            else getattr(telemetry, "metrics", None)
        self.admission_limit = int(admission_limit)
        self.retry_limit = int(retry_limit)
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_backoff_max_s = float(retry_backoff_max_s)
        self.retry_jitter = float(retry_jitter)
        self.default_timeout_s = float(default_timeout_s)
        self.submit_timeout_s = float(submit_timeout_s)
        self.hedge = bool(hedge)
        self.hedge_min_delay_s = float(hedge_min_delay_s)
        self.hedge_percentile = float(hedge_percentile)
        self.hedge_min_samples = int(hedge_min_samples)
        self.probe_features = probe_features
        self.probe_bucket = probe_bucket
        self.rng = rng
        self.clock = clock
        self.sleep = sleep
        # distributed request tracing (docs/observability.md, "Request
        # tracing"): head-sampled at the rate given (default: the
        # BIGDL_TRACE_SAMPLE env knob), active only when telemetry can
        # durably record the spans -- without telemetry the request
        # path never mints a context (the no-op-cost contract)
        self._sampler = HeadSampler(trace_sample)
        self._tracing = telemetry is not None
        self._lock = threading.Lock()
        self._inflight_total = 0
        self._closed = False
        self._latencies = deque(maxlen=512)
        self._counters = {"ok": 0, "failed": 0, "shed": 0, "retries": 0,
                          "hedges": 0, "hedge_wins": 0}
        # wire-traffic accounting (binary transport): per-verb deltas
        # accumulate here and flush as durable ``wire`` fleet events
        # every ``wire_flush_every`` RPCs (and at close) -- the
        # metrics bridge and obs_report read THOSE, so live series and
        # post-hoc reports agree and nothing double-counts
        self.wire_flush_every = max(1, int(wire_flush_every))
        self._wire_lock = threading.Lock()
        self._wire_acc = {}
        self._wire_unflushed = 0
        n_sub = sum(1 for r in self.replicas if r.kind == "subprocess")
        self._executor = None
        if n_sub:
            from concurrent.futures import ThreadPoolExecutor

            workers = executor_workers or min(32, 4 * n_sub + 4)
            self._executor = ThreadPoolExecutor(
                max_workers=workers,
                thread_name_prefix="bigdl-fleet-rpc")
        self._init_metrics()
        for i, rep in enumerate(self.replicas):
            if rep.rid is None:
                rep.rid = i
            rep.breaker = CircuitBreaker(
                failure_threshold=breaker_failures,
                reset_timeout_s=breaker_reset_s, clock=clock,
                on_transition=self._breaker_cb(rep))
            if rep.kind == "subprocess":
                rep._executor = self._executor
                rep._wire_sink = self._note_wire
            if len({r.rid for r in self.replicas[:i + 1]}) != i + 1:
                raise ValueError("duplicate replica ids")
        for rep in self.replicas:
            alive = True
            try:
                alive = rep.alive()
            except Exception:
                alive = False
            if alive:
                self._set_state(rep, "serving")
            else:
                self.mark_dead(rep, reason="not alive at registration")

    # ----- observability plumbing ------------------------------------------- #
    def _init_metrics(self):
        m = self.metrics
        if m is None:
            self._m = None
            return
        p = m.prefix
        self._m = {
            "requests": m.counter(
                f"{p}_fleet_requests_total",
                "fleet requests, by outcome", labelnames=("outcome",)),
            "retries": m.counter(f"{p}_fleet_retries_total",
                                 "request attempts retried onto "
                                 "another replica"),
            "hedges": m.counter(f"{p}_fleet_hedges_total",
                                "tail-latency hedges issued"),
            "hedge_wins": m.counter(f"{p}_fleet_hedge_wins_total",
                                    "hedged requests won by the "
                                    "second replica"),
            "sheds": m.counter(f"{p}_fleet_sheds_total",
                               "requests shed at admission (503)"),
            "inflight": m.gauge(f"{p}_fleet_inflight",
                                "in-flight requests, by replica",
                                labelnames=("replica",)),
        }

    def _inc(self, name, **labels):
        if self._m is not None:
            self._m[name].inc(**labels)

    def _emit(self, event, replica=None, **fields):
        if self.telemetry is None:
            return
        try:
            f = {k: v for k, v in fields.items() if v is not None}
            if replica is not None:
                f["replica"] = replica
            self.telemetry.record("fleet", event=event, **f)
        except Exception:
            log.exception("fleet telemetry record failed (%s)", event)

    def _note_wire(self, rid, verb, rtt_s, bytes_out, bytes_in):
        """One worker RPC's wire cost, accumulated per verb.  RTT
        samples are kept only up to the flush cadence so the event's
        histogram contribution is complete, not sampled."""
        with self._wire_lock:
            d = self._wire_acc.setdefault(
                verb, {"calls": 0, "bytes_sent": 0, "bytes_recv": 0,
                       "rtt_s": []})
            d["calls"] += 1
            d["bytes_sent"] += int(bytes_out)
            d["bytes_recv"] += int(bytes_in)
            if len(d["rtt_s"]) < 2 * self.wire_flush_every:
                d["rtt_s"].append(round(float(rtt_s), 6))
            self._wire_unflushed += 1
            if self._wire_unflushed < self.wire_flush_every:
                return
            acc, self._wire_acc = self._wire_acc, {}
            self._wire_unflushed = 0
        self._flush_wire(acc)

    def _flush_wire(self, acc):
        for verb, d in acc.items():
            self._emit("wire", verb=verb, calls=d["calls"],
                       bytes_sent=d["bytes_sent"],
                       bytes_recv=d["bytes_recv"], rtt_s=d["rtt_s"])

    def wire_stats(self):
        """The UNFLUSHED per-verb wire aggregate (flushed deltas are
        in the durable ``wire`` events)."""
        with self._wire_lock:
            return {v: dict(d, rtt_s=list(d["rtt_s"]))
                    for v, d in self._wire_acc.items()}

    def _breaker_cb(self, rep):
        def cb(frm, to):
            self._emit("breaker", replica=rep.rid,
                       **{"from": frm, "to": to})
        return cb

    def _set_state(self, rep, state, reason=None):
        if state not in REPLICA_STATES:
            raise ValueError(f"unknown replica state {state!r}")
        prev = rep.state
        if prev == state:
            return
        rep.state = state
        self._emit("state", replica=rep.rid, state=state, prev=prev,
                   reason=None if reason is None else str(reason)[:300])

    # ----- request path ------------------------------------------------------ #
    def predict(self, feature, timeout=None):
        """One request through the fleet: admission -> route -> (retry/
        hedge) -> result.  Raises ``FleetOverloadedError`` on shed,
        ``FleetUnavailableError`` when the deadline/retry budget runs
        out without a result."""
        return self._request(feature, timeout, op="submit",
                             hedge_ok=True)

    def generate(self, prompt, max_new_tokens=16, eos_id=None,
                 timeout=None, temperature=0.0, top_k=0, top_p=1.0,
                 seed=None):
        """One GENERATION request through the fleet: same admission
        window, least-loaded routing, breakers and deadline-budgeted
        retries as ``predict`` (a failed/dead replica's request re-runs
        from the prompt on a sibling -- greedy decoding makes the retry
        idempotent), returning the generated token-id list.

        Sampling (``temperature`` / ``top_k`` / ``top_p`` / ``seed``)
        rides the request: when the caller samples without pinning a
        seed, the FLEET mints one here -- before routing -- so every
        retry of this request replays the exact same token stream on
        whichever replica it lands on (the scheduler's per-position
        fold-in RNG makes the draw a pure function of (seed, position),
        which is what keeps sampled retries idempotent too).

        Hedging is DISABLED for generation even when the fleet hedges
        predicts, deliberately: a multi-token request occupies a decode
        slot for its entire lifetime, so a hedge would double-book the
        fleet's scarcest serving resource -- two replicas each burning
        a slot for hundreds of ticks -- to shave one request's tail,
        and the loser's work cannot be abandoned mid-stream the way a
        single pending predict RPC can (the worker decodes the whole
        sequence regardless).  Tail tolerance for generation comes from
        retry-on-failure plus more slots, not duplication."""
        req = {"prompt": prompt, "max_new_tokens": int(max_new_tokens),
               "eos_id": eos_id}
        if temperature > 0.0 or top_k > 0 or top_p < 1.0 \
                or seed is not None:
            if seed is None and temperature > 0.0:
                seed = int.from_bytes(os.urandom(4), "little") & 0x7fffffff
            req.update(temperature=float(temperature), top_k=int(top_k),
                       top_p=float(top_p), seed=seed)
        return self._request(req, timeout, op="submit_generate",
                             hedge_ok=False)

    def _request(self, feature, timeout, op, hedge_ok):
        if self._closed:
            raise RuntimeError("ServingFleet is closed")
        budget = self.default_timeout_s if timeout is None \
            else float(timeout)
        deadline = self.clock() + budget
        # trace root: minted HERE, before admission, so even a shed
        # request has an identity.  The keep/drop decision is deferred
        # to completion (RequestTrace): errors/sheds/p99 tails override
        # an unsampled head decision and always reach traces.jsonl.
        rt, t_req = None, 0.0
        if self._tracing:
            rt = RequestTrace(
                TraceContext.mint(sampled=self._sampler.sample()))
            t_req = time.time()
        with self._lock:
            if self._inflight_total >= self.admission_limit:
                self._counters["shed"] += 1
                shed = True
            else:
                self._inflight_total += 1
                shed = False
        if shed:
            self._inc("requests", outcome="shed")
            self._inc("sheds")
            if rt is not None:
                rt.add("fleet_request", rt.ctx, t_req, 0.0,
                       status="shed", op=op)
                rt.flush(self.telemetry)
            raise FleetOverloadedError(
                f"fleet admission window full ({self.admission_limit} "
                f"requests in flight); shedding instead of queueing -- "
                f"retry with backoff")
        try:
            y = self._serve(feature, deadline, op=op, hedge_ok=hedge_ok,
                            rt=rt)
        except Exception as e:
            with self._lock:
                self._counters["failed"] += 1
            self._inc("requests", outcome="failed")
            if rt is not None:
                rt.add("fleet_request", rt.ctx, t_req,
                       time.time() - t_req,
                       status="error:" + type(e).__name__, op=op)
                rt.flush(self.telemetry)
            raise
        else:
            with self._lock:
                self._counters["ok"] += 1
            self._inc("requests", outcome="ok")
            if rt is not None:
                dur = time.time() - t_req
                rt.add("fleet_request", rt.ctx, t_req, dur,
                       status="ok", op=op)
                if op == "submit" and self._tail_latency(dur):
                    rt.force()      # p99-tail override: keep the slow ones
                rt.flush(self.telemetry)
            return y
        finally:
            with self._lock:
                self._inflight_total -= 1

    def _count(self, name):
        with self._lock:
            self._counters[name] += 1
        self._inc(name if name != "hedge_wins" else "hedge_wins")

    def _pick(self, exclude=(), prefer_not=()):
        """Least-loaded routing over admittable replicas: lifecycle
        ``serving``, breaker admits (an ``acquire`` that returns True
        reserves the attempt -- every pick ends in exactly one breaker
        record call via ``_finish``)."""
        with self._lock:
            cands = [r for r in self.replicas
                     if r.state == "serving" and r.rid not in exclude]
            cands.sort(key=lambda r: (r.rid in prefer_not, r.inflight,
                                      r.rid))
        for r in cands:
            if r.breaker.acquire():
                return r
        return None

    @staticmethod
    def _drain_refusal(err):
        """An ``EngineDraining`` refusal is a mid-deploy 'pick another
        replica' signal, NOT a serving failure -- it must not count
        toward the breaker's consecutive-failure streak.  The worker
        protocol carries the exception type across the socket
        (``ReplicaCallError.error_type``)."""
        from bigdl_tpu.serving.engine import EngineDraining

        return isinstance(err, EngineDraining) or \
            getattr(err, "error_type", None) == "EngineDraining"

    def _launch(self, rep, feature, remaining, op="submit",
                trace=None):
        with self._lock:
            rep.inflight += 1
        if self._m is not None:
            self._m["inflight"].set(rep.inflight, replica=str(rep.rid))
        t0 = self.clock()
        # the context crosses into the replica only when the head
        # sampler kept it: a late-forced (error-path) trace keeps its
        # fleet spans but does no remote work -- and the kwarg is
        # omitted entirely otherwise, so replica implementations
        # predating the trace parameter keep working untraced
        kw = {}
        if trace is not None and trace.sampled:
            kw["trace"] = trace
        try:
            fut = getattr(rep, op)(
                feature, timeout=remaining,
                admit_timeout=min(remaining, self.submit_timeout_s),
                **kw)
        except Exception as e:
            with self._lock:
                rep.inflight = max(0, rep.inflight - 1)
            if self._drain_refusal(e):
                rep.breaker.record_cancel()
            else:
                rep.failed += 1
                rep.breaker.record_failure()
            raise
        fut.add_done_callback(
            lambda f, _r=rep, _t=t0, _op=op: self._finish(_r, f, _t, _op))
        return fut

    def _finish(self, rep, fut, t0, op="submit"):
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)
        if self._m is not None:
            try:
                self._m["inflight"].set(rep.inflight,
                                        replica=str(rep.rid))
            except Exception:
                pass
        if fut.cancelled():
            rep.breaker.record_cancel()
            return
        err = fut.exception()
        if err is None:
            rep.served += 1
            rep.breaker.record_success()
            if op == "submit":
                # ONLY predict latencies calibrate the hedge reservoir:
                # a multi-token generation is seconds where a predict is
                # milliseconds, and one mixed p99 would push the predict
                # hedge trigger past every request deadline
                self._note_latency(self.clock() - t0)
        elif self._drain_refusal(err):
            rep.breaker.record_cancel()
        else:
            rep.failed += 1
            rep.breaker.record_failure()

    def _note_latency(self, s):
        with self._lock:
            self._latencies.append(float(s))

    def _tail_latency(self, s):
        """True when this request's latency lands beyond the p99 of
        the latency reservoir -- the always-sample override that keeps
        the slow tail reconstructable even at a 1% head rate."""
        with self._lock:
            if len(self._latencies) < self.hedge_min_samples:
                return False
            samples = sorted(self._latencies)
        return s > percentile(samples, 99.0)

    def _hedge_delay(self):
        """The p99-derived hedge trigger, or None while hedging is off
        / uncalibrated (fewer than ``hedge_min_samples`` latencies)."""
        if not self.hedge:
            return None
        with self._lock:
            if len(self._latencies) < self.hedge_min_samples:
                return None
            samples = sorted(self._latencies)
        return max(self.hedge_min_delay_s,
                   percentile(samples, self.hedge_percentile))

    def _backoff_sleep(self, attempt, deadline):
        b = capped_backoff(attempt - 1, self.retry_backoff_s,
                           self.retry_backoff_max_s,
                           jitter=self.retry_jitter, rng=self.rng)
        b = min(b, max(0.0, deadline - self.clock()))
        if b > 0:
            self.sleep(b)

    def _serve(self, feature, deadline, op="submit", hedge_ok=True,
               rt=None):
        from concurrent.futures import FIRST_COMPLETED
        from concurrent.futures import wait as future_wait

        attempts = 0                  # failed rounds so far
        failed_rids = []
        last_err = None
        # per-attempt trace spans: fut -> (child ctx, wall start,
        # replica id, was-a-hedge).  Statuses are recorded HERE, on the
        # request thread at the moment each outcome is decided --
        # recording in the done-callback would race the final flush
        # (an abandoned in-process future resolves on a later tick,
        # possibly after the winner already returned).
        spans = {}

        def note(f, status):
            if rt is None or f not in spans:
                return
            ctx, ts, rid, is_hedge = spans.pop(f)
            kw = {"replica": rid, "op": op}
            if is_hedge:
                kw["hedge"] = True
            rt.add("fleet_attempt", ctx, ts, time.time() - ts,
                   status=status, **kw)

        def give_up(msg):
            raise FleetUnavailableError(
                f"{msg} after {attempts} failed attempt(s)"
                + (f" (replicas tried: {sorted(set(failed_rids))})"
                   if failed_rids else "")
                + (f": {last_err}" if last_err is not None else "")) \
                from last_err

        while True:
            remaining = deadline - self.clock()
            if remaining <= 0:
                give_up("request deadline exhausted")
            rep = self._pick(prefer_not=failed_rids)
            if rep is None:
                last_err = last_err or FleetUnavailableError(
                    "no admittable replica (dead, draining, or "
                    "circuit-open)")
                attempts += 1
                if attempts > self.retry_limit:
                    give_up("no admittable replica")
                self._count("retries")
                self._backoff_sleep(attempts, deadline)
                continue
            futs = {}
            actx = rt.ctx.child() if rt is not None else None
            try:
                fut = self._launch(rep, feature, remaining, op=op,
                                   trace=actx)
                futs[fut] = rep
                if rt is not None:
                    spans[fut] = (actx, time.time(), rep.rid, False)
            except Exception as e:
                last_err = e
                failed_rids.append(rep.rid)
                if rt is not None:
                    now = time.time()
                    rt.add("fleet_attempt", actx, now, 0.0,
                           status="error:" + type(e).__name__,
                           replica=rep.rid, op=op)
                attempts += 1
                if attempts > self.retry_limit:
                    give_up("request failed")
                self._count("retries")
                self._backoff_sleep(attempts, deadline)
                continue
            hedged = False
            primary = fut
            # ONE percentile derivation per attempt, not one per wait
            # iteration (sorting the reservoir on the hot path);
            # hedge_ok=False (generation) never arms the hedge timer
            delay = self._hedge_delay() if hedge_ok else None
            while futs:
                remaining = deadline - self.clock()
                if remaining <= 0:
                    for f, r in futs.items():
                        r.abandon(f)
                        note(f, "error:deadline")
                    give_up("request deadline exhausted mid-attempt")
                wait_s, hedge_due = remaining, False
                if not hedged and delay is not None and delay < wait_s:
                    wait_s, hedge_due = delay, True
                done, _ = future_wait(set(futs), timeout=wait_s,
                                      return_when=FIRST_COMPLETED)
                winner = None
                for f in done:
                    if not f.cancelled() and f.exception() is None:
                        winner = f
                        break
                if winner is not None:
                    for f, r in futs.items():
                        if f is not winner:
                            r.abandon(f)
                            # the only way two futures race is a hedge:
                            # the still-pending half of the pair is THE
                            # one hedge_lost span of the request
                            note(f, "hedge_lost")
                    # a hedge "win" means the second replica beat a
                    # primary that was STILL pending -- a hedge that
                    # merely outlived an already-failed primary is not
                    # a tail-latency win
                    if winner is not primary and primary in futs:
                        self._count("hedge_wins")
                    note(winner, "ok")
                    return winner.result()
                for f in done:             # failures/cancellations
                    r = futs.pop(f)
                    if not f.cancelled():
                        last_err = f.exception()
                        note(f, "error:" + type(last_err).__name__)
                    else:
                        note(f, "cancelled")
                    failed_rids.append(r.rid)
                if not futs:
                    break                  # whole round failed -> retry
                if not done and hedge_due:
                    hedged = True          # at most one hedge/request
                    second = self._pick(
                        exclude=[r.rid for r in futs.values()],
                        prefer_not=failed_rids)
                    if second is not None:
                        actx2 = rt.ctx.child() if rt is not None \
                            else None
                        try:
                            f2 = self._launch(second, feature,
                                              remaining, op=op,
                                              trace=actx2)
                            futs[f2] = second
                            if rt is not None:
                                spans[f2] = (actx2, time.time(),
                                             second.rid, True)
                            self._count("hedges")
                        except Exception as e:
                            last_err = e
                            failed_rids.append(second.rid)
                            if rt is not None:
                                rt.add("fleet_attempt", actx2,
                                       time.time(), 0.0,
                                       status="error:"
                                       + type(e).__name__,
                                       replica=second.rid, op=op,
                                       hedge=True)
            attempts += 1
            if attempts > self.retry_limit:
                give_up("request failed")
            self._count("retries")
            self._backoff_sleep(attempts, deadline)

    # ----- status surface ---------------------------------------------------- #
    def replica_ids(self, live_only=False):
        return [r.rid for r in self.replicas
                if not live_only or r.state not in ("dead", "closed")]

    def _by_id(self, rid):
        for r in self.replicas:
            if r.rid == rid:
                return r
        raise KeyError(f"unknown replica {rid}")

    def replica_states(self):
        return {r.rid: r.describe() for r in self.replicas}

    def counters(self):
        with self._lock:
            return dict(self._counters)

    def memory_headroom(self):
        """The fleet-wide capacity signal (future autoscaler input):
        per-replica ``memory_headroom()`` plus aggregates -- the
        TIGHTEST device headroom across replicas (the replica that
        OOMs first bounds the fleet) and the SUMMED free KV blocks
        (shed-resistant admission capacity).  Replicas that cannot
        report (remote workers, dead processes) are skipped."""
        per = {}
        for r in self.replicas:
            if r.state in ("dead", "closed"):
                continue
            try:
                h = r.memory_headroom()
            except Exception:
                h = None
            if h is not None:
                per[r.rid] = h
        agg = {"replicas": per}
        headrooms = [h["headroom_bytes"] for h in per.values()
                     if h.get("headroom_bytes") is not None]
        if headrooms:
            agg["min_headroom_bytes"] = min(headrooms)
        fracs = [h["headroom_fraction"] for h in per.values()
                 if h.get("headroom_fraction") is not None]
        if fracs:
            agg["min_headroom_fraction"] = min(fracs)
        frees = [h["kv_blocks_free"] for h in per.values()
                 if h.get("kv_blocks_free") is not None]
        if frees:
            agg["kv_blocks_free"] = sum(frees)
            agg["kv_blocks_total"] = sum(
                h.get("kv_blocks_total", 0) for h in per.values())
        return agg

    # ----- lifecycle transitions (supervisor + deploys) ---------------------- #
    def mark_dead(self, rep, reason=None):
        """The replica's process is gone: stop routing NOW (breaker
        forced open, lifecycle ``dead``) -- in-flight attempts fail and
        retry elsewhere."""
        self._set_state(rep, "dead", reason=reason)
        rep.breaker.force_open()

    def mark_joined(self, rep):
        """A restarted replica is healthy again: breaker reset closed,
        lifecycle back to ``serving``."""
        rep.breaker.reset()
        self._set_state(rep, "serving", reason="rejoined")

    def drain_replica(self, rid, timeout=None):
        """Stop routing to one replica and wait for its accepted work
        to finish (the rolling deploy's first step).  Routing skips it
        the moment the state leaves ``serving``; a request that raced
        in anyway either completes (drain waits) or raises
        ``EngineDraining`` and retries on a sibling."""
        rep = self._by_id(rid)
        self._set_state(rep, "draining")
        try:
            ok = bool(rep.drain(timeout=timeout))
        except Exception:
            # a failed drain call must not strand the replica in
            # "draining" (unroutable forever); the caller sees the
            # error, routing sees a serving replica again
            self._set_state(rep, "serving",
                            reason="drain call failed")
            raise
        if ok:
            self._set_state(rep, "drained")
        return ok

    def undrain_replica(self, rid):
        rep = self._by_id(rid)
        rep.undrain()
        self._set_state(rep, "serving")

    def commit_replica(self, rid, handle, version=None, digest=None):
        self._by_id(rid).commit(handle, version=version, digest=digest)

    def gate_replica(self, rid, handle):
        """(ok, reason) of the per-replica deploy gate on an
        already-staged fleet handle."""
        rep = self._by_id(rid)
        h = (handle.get("per_replica") or {}).get(rid)
        if h is None:
            return False, "no staged candidate for this replica"
        try:
            return rep.gate(h, self.probe_features, self.probe_bucket)
        except Exception as e:
            return False, f"gate probe failed: {e}"

    # ----- deploy facade (serving/deploy.py drives these) -------------------- #
    def _exposure_rep(self):
        for rep in self.replicas:
            if rep.kind == "in_process":
                return rep
        raise RuntimeError(
            "this fleet has no in-process replica: shadow/canary "
            "staged exposure needs one (tools/serve_fleet.py runs the "
            "driver's own engine as replica 0)")

    @property
    def exposure(self):
        """The staged-exposure engine (first in-process replica):
        shadow mirrors and canary routing run here."""
        return self._exposure_rep().engine

    @property
    def ladder(self):
        return self.exposure.ladder

    def predict_at(self, feature, bucket):
        return self.exposure.predict_at(feature, bucket)

    def _load_snapshot_weights(self, p, src_layout):
        return self.exposure._load_snapshot_weights(p, src_layout)

    def stage_weights(self, params=None, mstate=None, src_layout=None,
                      path=None):
        """Fan a candidate out: stage on every live replica (nothing
        committed anywhere).  In-process replicas stage the in-memory
        tree; subprocess replicas load+stage ``path`` in their own
        process, or -- on the binary transport -- take the in-memory
        tree over the wire (``weight_wire="int8"`` replicas ship the
        blockwise-int8 payload+scales and dequantize worker-side).
        Returns the fleet handle ``{"per_replica": {rid: handle}}``
        the rolling cutover walks."""
        per = {}
        model_bytes = quantized = None
        for rep in self.replicas:
            if rep.state in ("dead", "closed"):
                continue               # it will boot from the registry
            try:
                h = rep.stage(params=params, mstate=mstate,
                              src_layout=src_layout, path=path)
            except Exception as e:
                # a replica that DIED under the stage is skipped like
                # everywhere else in the roll -- one crash must not
                # reject a healthy candidate fleet-wide (and put it on
                # the reject cooldown); a replica that is alive and
                # refused is judging the CANDIDATE, and that propagates
                alive = True
                try:
                    alive = rep.alive()
                except Exception:
                    alive = False
                if not alive:
                    self.mark_dead(rep, reason=f"died mid-stage: {e}")
                    continue
                raise
            per[rep.rid] = h
            if isinstance(h, dict):
                model_bytes = h.get("model_bytes", model_bytes)
                quantized = h.get("quantized", quantized)
        if not per:
            raise RuntimeError("no live replica to stage on")
        return {"fleet": True, "per_replica": per,
                "model_bytes": model_bytes, "quantized": quantized}

    def capture_staged(self):
        """Every live replica's CURRENT weights as a fleet handle (the
        rolling rollback target).  A replica that dies under the
        capture is marked dead and skipped -- one crash must not abort
        the rollout that would have skipped it anyway."""
        per = {}
        for rep in self.replicas:
            if rep.state in ("dead", "closed"):
                continue
            try:
                per[rep.rid] = rep.capture()
            except Exception as e:
                alive = True
                try:
                    alive = rep.alive()
                except Exception:
                    alive = False
                if not alive:
                    self.mark_dead(rep, reason=f"died mid-capture: {e}")
                else:
                    log.exception("capture on replica %s failed",
                                  rep.rid)
        return {"fleet": True, "per_replica": per}

    def commit_staged(self, handle, version=None, digest=None):
        """Commit an already-staged fleet handle on every live replica
        -- the NON-rolling spelling (boot-time resume, whole-fleet
        rollback): each per-replica commit is the atomic pointer swap,
        no drain needed.  A replica whose commit fails (worker
        restarted since staging, token evicted) is logged and SKIPPED
        so one bad replica cannot leave the rest of the fleet on the
        wrong version mid-rollback; the call only raises when NO
        replica committed."""
        per = handle.get("per_replica") or {}
        committed, first_err = [], None
        for rid in sorted(per):
            rep = self._by_id(rid)
            if rep.state in ("dead", "closed"):
                continue
            try:
                rep.commit(per[rid], version=version, digest=digest)
                committed.append(rid)
            except Exception as e:
                first_err = first_err or e
                log.exception("commit_staged failed on replica %s "
                              "(the supervisor / next deploy must "
                              "reconcile it)", rid)
        if first_err is not None and not committed:
            raise RuntimeError(
                f"commit_staged failed on every replica: {first_err}") \
                from first_err
        return self

    def release_staged(self, handle):
        """Release a rejected candidate's staged buffers fleet-wide
        (subprocess workers drop their tokens; in-process handles are
        garbage)."""
        per = (handle or {}).get("per_replica") or {}
        for rid, h in per.items():
            try:
                self._by_id(rid).release(h)
            except Exception:
                pass

    def eval_staged(self, handle, x, tick=0):
        rep = self._exposure_rep()
        return rep.engine.eval_staged(handle["per_replica"][rep.rid], x,
                                      tick=tick)

    def set_canary(self, handle, fraction=0.1, version=None):
        rep = self._exposure_rep()
        h = None if handle is None else handle["per_replica"][rep.rid]
        return rep.engine.set_canary(h, fraction, version=version)

    def canary_stats(self):
        return self.exposure.canary_stats()

    def set_shadow(self, fn, fraction=1.0):
        return self.exposure.set_shadow(fn, fraction)

    def set_serving_version(self, version, digest=None):
        for rep in self.replicas:
            if rep.state in ("dead", "closed"):
                continue
            try:
                rep.set_version(version, digest)
            except Exception:
                log.exception("set_serving_version failed on replica "
                              "%s", rep.rid)
        return self

    # ----- lifecycle --------------------------------------------------------- #
    def close(self, timeout=10.0):
        """Stop the fleet: emit the final durable stats event, close
        every replica (subprocess workers get a polite stop, then
        terminate), shut the RPC executor down.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            counters = dict(self._counters)
        with self._wire_lock:
            acc, self._wire_acc = self._wire_acc, {}
            self._wire_unflushed = 0
        self._flush_wire(acc)                  # the remainder delta
        self._emit("stats", **counters)
        for rep in self.replicas:
            try:
                rep.close()
            except Exception:
                log.exception("closing replica %s failed", rep.rid)
            self._set_state(rep, "closed")
        if self._executor is not None:
            self._executor.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# --------------------------------------------------------------------------- #
# The supervisor: dead subprocess replicas come back.
# --------------------------------------------------------------------------- #


class FleetSupervisor:
    """Watch subprocess replicas; restart the dead under capped,
    jittered backoff (the ``optim/recovery.RunSupervisor`` pattern,
    per-replica).  A restarted worker boots from the registry's
    COMMITTED version (its ``--registry`` flag ->
    ``worker.boot_from_registry``), so it rejoins serving exactly what
    the fleet serves -- never a half-promoted candidate.

    ``check()`` is one supervision cycle (tests drive it with an
    injected clock); ``start()`` runs it on a poll thread.  Per-replica
    budget: after ``max_restarts`` failed resurrections the replica is
    marked ``closed`` and the fleet keeps serving on the survivors --
    a permanently crashing worker must not consume the supervisor
    forever."""

    def __init__(self, fleet, max_restarts=5, backoff_base_s=0.5,
                 backoff_max_s=30.0, jitter=0.25, rng=None,
                 poll_interval_s=0.2, clock=time.monotonic):
        self.fleet = fleet
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self.rng = rng
        self.poll_interval_s = float(poll_interval_s)
        self.clock = clock
        self.restarts = {}             # rid -> attempts so far
        self.events = []
        self._due = {}                 # rid -> next-restart clock time
        self._backoff = {}             # rid -> last scheduled backoff
        self._stop = threading.Event()
        self._thread = None

    def backoff_s(self, restarts):
        return capped_backoff(restarts, self.backoff_base_s,
                              self.backoff_max_s, jitter=self.jitter,
                              rng=self.rng)

    def check(self):
        """One cycle: detect deaths, schedule + perform due restarts.
        Returns the list of replica ids restarted this cycle."""
        restarted = []
        for rep in self.fleet.replicas:
            if rep.kind != "subprocess" or rep.state == "closed":
                continue
            if rep.state != "dead" and not rep.alive():
                rc = rep.proc.poll() if rep.proc is not None else None
                n = self.restarts.get(rep.rid, 0)
                backoff = self.backoff_s(n)
                self.fleet.mark_dead(
                    rep, reason=f"process died (rc={rc})")
                self._due[rep.rid] = self.clock() + backoff
                self._backoff[rep.rid] = backoff
            if rep.state != "dead":
                continue
            due = self._due.get(rep.rid)
            if due is None:            # died before we ever saw it
                self._due[rep.rid] = self.clock()
                self._backoff[rep.rid] = 0.0
                continue
            if self.clock() < due:
                continue
            n = self.restarts.get(rep.rid, 0)
            if n >= self.max_restarts:
                self.fleet._set_state(
                    rep, "closed",
                    reason=f"restart budget ({self.max_restarts}) "
                           f"exhausted")
                continue
            self.restarts[rep.rid] = n + 1
            try:
                rep.respawn(n + 1)
            except Exception as e:
                log.exception("restart of replica %s failed", rep.rid)
                backoff = self.backoff_s(n + 1)
                self._due[rep.rid] = self.clock() + backoff
                self._backoff[rep.rid] = backoff
                self.fleet._emit("restart_failed", replica=rep.rid,
                                 restart=n + 1, error=str(e)[:300])
                continue
            self.fleet.mark_joined(rep)
            event = {"replica": rep.rid, "restart": n + 1,
                     "backoff_s": self._backoff.get(rep.rid, 0.0),
                     "cause": "process_death"}
            self.events.append(event)
            self.fleet._emit("restart", **event)
            restarted.append(rep.rid)
        return restarted

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="bigdl-fleet-supervisor",
            daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.check()
            except Exception:
                log.exception("fleet supervision cycle failed")
            self._stop.wait(self.poll_interval_s)

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5)
