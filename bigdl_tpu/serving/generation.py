"""Autoregressive generation serving: compiled KV-cache decode steps
and a slot-based continuous-batching scheduler.

The serving stack could only run FULL forwards: serving a transformer
token-by-token meant re-running the O(L^2) forward over the whole
prefix for every new token.  This module restructures the computation
so the compiler sees O(1) incremental work per token (the TVM lesson,
arxiv 1802.04799): the model layer's KV cache (``TransformerLM
.init_cache`` / ``apply(cache=, pos=)``, nn/attention.py) turns a
decode step into one token's projections plus a masked attention read
over fixed-shape buffers, and this module turns THAT into a serving
loop with a closed executable set:

- ``generate_steps(model)`` -- the jitted (prefill, decode) pair,
  compiled once per model and cached on the instance like
  ``optim.validation.compiled_eval_step``.  Both steps DONATE the slot
  cache, so XLA updates the K/V buffers in place instead of copying
  ``slots x max_len`` of cache every tick.
- ``GenerateScheduler`` -- continuous batching over a fixed pool of
  decode slots: prefill ticks admit waiting prompts into free slots
  (batch-bucketed and prompt-length-bucketed through the same
  ``BucketLadder`` machinery the eval path uses, so the compiled-shape
  set is closed and warmable); decode ticks advance EVERY occupied
  slot one token in a single fixed-shape step.  Sequences join and
  leave slots mid-flight without recompiling anything: the cache
  batch axis never changes, and a vacated row is simply garbage the
  per-row frontier mask keeps invisible until the next occupant's
  prefill overwrites it.  Row ``slots`` (one past the pool) is a TRASH
  slot: prefill padding rows scatter their K/V there, so a
  partially-filled prefill bucket can never corrupt a live sequence.
- ``GenerateFuture`` -- the streaming per-request handle: tokens are
  pushed as ticks complete (``stream()`` yields them live);
  ``result()`` waits for EOS / ``max_new_tokens`` and returns the full
  generated list.

Every tick lands as a ``kind:"inference"`` telemetry event stamped
with ``tick_kind`` ("prefill"/"decode"), ``tokens`` emitted, and slot
occupancy -- the fields behind ``bigdl_serving_tokens_total`` and the
slot-utilization gauge (docs/observability.md, "Serving telemetry").
In THIS scheduler decoding is greedy (argmax in-jit, so only token
ids cross the host boundary each tick).

``PagedGenerateScheduler`` (below) is the memory-scale successor: the
same dispatcher contract, but the cache is a PAGED block pool
addressed through per-sequence block tables (serving/paging.py) --
prefix blocks shared across requests, long prompts prefilled in
fixed-size chunks interleaved with decode ticks, and temperature /
top-k / top-p sampling drawn inside the decode step
(serving/sampling.py).  The contiguous scheduler stays as the greedy
A/B baseline the bench compares against (docs/performance.md, "Paged
KV cache").
"""

import collections
import logging
import os
import queue
import threading
import time
from concurrent.futures import Future, TimeoutError as FutureTimeoutError
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.observability.spans import span
from bigdl_tpu.serving.buckets import BucketLadder

log = logging.getLogger("bigdl_tpu.serving")


def _scatter_rows(slot_leaf, frag_leaf, slot_ids, t):
    """Write a prefill fragment's rows into the slot cache at
    ``slot_ids``, first ``t`` positions.  K/V leaves are ``(batch,
    max_len, heads, head_dim)`` -- the batch axis sits at ``ndim - 4``,
    which also lands on the right axis for the scan-stacked layout's
    extra leading layer dim."""
    if slot_leaf.ndim == 4:
        return slot_leaf.at[slot_ids, :t].set(frag_leaf)
    return slot_leaf.at[:, slot_ids, :t].set(frag_leaf)


def generate_steps(model, cache_dtype=jnp.float32):
    """The jitted ``(prefill, decode)`` pair for ``model``, compiled
    once per (model, cache dtype) and cached on the instance (same
    lifetime story as ``compiled_eval_step``: dropping the model drops
    its executables).

    - ``prefill(params, slot_cache, tokens (B, T), lengths (B,),
      slot_ids (B,)) -> (first_tokens (B,), new_slot_cache)``: one
      ragged-prompt prefill -- runs the cached forward over the padded
      prompt batch, scatters the K/V fragment into the slot cache rows
      named by ``slot_ids``, and reads each row's first generated
      token at its TRUE ``length - 1`` (padding rows point at the
      trash slot and are discarded).
    - ``decode(params, slot_cache, tokens (S,), pos (S,)) ->
      (next_tokens (S,), new_slot_cache)``: one fixed-shape step over
      the whole pool.

    Both donate the slot cache (argument 1): steady-state decode moves
    one token's activations, not the cache.
    """
    cache = model.__dict__.setdefault("_compiled_generate_steps", {})
    key = np.dtype(cache_dtype).name
    fns = cache.get(key)
    if fns is not None:
        return fns

    def prefill(params, slot_cache, tokens, lengths, slot_ids):
        n, t = tokens.shape
        local = model.init_cache(n, t, cache_dtype)
        logits, frag = model.apply(params, (), tokens, cache=local)
        idx = jnp.clip(lengths.astype(jnp.int32) - 1, 0, t - 1)
        row = jnp.take_along_axis(
            logits, idx[:, None, None], axis=1)[:, 0]
        first = jnp.argmax(row, axis=-1).astype(jnp.int32)
        new = jax.tree.map(
            lambda sc, fr: _scatter_rows(sc, fr, slot_ids, t),
            slot_cache, frag)
        return first, new

    def decode(params, slot_cache, tokens, pos):
        logits, new = model.apply(params, (), tokens[:, None],
                                  cache=slot_cache, pos=pos)
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        return nxt, new

    fns = (jax.jit(prefill, donate_argnums=(1,)),
           jax.jit(decode, donate_argnums=(1,)))
    cache[key] = fns
    return fns


class GenerateFuture(Future):
    """Per-request generation handle.  ``result(timeout)`` returns the
    full generated token list (EOS included when hit); ``stream()``
    yields tokens LIVE as decode ticks complete.  Once finished,
    ``finish_reason`` ("eos" / "length"), ``prompt_len`` and the
    end-to-end ``latency_s`` are set."""

    def __init__(self, prompt_len: int, max_new_tokens: int,
                 eos_id: Optional[int]):
        super().__init__()
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.finish_reason: Optional[str] = None
        self.latency_s: Optional[float] = None
        #: latency_s split at slot admission: time queued waiting for a
        #: free decode slot vs time actually prefilling/decoding (one
        #: mixed number hides queue pressure behind decode speed)
        self.queue_wait_s: Optional[float] = None
        self.decode_s: Optional[float] = None
        self._t_submit = time.perf_counter()
        #: wall-clock twin of _t_submit, anchoring trace records
        self._t_submit_wall = time.time()
        #: perf_counter stamp when a prefill tick admitted us to a slot
        self._t_admit: Optional[float] = None
        #: sampled TraceContext from the submitting engine, or None
        self._trace = None
        #: SamplingParams for this request (None = greedy argmax);
        #: only the paged scheduler accepts non-greedy settings
        self.sampling = None
        #: prompt positions served straight from the prefix cache
        #: (paged scheduler only; 0 means every position was computed)
        self.prefix_hit_tokens = 0
        self._stream: "queue.Queue" = queue.Queue()
        #: set by GenerateScheduler._abandon on a CLAIMED request: the
        #: dispatcher evicts the sequence at the next tick boundary
        self._abandoned = False

    def stream(self, timeout: Optional[float] = None):
        """Yield generated token ids as they are produced.  ``timeout``
        bounds the WHOLE stream; a tick that errors re-raises here."""
        deadline = None if timeout is None \
            else time.perf_counter() + timeout
        while True:
            remaining = None if deadline is None \
                else deadline - time.perf_counter()
            if remaining is not None and remaining <= 0:
                raise FutureTimeoutError(
                    f"token stream timed out after {timeout}s")
            try:
                item = self._stream.get(timeout=remaining)
            except queue.Empty:
                raise FutureTimeoutError(
                    f"token stream timed out after {timeout}s") from None
            if item is None:                      # completion sentinel
                return
            if isinstance(item, BaseException):
                raise item
            yield item


class _Slot:
    """One occupied decode slot: the request's future, its token tally
    and the cache frontier (``pos`` = where the NEXT token's K/V will
    be written; ``last`` = the token that decode step feeds in)."""

    __slots__ = ("fut", "tokens", "last", "pos")

    def __init__(self, fut, first_token, pos):
        self.fut = fut
        self.tokens = [first_token]
        self.last = first_token
        self.pos = pos


class GenerateScheduler:
    """Slot-based continuous batching over one model's KV cache.

    ``slots`` decode slots plus one trash row share a single
    fixed-shape cache (``model.init_cache(slots + 1, max_len)``).  The
    dispatcher thread alternates: a PREFILL tick admits up to
    ``len(free slots)`` waiting prompts (batch padded to the slot
    ladder, prompts padded to the prompt-length ladder), a DECODE tick
    advances every occupied slot one token.  Finished sequences free
    their slot immediately -- the next prefill reuses it without any
    recompile, because nothing about the compiled shapes depends on
    WHICH slots are live (the acceptance contract: zero new compiles
    after ``precompile()`` across a mixed-length closed-loop workload,
    pinned in tests/test_decode.py).

    ``params_fn`` is read once per tick, so an engine-level
    ``refresh_params`` hot-swap takes effect on the next tick; a
    sequence mid-flight finishes with its earlier tokens' K/V from the
    old weights (documented in docs/performance.md -- the alternative,
    draining generation for every swap, is a worse availability
    trade).
    """

    def __init__(self, model, slots: int = 8, max_len: Optional[int] = None,
                 prompt_ladder: Optional[BucketLadder] = None,
                 queue_capacity: int = 1024, cache_dtype=jnp.float32,
                 telemetry=None, params_fn=None, admission_check=None,
                 exhausted_hook=None, name: str = "generate"):
        if not hasattr(model, "init_cache"):
            raise TypeError(
                f"{type(model).__name__} has no init_cache(): generation "
                f"needs a KV-cache decode mode (TransformerLM has one)")
        if slots < 1:
            raise ValueError(f"need at least 1 decode slot, got {slots}")
        self.model = model
        self.slots = int(slots)
        model_max = getattr(model, "max_len", None)
        self.max_len = int(model_max if max_len is None
                           else min(max_len, model_max or max_len))
        self.queue_capacity = int(queue_capacity)
        self.telemetry = telemetry
        #: optional callable run under THIS scheduler's lock right
        #: before a request enqueues (raising refuses admission): the
        #: owning engine injects its draining/closed check here, so an
        #: engine.drain() that observed an idle scheduler can never
        #: race a generate() that already passed the engine-side check
        self._admission_check = admission_check
        #: optional callable(exc) invoked when the KV pool sheds a
        #: request (``BlockPoolExhausted``): the owning engine points
        #: this at its MemoryLedger's forensic dump so the first
        #: exhaustion leaves a durable memory_dump event
        self._exhausted_hook = exhausted_hook
        self._params = params_fn or (lambda: model.parameters()[0])
        # prompt lengths round up this ladder (rung = the padded prefill
        # T); a COPY like the engine's batch ladder, so growth stays ours
        self.prompt_ladder = prompt_ladder.copy() \
            if prompt_ladder is not None \
            else BucketLadder(self.max_len,
                              min_size=min(8, self.max_len))
        if self.prompt_ladder.max > self.max_len:
            raise ValueError(
                f"prompt ladder's largest rung {self.prompt_ladder.max} "
                f"exceeds the cache max_len {self.max_len}")
        # admission counts round up this one (prefill batch rungs)
        self.batch_ladder = BucketLadder(self.slots)
        #: slot pool + 1 trash row (prefill padding rows scatter there)
        self._trash = self.slots
        self._cache_dtype = cache_dtype
        self._setup_steps()      # compiled steps + self._cache (the
        #                          paged subclass swaps in pool + tables)
        self._slots = [None] * self.slots
        self._free = collections.deque(range(self.slots))
        self._pending = collections.deque()
        # requests popped off the queue but not yet slotted (or failed):
        # the engine predict path's _in_tick equivalent, so drain() can
        # wait for TRUE quiescence instead of missing a request that is
        # mid-prefill between queue-pop and slot assignment
        self._in_flight = 0
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._running = True
        self._tick = 0
        self._served = 0
        self._tokens_out = 0
        self._dispatcher = threading.Thread(
            target=self._loop, name=f"bigdl-serving-{name}", daemon=True)
        self._dispatcher.start()

    #: set by PagedGenerateScheduler -- the contiguous scheduler's
    #: compiled steps only argmax, so non-greedy sampling is refused at
    #: submit instead of silently decoding greedy
    supports_sampling = False

    def _setup_steps(self):
        """Compile the step pair and allocate the device cache; the
        paged subclass overrides this with the pool + allocator."""
        self._prefill_fn, self._decode_fn = generate_steps(
            self.model, self._cache_dtype)
        self._cache = self.model.init_cache(self.slots + 1, self.max_len,
                                            self._cache_dtype)

    def _reset_pool(self):
        """Reallocate the device cache after a failed (donating) tick."""
        self._cache = self.model.init_cache(self.slots + 1, self.max_len,
                                            self._cache_dtype)

    def cache_bytes(self) -> int:
        """Device bytes the KV cache actually holds (the bench's
        peak-cache-bytes comparison reads this on both schedulers)."""
        return int(sum(leaf.size * leaf.dtype.itemsize
                       for leaf in jax.tree.leaves(self._cache)))

    # ----- request surface -------------------------------------------------- #
    def submit(self, prompt, max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               timeout: Optional[float] = None,
               trace=None, sampling=None) -> GenerateFuture:
        """Enqueue one prompt (1-D int token ids); returns the
        streaming future.  Blocks when ``queue_capacity`` requests are
        pending (``timeout`` bounds the wait, like engine.submit)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the cache max_len "
                f"{self.max_len}; raise decode_max_len or trim the "
                f"request")
        if sampling is not None and not sampling.greedy \
                and not self.supports_sampling:
            raise ValueError(
                "temperature/top-k/top-p sampling needs the paged "
                "scheduler (ServingEngine kv_cache='paged'); the "
                "contiguous pool decodes greedy only")
        fut = GenerateFuture(prompt.size, max_new_tokens, eos_id)
        fut._trace = trace
        fut.sampling = sampling
        deadline = None if timeout is None \
            else time.perf_counter() + timeout
        with self._lock:
            if not self._running:
                raise RuntimeError("generation scheduler is closed")
            while self._running and \
                    len(self._pending) >= self.queue_capacity:
                remaining = None if deadline is None \
                    else deadline - time.perf_counter()
                if remaining is not None and remaining <= 0:
                    raise FutureTimeoutError(
                        f"generate submit timed out after {timeout}s: "
                        f"queue full ({self.queue_capacity} pending)")
                self._not_full.wait(timeout=remaining)
            if not self._running:
                raise RuntimeError("generation scheduler is closed")
            if self._admission_check is not None:
                self._admission_check()
            self._pending.append((prompt, fut))
            self._work.notify()
        return fut

    def _active(self):
        return [(i, s) for i, s in enumerate(self._slots) if s is not None]

    def stats(self):
        with self._lock:
            active = len(self._active())
            return {"pending": len(self._pending),
                    "in_flight": self._in_flight,
                    "slots": self.slots, "slots_active": active,
                    "ticks": self._tick, "served": self._served,
                    "tokens": self._tokens_out,
                    "running": self._running}

    # ----- warmup ----------------------------------------------------------- #
    def precompile(self) -> int:
        """Compile the whole generation shape set before traffic: the
        one decode executable plus every (admission rung x prompt-length
        rung) prefill.  Warmup runs on DUMMY caches (zeros_like the
        real one -- identical shapes key identical executables) so the
        live cache is never donated away.  Returns backend compiles
        performed."""
        from bigdl_tpu.observability.watchdogs import backend_compile_count

        params = self._params()
        before = backend_compile_count()
        dummy = jax.tree.map(jnp.zeros_like, self._cache)
        s = self.slots + 1
        nxt, dummy = self._decode_fn(params, dummy,
                                     np.zeros((s,), np.int32),
                                     np.zeros((s,), np.int32))
        jax.block_until_ready(nxt)
        for b in self.batch_ladder:
            for t in self.prompt_ladder:
                first, dummy = self._prefill_fn(
                    params, dummy, np.zeros((int(b), int(t)), np.int32),
                    np.ones((int(b),), np.int32),
                    np.full((int(b),), self._trash, np.int32))
                jax.block_until_ready(first)
        return backend_compile_count() - before

    # ----- dispatcher ------------------------------------------------------- #
    def _loop(self):
        while True:
            with self._lock:
                while self._running and not self._pending \
                        and not self._active():
                    self._idle.notify_all()
                    self._work.wait()
                if not self._running and not self._pending \
                        and not self._active():
                    self._idle.notify_all()
                    return
                admit = []
                if self._pending and self._free:
                    take = min(len(self._free), len(self._pending))
                    admit = [self._pending.popleft() for _ in range(take)]
                    self._in_flight += len(admit)
                    self._not_full.notify_all()
                qdepth = len(self._pending)
            try:
                # a cancelled future's prompt is dropped here (its slot
                # was never assigned); claiming moves PENDING->RUNNING
                # so result-setting can't race a caller's cancel().  A
                # dropped future still gets the stream sentinel -- a
                # consumer blocked in stream() must see the end, not
                # hang on a request nobody will ever decode
                claimed = []
                for p, f in admit:
                    if f.set_running_or_notify_cancel():
                        claimed.append((p, f))
                    else:
                        f._stream.put(None)
                self._sweep_abandoned()
                if claimed:
                    # by the time _run_prefill returns, every claimed
                    # request is slotted (visible to _active) or failed
                    self._run_prefill(claimed, qdepth)
                if self._active():
                    self._run_decode(qdepth)
            except Exception:
                # defensive: per-tick failures are already surfaced on
                # the affected futures; this keeps an unexpected
                # scheduler bug from silently killing the dispatcher
                log.exception("generation scheduler tick failed")
            finally:
                with self._lock:
                    self._in_flight -= len(admit)
                    if not self._pending and not self._in_flight \
                            and not self._active():
                        self._idle.notify_all()

    def _compiles(self):
        if self.telemetry is None:
            return None
        from bigdl_tpu.observability.watchdogs import backend_compile_count

        return backend_compile_count()

    def _run_prefill(self, reqs, qdepth):
        t0 = time.perf_counter()
        for _p, f in reqs:
            f._t_admit = t0          # queue wait ends at slot admission
        execs_before = self._compiles()
        n = len(reqs)
        bucket = self.batch_ladder.bucket_for(n) or self.batch_ladder.add(n)
        longest = max(int(p.size) for p, _ in reqs)
        t_pad = self.prompt_ladder.bucket_for(longest) \
            or self.prompt_ladder.add(longest)
        tokens = np.zeros((bucket, t_pad), np.int32)
        lengths = np.ones((bucket,), np.int32)
        slot_ids = np.full((bucket,), self._trash, np.int32)
        slots = []
        with self._lock:
            for i, (p, _f) in enumerate(reqs):
                tokens[i, : p.size] = p
                lengths[i] = p.size
                slot_ids[i] = self._free.popleft()
                slots.append(slot_ids[i])
        try:
            with span("generate_prefill", tick=self._tick, records=n):
                first, self._cache = self._prefill_fn(
                    self._params(), self._cache, tokens, lengths, slot_ids)
                first = np.asarray(first)            # host sync
        except Exception as e:
            log.exception("prefill tick failed (%d prompts)", n)
            self._tick_failed(e, [f for _p, f in reqs], slots)
            return
        done_lat = []
        for i, (p, f) in enumerate(reqs):
            slot = _Slot(f, int(first[i]), pos=int(p.size))
            self._slots[slots[i]] = slot
            self._deliver(slots[i], slot, done_lat)
        self._tick += 1
        self._record_tick("prefill", t0, records=n, tokens=n,
                          bucket=int(bucket), prompt_bucket=int(t_pad),
                          qdepth=qdepth, execs_before=execs_before,
                          latencies=done_lat,
                          riders=[f for _p, f in reqs])

    def _run_decode(self, qdepth):
        t0 = time.perf_counter()
        execs_before = self._compiles()
        s = self.slots + 1
        tokens = np.zeros((s,), np.int32)
        pos = np.zeros((s,), np.int32)
        active = self._active()
        for i, slot in active:
            tokens[i] = slot.last
            pos[i] = slot.pos
        try:
            with span("generate_decode", tick=self._tick,
                      records=len(active)):
                nxt, self._cache = self._decode_fn(
                    self._params(), self._cache, tokens, pos)
                nxt = np.asarray(nxt)                # host sync
        except Exception as e:
            log.exception("decode tick failed (%d slots)", len(active))
            self._tick_failed(e, [], [])
            return
        done_lat = []
        for i, slot in active:
            slot.pos += 1
            slot.last = int(nxt[i])
            slot.tokens.append(slot.last)
            self._deliver(i, slot, done_lat)
        self._tick += 1
        self._record_tick("decode", t0, records=0, tokens=len(active),
                          qdepth=qdepth, execs_before=execs_before,
                          latencies=done_lat, slots_before=len(active),
                          riders=[slot.fut for _i, slot in active])

    def _tick_failed(self, e, futs, extra_free):
        """A failed tick is a POOL loss, not just this tick's: both
        compiled steps DONATE the slot cache, and jax invalidates
        donated buffers at call time -- after a runtime failure
        ``self._cache`` points at deleted arrays, so every live
        sequence's K/V is gone with it.  Fail the tick's own futures
        AND every still-active slot honestly, then reallocate a fresh
        zero cache so the scheduler keeps serving NEW prompts instead
        of raising 'Array has been deleted' forever."""
        failed = list(futs)
        for i, slot in self._active():
            failed.append(slot.fut)
            self._release_slot(i, slot)
        with self._lock:
            self._free.extend(extra_free)
        self._reset_pool()
        for f in failed:
            if not f.done():
                f._stream.put(e)
                f._stream.put(None)
                f.set_exception(e)

    def _abandon(self, fut):
        """Give up on a generation nobody will read (the sibling of
        ``ServingEngine._abandon``).  Still pending: cancel, free its
        queue slot now, end the stream.  Already CLAIMED: mark it for
        eviction -- the dispatcher frees the decode slot at the next
        tick boundary (``_sweep_abandoned``) instead of decoding the
        rest of ``max_new_tokens`` into a slot nobody reads, which is
        what lets a fleet deadline-retry on a sibling without
        double-booking decode slots for the whole sequence."""
        if not fut.cancel():         # already decoding (or done)
            fut._abandoned = True
            return
        fut._stream.put(None)
        with self._lock:
            for entry in self._pending:
                if entry[1] is fut:
                    self._pending.remove(entry)
                    self._not_full.notify()
                    break

    def _sweep_abandoned(self):
        """Evict abandoned mid-flight sequences: free the slot and
        resolve the future with the tokens decoded so far (a PARTIAL
        result, ``finish_reason: "abandoned"`` -- a success as far as
        replica health accounting goes: the replica worked, the caller
        left)."""
        for i, slot in self._active():
            fut = slot.fut
            if not fut._abandoned or fut.done():
                continue
            self._release_slot(i, slot)
            fut.finish_reason = "abandoned"
            self._stamp_latency(fut)
            fut._stream.put(None)
            fut.set_result(list(slot.tokens))
            self._record_request_trace(fut, len(slot.tokens))

    def _release_slot(self, index, slot):
        """Return a slot to the free pool (every eviction path funnels
        here; the paged subclass also releases the sequence's blocks)."""
        self._slots[index] = None
        with self._lock:
            self._free.append(index)

    def _deliver(self, index, slot, done_lat):
        """Stream the slot's newest token; complete + free the slot on
        EOS or the request's token budget."""
        fut = slot.fut
        tok = slot.tokens[-1]
        fut._stream.put(tok)
        reason = None
        if fut.eos_id is not None and tok == fut.eos_id:
            reason = "eos"
        elif len(slot.tokens) >= fut.max_new_tokens:
            reason = "length"
        if reason is None:
            return
        self._release_slot(index, slot)
        fut.finish_reason = reason
        self._stamp_latency(fut)
        done_lat.append(fut)
        self._served += 1
        fut._stream.put(None)
        fut.set_result(list(slot.tokens))
        self._record_request_trace(fut, len(slot.tokens))

    @staticmethod
    def _stamp_latency(fut):
        """Set latency_s and its queue-wait/decode split on a finished
        future (admit stamp missing => the whole latency was a wait)."""
        now = time.perf_counter()
        fut.latency_s = now - fut._t_submit
        admit = fut._t_admit if fut._t_admit is not None else now
        fut.queue_wait_s = max(0.0, admit - fut._t_submit)
        fut.decode_s = max(0.0, now - admit)

    def _record_request_trace(self, fut, n_tokens):
        """Completion span for one traced generation -- the decode-side
        mirror of the fleet's root span, carrying the queue-wait vs
        decode split and every token's tick story via the tick links."""
        if fut._trace is None or self.telemetry is None:
            return
        emit = getattr(self.telemetry, "record_trace", None)
        if emit is None:
            return
        try:
            kw = {}
            if fut.prefix_hit_tokens:
                # how much of this request's prompt the prefix cache
                # served -- ties a fast queue_wait/decode split to its
                # cause in the trace story
                kw["prefix_hit_tokens"] = fut.prefix_hit_tokens
            emit("generate_request", fut._trace.child(),
                 fut._t_submit_wall, fut.latency_s or 0.0,
                 queue_wait_s=round(fut.queue_wait_s or 0.0, 6),
                 decode_s=round(fut.decode_s or 0.0, 6),
                 tokens=n_tokens, finish_reason=fut.finish_reason, **kw)
        except Exception:
            log.exception("generation trace record failed")

    def _record_tick(self, kind, t0, records, tokens, qdepth,
                     execs_before, latencies, bucket=None,
                     prompt_bucket=None, slots_before=None,
                     riders=None, extra=None):
        self._tokens_out += tokens
        if self.telemetry is None:
            return
        try:
            wall = time.perf_counter() - t0
            active = slots_before if slots_before is not None \
                else len(self._active())
            event = dict(step=self._tick, wall_s=wall, tick_kind=kind,
                         records=records, tokens=tokens,
                         tokens_per_s=tokens / max(wall, 1e-9),
                         slots_active=active, slots_total=self.slots,
                         queue_depth=qdepth,
                         queue_capacity=self.queue_capacity)
            if bucket is not None:
                event["bucket"] = bucket
                event["batch_fill"] = records / bucket
                event["pad_waste"] = (bucket - records) / bucket
            if extra:
                # paged-pool occupancy + prefix-hit fields (the metrics
                # bridge turns these into bigdl_serving_kv_blocks{state}
                # and bigdl_serving_prefix_hits_total)
                event.update(extra)
            if prompt_bucket is not None:
                event["prompt_bucket"] = prompt_bucket
            if latencies:
                # a DISTINCT field from predict's request_latency_s: a
                # multi-token generation is seconds where a predict is
                # milliseconds, and one mixed series would burn any
                # predict-tuned latency SLO (and its canary auto-
                # rollback) on perfectly healthy generate traffic.
                # queue-wait and decode time land as SEPARATE series:
                # one merged number read as "slow decode" when the real
                # story was slot starvation
                event["generate_latency_s"] = [round(f.latency_s, 6)
                                               for f in latencies]
                event["generate_queue_wait_s"] = [
                    round(f.queue_wait_s or 0.0, 6) for f in latencies]
                event["generate_decode_s"] = [
                    round(f.decode_s or 0.0, 6) for f in latencies]
                traces = [f._trace.trace_id if f._trace is not None
                          else None for f in latencies]
                if any(t is not None for t in traces):
                    # parallel to generate_latency_s: the metrics
                    # bridge zips the two for histogram exemplars
                    event["generate_traces"] = traces
            if riders:
                tids = [f._trace.trace_id for f in riders
                        if f._trace is not None]
                if tids:
                    # which traced sequences were RESIDENT this tick:
                    # obs_report attributes slot occupancy by trace
                    event["trace_ids"] = tids
            after = self._compiles()
            if after is not None and after - execs_before > 0:
                # nonzero after precompile() = a generation shape leak
                event["compiles"] = after - execs_before
            self.telemetry.record("inference", **event)
            self._record_tick_trace(kind, wall, riders, records, tokens)
        except Exception:
            log.exception("generation telemetry record failed (tick %d)",
                          self._tick)

    def _record_tick_trace(self, kind, wall, riders, records, tokens):
        """One span per tick with links to every traced sequence that
        rode it -- the continuous-batching shape (one tick, N resident
        requests) is a links relationship, not parent/child, because
        the tick belongs to ALL of them equally."""
        emit = getattr(self.telemetry, "record_trace", None)
        if emit is None or not riders:
            return
        links = [f._trace.trace_id for f in riders
                 if f._trace is not None]
        if not links:
            return
        from bigdl_tpu.observability.tracing import TraceContext

        emit("%s_tick" % kind, TraceContext.mint(),
             time.time() - wall, wall, links=links, tick=self._tick,
             records=records, tokens=tokens)

    # ----- lifecycle -------------------------------------------------------- #
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until no generation work is pending or mid-flight.
        ADMISSION gating belongs to the owning engine (its ``drain()``
        closes ``generate()`` before calling this); returns False when
        ``timeout`` passes with sequences still decoding."""
        deadline = None if timeout is None \
            else time.perf_counter() + timeout
        with self._lock:
            self._work.notify_all()
            while self._pending or self._in_flight or self._active():
                remaining = None if deadline is None \
                    else deadline - time.perf_counter()
                if remaining is not None and remaining <= 0:
                    return False
                self._idle.wait(timeout=remaining)
        return True

    def close(self, timeout: Optional[float] = 10.0):
        with self._lock:
            self._running = False
            self._work.notify_all()
            self._not_full.notify_all()
        self._dispatcher.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def paged_generate_steps(model, cache_dtype=jnp.float32):
    """The jitted step triple for PAGED generation, compiled once per
    (model, cache dtype) and cached on the instance like
    ``generate_steps``:

    - ``chunk_prefill(params, pool, tokens (B, Tc), start (B,),
      lengths (B,), tables (B, MB), temperature, top_k, top_p, seed
      (each (B,))) -> (first_tokens (B,), new_pool)``: one fixed-size
      prompt chunk per row, scattered into the block pool through the
      tables; row ``i``'s returned token is sampled from its LAST
      valid chunk position's logits -- only meaningful for rows whose
      chunk completes the prompt, garbage (and discarded) otherwise.
    - ``decode(params, pool, tokens (S,), pos (S,), tables (S, MB),
      temperature, top_k, top_p, seed (each (S,))) -> (next_tokens,
      new_pool)``: one fixed-shape step over the whole slot pool.
    - ``copy_block(pool, src, dst) -> new_pool``: the copy-on-write
      primitive -- physical block ``src`` duplicated into ``dst``
      across every layer, one executable regardless of which blocks.

    Sampling runs in-jit (serving/sampling.py): the knobs are runtime
    arrays, so greedy and sampled rows share each executable, and the
    RNG folds on (seed, token position) -- a request replays
    identically however it was chunked or slotted.  All three steps
    donate the pool.
    """
    from bigdl_tpu.serving.sampling import sample_tokens

    cache = model.__dict__.setdefault("_compiled_paged_steps", {})
    key = np.dtype(cache_dtype).name
    fns = cache.get(key)
    if fns is not None:
        return fns

    def chunk_prefill(params, pool, tokens, start, lengths, tables,
                      temperature, top_k, top_p, seed):
        tc = tokens.shape[1]
        logits, new = model.apply_paged(params, tokens, pool, tables,
                                        pos=start, lengths=lengths)
        idx = jnp.clip(lengths.astype(jnp.int32) - 1, 0, tc - 1)
        row = jnp.take_along_axis(
            logits, idx[:, None, None], axis=1)[:, 0]
        # the sampled token OCCUPIES position start + lengths; folding
        # the RNG on that position makes the draw independent of how
        # the prompt was chunked
        first = sample_tokens(row, temperature, top_k, top_p, seed,
                              start + lengths)
        return first, new

    def decode(params, pool, tokens, pos, tables, temperature, top_k,
               top_p, seed):
        logits, new = model.apply_paged(params, tokens[:, None], pool,
                                        tables, pos=pos)
        nxt = sample_tokens(logits[:, 0], temperature, top_k, top_p,
                            seed, pos + 1)
        return nxt, new

    def copy_block(pool, src, dst):
        def cp(leaf):
            # pool leaves are (NB, bs, H, Dh); the scan-stacked layout
            # adds a leading layer axis -- block axis sits at ndim - 4
            # either way (same convention as _scatter_rows)
            if leaf.ndim == 4:
                return leaf.at[dst].set(leaf[src])
            return leaf.at[:, dst].set(leaf[:, src])
        return jax.tree.map(cp, pool)

    fns = (jax.jit(chunk_prefill, donate_argnums=(1,)),
           jax.jit(decode, donate_argnums=(1,)),
           jax.jit(copy_block, donate_argnums=(0,)))
    cache[key] = fns
    return fns


class _PagedSlot:
    """One admitted sequence in the paged scheduler.  While
    ``consumed < len(prompt)`` the slot is PREFILLING: chunk ticks
    advance ``consumed`` (which starts at the prefix-cache hit length,
    not 0).  The final chunk samples the first token and flips the
    slot to decoding, after which the fields mean exactly what
    ``_Slot``'s do."""

    __slots__ = ("fut", "prompt", "seq", "consumed", "tokens", "last",
                 "pos", "seed")

    def __init__(self, fut, prompt, seq, consumed, seed):
        self.fut = fut
        self.prompt = prompt
        self.seq = seq                    # BlockAllocator sequence id
        self.consumed = int(consumed)
        self.tokens = []
        self.last = None
        self.pos = None
        self.seed = int(seed)

    @property
    def prefilling(self):
        return self.consumed < self.prompt.size


class PagedGenerateScheduler(GenerateScheduler):
    """Continuous batching over a PAGED KV cache: the dispatcher
    contract (slots, futures, telemetry, drain/close) is inherited
    from ``GenerateScheduler``; what changes is where K/V live and how
    prompts arrive.

    - The cache is ``model.init_paged_cache(num_blocks, block_size)``
      -- memory scales with ``num_blocks``, not ``slots x max_len``
      worst case -- and every sequence addresses it through a
      ``BlockAllocator`` table (serving/paging.py).  Admission
      RESERVES the request's worst-case block need; a pool that can't
      hold it sheds the request with ``BlockPoolExhausted`` instead of
      letting decode corrupt a neighbour later.
    - Prompts whose leading full blocks hash-match an earlier request
      map the SHARED blocks (``prefix_hit_tokens``) and skip that much
      prefill compute and memory.
    - A long prompt prefills in ``prefill_chunk``-token chunks, ONE
      chunk per dispatcher iteration with a decode tick in between --
      so an admitted 10k-token prompt delays live streams by one
      chunk's latency per token, never head-of-line-blocks them.
    - Decode ticks sample in-jit per the request's ``SamplingParams``
      (greedy by default, bit-identical to the contiguous argmax).

    The executable set stays closed and warmable: ONE decode shape,
    one chunk shape per admission-batch rung, one block-copy -- zero
    steady-state recompiles across mixed lengths, chunked prefill and
    sampled decoding (the acceptance contract, tests/test_paged.py).
    """

    supports_sampling = True

    def __init__(self, model, slots: int = 8, max_len: Optional[int] = None,
                 prompt_ladder: Optional[BucketLadder] = None,
                 queue_capacity: int = 1024, cache_dtype=jnp.float32,
                 telemetry=None, params_fn=None, admission_check=None,
                 exhausted_hook=None, name: str = "generate",
                 block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None):
        if not hasattr(model, "init_paged_cache"):
            raise TypeError(
                f"{type(model).__name__} has no init_paged_cache(): the "
                f"paged scheduler needs the block-pool decode mode "
                f"(TransformerLM has one); kv_cache='contiguous' works "
                f"with plain init_cache models")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        model_max = getattr(model, "max_len", None)
        eff_max = int(model_max if max_len is None
                      else min(max_len, model_max or max_len))
        #: table width: enough entries to map max_len positions
        self.max_blocks_per_seq = -(-eff_max // self.block_size)
        #: pool size; the default matches the contiguous pool's token
        #: capacity (slots x max_len) -- pass something smaller to
        #: actually cap memory (the bench does; prefix sharing means a
        #: smaller pool still holds the same traffic)
        self.num_blocks = int(num_blocks) if num_blocks is not None \
            else int(slots) * self.max_blocks_per_seq
        if prefill_chunk is None:
            prefill_chunk = min(64, eff_max)
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.prefill_chunk = int(min(prefill_chunk, eff_max))
        # admission-tick prefix-hit deltas, stamped on the next chunk
        # tick's telemetry event (prompt_tokens is the hit-rate
        # denominator: positions ADMITTED, hit or not)
        self._hits_delta = 0
        self._hit_tokens_delta = 0
        self._prompt_tokens_delta = 0
        self._seq_counter = 0
        super().__init__(model, slots=slots, max_len=max_len,
                         prompt_ladder=prompt_ladder,
                         queue_capacity=queue_capacity,
                         cache_dtype=cache_dtype, telemetry=telemetry,
                         params_fn=params_fn,
                         admission_check=admission_check,
                         exhausted_hook=exhausted_hook, name=name)

    def _setup_steps(self):
        self._chunk_fn, self._decode_fn, self._copy_fn = \
            paged_generate_steps(self.model, self._cache_dtype)
        self._cache = self.model.init_paged_cache(
            self.num_blocks, self.block_size, self._cache_dtype)
        self._alloc = self._make_alloc()

    def kv_dtype(self) -> str:
        """Short storage-dtype name of the paged pool ("fp32"/"int8"),
        the spelling BlockAllocator namespaces prefix hashes with."""
        name = np.dtype(self._cache_dtype).name
        return {"float32": "fp32", "bfloat16": "bf16",
                "float16": "fp16"}.get(name, name)

    def _make_alloc(self):
        """Build the allocator for the pool JUST allocated: it learns
        the pool's storage dtype (prefix hashes refuse to cross
        storage formats) and the measured device bytes behind one
        addressable block -- every leaf, scales included -- so
        ``stats()`` reports real narrow bytes, not compute-dtype
        hand-math (ROADMAP item 3's rule)."""
        from bigdl_tpu.serving.paging import BlockAllocator

        pool_bytes = sum(leaf.size * leaf.dtype.itemsize
                         for leaf in jax.tree.leaves(self._cache))
        return BlockAllocator(
            self.num_blocks, self.block_size, kv_dtype=self.kv_dtype(),
            bytes_per_block=int(pool_bytes) // (self.num_blocks + 1))

    def _reset_pool(self):
        # a failed donating tick killed the device pool, so every
        # cached prefix block's CONTENT is gone too: fresh allocator,
        # empty registry (the base already released live sequences)
        self._cache = self.model.init_paged_cache(
            self.num_blocks, self.block_size, self._cache_dtype)
        self._alloc = self._make_alloc()

    def flush_prefix_cache(self):
        """Invalidate cached prefix blocks (engine weight swaps call
        this -- K/V computed under old weights must not serve new
        prompts)."""
        self._alloc.flush_cached()

    def stats(self):
        st = super().stats()
        st["kv"] = self._alloc.stats()
        st["block_size"] = self.block_size
        st["prefill_chunk"] = self.prefill_chunk
        return st

    # ----- warmup ----------------------------------------------------------- #
    def precompile(self) -> int:
        """Warm the whole paged shape set: the one decode executable,
        one chunk-prefill per admission rung, and the COW block copy.
        Dummy pools only -- the live pool is never donated away."""
        from bigdl_tpu.observability.watchdogs import backend_compile_count

        params = self._params()
        before = backend_compile_count()
        dummy = jax.tree.map(jnp.zeros_like, self._cache)
        s = self.slots
        mb = self.max_blocks_per_seq
        trash = np.int32(self._alloc.trash)

        def knobs(n):
            return (np.zeros((n,), np.float32), np.zeros((n,), np.int32),
                    np.ones((n,), np.float32), np.zeros((n,), np.int32))

        nxt, dummy = self._decode_fn(
            params, dummy, np.zeros((s,), np.int32),
            np.zeros((s,), np.int32), np.full((s, mb), trash, np.int32),
            *knobs(s))
        jax.block_until_ready(nxt)
        tc = self.prefill_chunk
        for b in self.batch_ladder:
            b = int(b)
            first, dummy = self._chunk_fn(
                params, dummy, np.zeros((b, tc), np.int32),
                np.zeros((b,), np.int32), np.ones((b,), np.int32),
                np.full((b, mb), trash, np.int32), *knobs(b))
            jax.block_until_ready(first)
        dummy = self._copy_fn(dummy, np.int32(0), np.int32(0))
        jax.block_until_ready(jax.tree.leaves(dummy)[0])
        return backend_compile_count() - before

    # ----- dispatcher ticks -------------------------------------------------- #
    def _release_slot(self, index, slot):
        seq = getattr(slot, "seq", None)
        if seq is not None:
            self._alloc.free_sequence(seq)
        super()._release_slot(index, slot)

    def _kv_extra(self):
        st = self._alloc.stats()
        extra = {"kv_blocks_used": st["blocks_used"],
                 "kv_blocks_cached": st["blocks_cached"],
                 "kv_blocks_free": st["blocks_free"],
                 "kv_blocks_total": st["blocks_total"]}
        if self._prompt_tokens_delta:
            extra["prompt_tokens"] = self._prompt_tokens_delta
            self._prompt_tokens_delta = 0
        if self._hits_delta or self._hit_tokens_delta:
            extra["prefix_hits"] = self._hits_delta
            extra["prefix_hit_tokens"] = self._hit_tokens_delta
            self._hits_delta = 0
            self._hit_tokens_delta = 0
        return extra

    def _run_prefill(self, reqs, qdepth):
        """ADMISSION only (no device work): assign a slot, match the
        prefix cache, reserve the worst-case block need.  The actual
        prompt compute happens one chunk per dispatcher iteration in
        ``_run_decode``, interleaved with decode ticks."""
        from bigdl_tpu.serving.paging import BlockPoolExhausted

        t0 = time.perf_counter()
        for p, f in reqs:
            f._t_admit = t0          # queue wait ends at slot admission
        for p, f in reqs:
            sp = f.sampling
            seed = 0
            if sp is not None and not sp.greedy:
                seed = sp.seed if sp.seed is not None else \
                    int.from_bytes(os.urandom(4), "little") & 0x7fffffff
            seq = self._seq_counter
            self._seq_counter += 1
            with self._lock:
                idx = self._free.popleft()
            try:
                cached = self._alloc.begin_sequence(
                    seq, p.tolist(), int(p.size) + f.max_new_tokens)
            except BlockPoolExhausted as e:
                with self._lock:
                    self._free.append(idx)
                hook = self._exhausted_hook
                if hook is not None:
                    # forensics BEFORE the caller sees the failure: the
                    # dump must be on disk even if the shed cascades
                    try:
                        hook(e)
                    except Exception:
                        log.exception("exhausted_hook failed")
                f._stream.put(e)
                f._stream.put(None)
                f.set_exception(e)
                continue
            f.prefix_hit_tokens = cached
            self._hits_delta += cached // self.block_size
            self._hit_tokens_delta += cached
            self._prompt_tokens_delta += int(p.size)
            self._slots[idx] = _PagedSlot(f, p, seq, cached, seed)

    def _sampling_rows(self, n):
        return (np.zeros((n,), np.float32), np.zeros((n,), np.int32),
                np.ones((n,), np.float32), np.zeros((n,), np.int32))

    @staticmethod
    def _fill_sampling(arrs, r, slot):
        sp = slot.fut.sampling
        if sp is None or sp.greedy:
            return
        temp, top_k, top_p, seed = arrs
        temp[r] = sp.temperature
        top_k[r] = sp.top_k
        top_p[r] = sp.top_p
        seed[r] = slot.seed

    def _run_decode(self, qdepth):
        """One dispatcher iteration of device work: at most ONE prefill
        chunk per currently-prefilling sequence, then one decode tick
        over every decoding slot -- the interleave that keeps chunked
        prefill from starving live streams."""
        if any(s.prefilling for _i, s in self._active()):
            self._run_chunk_tick(qdepth)
        if any(not s.prefilling for _i, s in self._active()):
            self._run_decode_tick(qdepth)

    def _run_chunk_tick(self, qdepth):
        t0 = time.perf_counter()
        execs_before = self._compiles()
        rows = [(i, s) for i, s in self._active() if s.prefilling]
        n = len(rows)
        bucket = self.batch_ladder.bucket_for(n) or self.batch_ladder.add(n)
        tc = self.prefill_chunk
        mb = self.max_blocks_per_seq
        tokens = np.zeros((bucket, tc), np.int32)
        start = np.zeros((bucket,), np.int32)
        lens = np.zeros((bucket,), np.int32)
        tables = np.full((bucket, mb), self._alloc.trash, np.int32)
        knobs = self._sampling_rows(bucket)
        for r, (i, s) in enumerate(rows):
            chunk = s.prompt[s.consumed:s.consumed + tc]
            tokens[r, :chunk.size] = chunk
            start[r] = s.consumed
            lens[r] = chunk.size
            self._cow_guard(s, s.consumed, s.consumed + chunk.size - 1)
            tables[r] = self._alloc.table_row(s.seq, mb)
            self._fill_sampling(knobs, r, s)
        try:
            with span("generate_prefill", tick=self._tick, records=n):
                first, self._cache = self._chunk_fn(
                    self._params(), self._cache, tokens, start, lens,
                    tables, *knobs)
                first = np.asarray(first)            # host sync
                self._mirror_chunk(tokens, start, lens, tables, knobs)
        except Exception as e:
            log.exception("chunk prefill tick failed (%d prompts)", n)
            self._tick_failed(e, [], [])
            return
        done_lat = []
        emitted = 0
        for r, (i, s) in enumerate(rows):
            s.consumed += int(lens[r])
            # full prompt blocks now hold real K/V: register their
            # hashes so later admissions can share them
            self._alloc.commit_full_blocks(s.seq, s.consumed)
            if not s.prefilling:                     # prompt complete
                s.last = int(first[r])
                s.tokens = [s.last]
                s.pos = int(s.prompt.size)
                emitted += 1
                self._deliver(i, s, done_lat)
        self._tick += 1
        self._record_tick("prefill", t0, records=n, tokens=emitted,
                          bucket=int(bucket), prompt_bucket=tc,
                          qdepth=qdepth, execs_before=execs_before,
                          latencies=done_lat,
                          riders=[s.fut for _i, s in rows],
                          extra=self._kv_extra())

    def _run_decode_tick(self, qdepth):
        t0 = time.perf_counter()
        execs_before = self._compiles()
        s_n = self.slots
        mb = self.max_blocks_per_seq
        tokens = np.zeros((s_n,), np.int32)
        pos = np.zeros((s_n,), np.int32)
        tables = np.full((s_n, mb), self._alloc.trash, np.int32)
        knobs = self._sampling_rows(s_n)
        active = [(i, s) for i, s in self._active() if not s.prefilling]
        for i, s in active:
            self._cow_guard(s, s.pos, s.pos)
            tokens[i] = s.last
            pos[i] = s.pos
            tables[i] = self._alloc.table_row(s.seq, mb)
            self._fill_sampling(knobs, i, s)
        try:
            with span("generate_decode", tick=self._tick,
                      records=len(active)):
                nxt, self._cache = self._decode_fn(
                    self._params(), self._cache, tokens, pos, tables,
                    *knobs)
                nxt = np.asarray(nxt)                # host sync
        except Exception as e:
            log.exception("decode tick failed (%d slots)", len(active))
            self._tick_failed(e, [], [])
            return
        done_lat = []
        for i, s in active:
            s.pos += 1
            s.last = int(nxt[i])
            s.tokens.append(s.last)
            self._deliver(i, s, done_lat)
        self._tick += 1
        self._record_tick("decode", t0, records=0, tokens=len(active),
                          qdepth=qdepth, execs_before=execs_before,
                          latencies=done_lat, slots_before=len(active),
                          riders=[s.fut for _i, s in active],
                          extra=self._kv_extra())

    def _mirror_chunk(self, tokens, start, lens, tables, knobs):
        """Hook for a twin cache that must see every prompt chunk:
        no-op here; the speculative subclass replays the chunk through
        its drafter pool so draft decoding starts from a prefilled
        drafter context."""

    def _cow_guard(self, slot, first_pos, last_pos):
        """Copy-on-write check over the blocks a write will touch.  By
        construction writes only land in private blocks (prefix
        matching is capped below the last prompt token), so this
        normally just unregisters a block that was about to be shared;
        if a shared block IS about to be written, the sequence detaches
        onto a fresh copy first -- a refcount bug corrupts nobody."""
        bs = self.block_size
        for b in range(int(first_pos) // bs, int(last_pos) // bs + 1):
            cow = self._alloc.ensure_writable(slot.seq, b * bs)
            if cow is not None:
                src, dst = cow
                self._copy_cow_block(src, dst)

    def _copy_cow_block(self, src, dst):
        """Duplicate physical block ``src`` into ``dst`` (the
        speculative subclass also copies the drafter pool: the shared
        allocator's table move covers BOTH pools, so both must carry
        the content across)."""
        self._cache = self._copy_fn(self._cache, np.int32(src),
                                    np.int32(dst))


def speculative_verify_step(model, cache_dtype, k: int):
    """The jitted VERIFY step for speculative decoding, compiled once
    per (model, cache dtype, k) and cached on the instance.

    ``verify(params, pool, last (S,), drafts (k arrays of (S,)), pos
    (S,), tables (S, MB), temperature, top_k, top_p, seed (each (S,)))
    -> (sampled (S, k+1), new_pool)``: row ``i`` feeds ``[last,
    d_1 .. d_k]`` -- the newest
    committed token plus the drafter's k guesses -- at positions
    ``pos .. pos+k`` through the chunk-prefill path (every position's
    K/V scattered, every position's logits returned), then samples a
    token at EVERY position ``pos+1 .. pos+k+1`` with the same
    ``(seed, position)``-pure sampler plain decode uses.  Column ``j``
    of the result is therefore EXACTLY the token one fp32 decode tick
    would have drawn at position ``pos+j+1`` given the fed prefix --
    the property that makes greedy (and seeded-sampling) speculative
    output bit-identical to verifier-only decoding.  Donates the pool.
    """
    from bigdl_tpu.serving.sampling import sample_tokens

    cache = model.__dict__.setdefault("_compiled_spec_steps", {})
    key = (np.dtype(cache_dtype).name, int(k))
    fn = cache.get(key)
    if fn is not None:
        return fn

    def verify(params, pool, last, drafts, pos, tables, temperature,
               top_k, top_p, seed):
        # assemble [last, d_1 .. d_k] IN-JIT: the tick then issues no
        # bare jnp glue ops, so the executable set after precompile()
        # is exactly the warmed one (the zero-recompile contract)
        tokens = jnp.concatenate(
            [last[:, None]] + [d[:, None] for d in drafts], axis=1)
        k1 = tokens.shape[1]
        logits, new = model.apply_paged(
            params, tokens, pool, tables, pos=pos,
            lengths=jnp.full_like(pos, k1))
        flat = logits.reshape((-1, logits.shape[-1]))
        positions = (pos[:, None] + 1
                     + jnp.arange(k1, dtype=jnp.int32)[None, :])

        def rep(a):
            return jnp.repeat(a, k1)

        sampled = sample_tokens(flat, rep(temperature), rep(top_k),
                                rep(top_p), rep(seed),
                                positions.reshape(-1))
        return sampled.reshape(tokens.shape), new

    fn = jax.jit(verify, donate_argnums=(1,))
    cache[key] = fn
    return fn


class SpeculativeScheduler(PagedGenerateScheduler):
    """Draft/verify decoding over the paged pool: per round, the int8
    TWIN (``quantize_model``'s structural copy, PR 10 -- gated into
    serving by the same ``AccuracyDeltaGate`` evidence) drafts
    ``spec_k`` tokens with cheap sequential decode steps, and the fp32
    verifier scores ALL of them in ONE chunk-shaped forward.  The
    longest prefix of drafts that matches what the verifier itself
    would have sampled is accepted, plus the verifier's own next token
    (the correction on a miss, the bonus on a clean sweep) -- so one
    fp32 forward emits between 1 and ``spec_k + 1`` tokens, and the
    stream is EXACTLY the verifier-only stream (greedy bit-identical;
    seeded sampling replay-stable, because acceptance compares against
    the ``(seed, position)``-pure draw the verifier would have made).

    Cache story: the drafter runs against its OWN device pool, but the
    two pools share ONE ``BlockAllocator`` -- same geometry, same
    block tables, so prefix hits, COW detaches and LRU evictions stay
    single-sourced (a COW copies the block in BOTH pools; every prompt
    chunk is mirrored into the drafter pool via ``_mirror_chunk``).
    Rejection needs no explicit rollback: a rejected draft's K/V sits
    BEYOND the committed frontier, causally masked until the next
    round's scatter overwrites it (writes precede reads in the
    compiled steps), and ``_cow_guard`` runs over the whole
    ``pos .. pos+k`` write span first so shared blocks detach before
    any speculative write lands.  Block tables carry
    ``ceil((spec_k+1)/block_size)`` extra trash-padded entries so a
    round straddling a sequence's reserved range routes its overshoot
    writes to the trash block instead of clamping into a live one.

    The executable set stays closed: the drafter's decode + chunk
    rungs + copy, the one ``spec_verify`` shape, and the inherited
    verifier set -- zero steady-state recompiles (pinned in
    tests/test_speculative.py).
    """

    def __init__(self, model, draft_model, spec_k: int = 4,
                 draft_params_fn=None, **kw):
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if not hasattr(draft_model, "init_paged_cache"):
            raise TypeError(
                f"{type(draft_model).__name__} has no init_paged_cache():"
                f" the drafter must run the same paged decode mode as "
                f"the verifier")
        self.spec_k = int(spec_k)
        self.draft_model = draft_model
        self._dparams = draft_params_fn or \
            (lambda: draft_model.parameters()[0])
        self._spec_rounds = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        super().__init__(model, **kw)
        # widen every table row so verify's pos..pos+k write span can
        # overshoot a finishing sequence's reserved blocks: the extra
        # entries are trash-padded, turning overshoot into trash-block
        # writes rather than an index clamp into a neighbour's block
        self.max_blocks_per_seq += -(-(self.spec_k + 1) // self.block_size)

    def _setup_steps(self):
        super()._setup_steps()
        self._dchunk_fn, self._ddecode_fn, self._dcopy_fn = \
            paged_generate_steps(self.draft_model, self._cache_dtype)
        self._verify_fn = speculative_verify_step(
            self.model, self._cache_dtype, self.spec_k)
        self._build_drafter_pool()

    def _build_drafter_pool(self):
        self._dcache = self.draft_model.init_paged_cache(
            self.num_blocks, self.block_size, self._cache_dtype)
        # one addressable block is backed by BOTH pools' leaves; the
        # allocator's byte report must say so or the ledger understates
        # the speculative price by half
        dbytes = sum(leaf.size * leaf.dtype.itemsize
                     for leaf in jax.tree.leaves(self._dcache))
        self._alloc.bytes_per_block += int(dbytes) // (self.num_blocks + 1)

    def _reset_pool(self):
        super()._reset_pool()
        self._build_drafter_pool()

    def cache_bytes(self) -> int:
        """Verifier pool + drafter pool -- the speculative price is
        BOTH pools resident, and hiding the drafter's share would
        falsify the bench's peak-bytes comparison."""
        return super().cache_bytes() + int(sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(self._dcache)))

    def stats(self):
        st = super().stats()
        drafted = self._spec_drafted
        st["speculative"] = {
            "k": self.spec_k, "rounds": self._spec_rounds,
            "drafted": drafted, "accepted": self._spec_accepted,
            "acceptance_rate": (self._spec_accepted / drafted)
            if drafted else None}
        return st

    def _mirror_chunk(self, tokens, start, lens, tables, knobs):
        """Replay the verifier's prompt chunk through the drafter pool
        (same tables -- the allocator is shared), so by the time a
        sequence flips to decoding, the drafter has its own K/V for
        every prompt position.  Runs inside the chunk tick's try:
        a drafter failure is a pool loss like any other donating-step
        failure, and ``_reset_pool`` rebuilds both pools."""
        first, self._dcache = self._dchunk_fn(
            self._dparams(), self._dcache, tokens, start, lens, tables,
            *knobs)
        jax.block_until_ready(first)      # surface errors in-tick

    def _copy_cow_block(self, src, dst):
        super()._copy_cow_block(src, dst)
        self._dcache = self._dcopy_fn(self._dcache, np.int32(src),
                                      np.int32(dst))

    # ----- warmup ----------------------------------------------------------- #
    def precompile(self) -> int:
        """Warm the inherited verifier set plus the speculative
        additions: drafter decode/chunk-rungs/copy and the one verify
        shape.  Dummy pools only, as in the base."""
        from bigdl_tpu.observability.watchdogs import backend_compile_count

        before = backend_compile_count()
        super().precompile()
        dparams = self._dparams()
        s = self.slots
        mb = self.max_blocks_per_seq
        trash = np.int32(self._alloc.trash)
        tabs = np.full((s, mb), trash, np.int32)
        knobs = self._sampling_rows(s)
        ddummy = jax.tree.map(jnp.zeros_like, self._dcache)
        nxt, ddummy = self._ddecode_fn(
            dparams, ddummy, np.zeros((s,), np.int32),
            np.zeros((s,), np.int32), tabs, *knobs)
        jax.block_until_ready(nxt)
        tc = self.prefill_chunk
        for b in self.batch_ladder:
            b = int(b)
            first, ddummy = self._dchunk_fn(
                dparams, ddummy, np.zeros((b, tc), np.int32),
                np.zeros((b,), np.int32), np.ones((b,), np.int32),
                np.full((b, mb), trash, np.int32),
                *self._sampling_rows(b))
            jax.block_until_ready(first)
        ddummy = self._dcopy_fn(ddummy, np.int32(0), np.int32(0))
        jax.block_until_ready(jax.tree.leaves(ddummy)[0])
        vdummy = jax.tree.map(jnp.zeros_like, self._cache)
        vt, vdummy = self._verify_fn(
            self._params(), vdummy, np.zeros((s,), np.int32),
            tuple(np.zeros((s,), np.int32)
                  for _ in range(self.spec_k)),
            np.zeros((s,), np.int32), tabs, *knobs)
        jax.block_until_ready(vt)
        return backend_compile_count() - before

    # ----- the speculative round --------------------------------------------- #
    def _run_decode_tick(self, qdepth):
        """One draft/verify round over every decoding slot:

        1. ``spec_k + 1`` drafter decode steps -- the first ``spec_k``
           produce the draft tokens ``d_1 .. d_k`` (each fed back in),
           the final one only WRITES ``d_k``'s K/V so the drafter pool
           covers the same ``pos .. pos+k`` span the verifier writes
           (without it, a clean-sweep round would leave the last
           accepted draft's position forever unwritten in the drafter
           pool, and later drafter reads would attend to garbage).
        2. One fp32 verify over ``[last, d_1 .. d_k]`` sampling every
           position.
        3. Accept the longest matching draft prefix + the verifier's
           next token; stream them through the normal ``_deliver``
           path (EOS / token budget truncate the run mid-emission).
        """
        t0 = time.perf_counter()
        execs_before = self._compiles()
        s_n = self.slots
        k = self.spec_k
        mb = self.max_blocks_per_seq
        tokens = np.zeros((s_n,), np.int32)
        pos = np.zeros((s_n,), np.int32)
        tables = np.full((s_n, mb), self._alloc.trash, np.int32)
        knobs = self._sampling_rows(s_n)
        active = [(i, s) for i, s in self._active() if not s.prefilling]
        for i, s in active:
            # COW the WHOLE write span up front, clamped to the
            # sequence's reserved range (overshoot writes go to trash
            # via the widened table padding, no block to detach there)
            hi = min(s.pos + k,
                     int(s.prompt.size) + s.fut.max_new_tokens - 1)
            self._cow_guard(s, s.pos, max(s.pos, hi))
            tokens[i] = s.last
            pos[i] = s.pos
            tables[i] = self._alloc.table_row(s.seq, mb)
            self._fill_sampling(knobs, i, s)
        try:
            with span("generate_decode", tick=self._tick,
                      records=len(active)):
                drafts = []
                cur = tokens
                for j in range(k + 1):
                    cur, self._dcache = self._ddecode_fn(
                        self._dparams(), self._dcache, cur, pos + j,
                        tables, *knobs)
                    if j < k:
                        drafts.append(cur)
                vtoks, self._cache = self._verify_fn(
                    self._params(), self._cache, tokens, tuple(drafts),
                    pos, tables, *knobs)
                dtoks = np.stack([np.asarray(d) for d in drafts],
                                 axis=1)                    # host sync
                vtoks = np.asarray(vtoks)
        except Exception as e:
            log.exception("speculative tick failed (%d slots)",
                          len(active))
            self._tick_failed(e, [], [])
            return
        done_lat = []
        emitted = 0
        drafted = accepted = 0
        for i, s in active:
            drafted += k
            a = 0
            while a < k and int(dtoks[i, a]) == int(vtoks[i, a]):
                a += 1
            accepted += a
            # vtoks[i, :a] == the accepted drafts; vtoks[i, a] is the
            # verifier's own next token (correction or bonus)
            for j in range(a + 1):
                s.pos += 1
                s.last = int(vtoks[i, j])
                s.tokens.append(s.last)
                emitted += 1
                self._deliver(i, s, done_lat)
                if s.fut.done():            # EOS / budget mid-run
                    break
        self._spec_rounds += 1
        self._spec_drafted += drafted
        self._spec_accepted += accepted
        extra = self._kv_extra()
        extra["spec_k"] = k
        extra["spec_drafted"] = drafted
        extra["spec_accepted"] = accepted
        self._tick += 1
        self._record_tick("decode", t0, records=0, tokens=emitted,
                          qdepth=qdepth, execs_before=execs_before,
                          latencies=done_lat, slots_before=len(active),
                          riders=[s.fut for _i, s in active],
                          extra=extra)
