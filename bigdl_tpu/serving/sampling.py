"""In-jit token sampling: temperature / top-k / top-p drawn INSIDE the
compiled decode step.

The contiguous scheduler (PR 15) only ever argmaxes, which kept the
decode step pure but locks serving to greedy output.  The obvious
extension -- ship logits to the host and sample there -- adds a
device->host round-trip of ``(slots, vocab)`` floats per generated
token, exactly the transfer the decode path was built to avoid.
Instead sampling runs inside the jitted step:

- every slot carries its sampling knobs as RUNTIME ARRAYS (temperature,
  top_k, top_p, seed -- one row each), so greedy and sampled slots
  share one executable and changing knobs never recompiles;
- randomness is ``fold_in(PRNGKey(seed), position)`` per row: the draw
  for the token at sequence position ``p`` depends only on (seed, p),
  so a given (seed, prompt) replays the same stream regardless of which
  slot it lands in, how prefill was chunked, or what its neighbours do
  -- deterministic replay is what makes fleet retries idempotent;
- the draw itself is Gumbel-max over the masked, temperature-scaled
  logits (argmax(logits/T + gumbel) samples the softmax exactly), which
  needs no normalization and no host sync.

``temperature <= 0`` means greedy -- the whole masking/gumbel result is
discarded for those rows, so the default path is bit-identical to the
old argmax.
"""

import jax
import jax.numpy as jnp


class SamplingParams:
    """Per-request sampling knobs, validated once at submission.

    ``temperature <= 0`` is greedy (top_k/top_p ignored); ``top_k <= 0``
    disables the k-cut; ``top_p`` keeps the smallest set of tokens whose
    probability mass reaches it (``1.0`` disables, ``0.0`` degenerates
    to greedy-at-temperature).  ``seed=None`` asks the scheduler to mint
    one -- pass an explicit seed for deterministic replay.
    """

    __slots__ = ("temperature", "top_k", "top_p", "seed")

    def __init__(self, temperature=0.0, top_k=0, top_p=1.0, seed=None):
        temperature = float(temperature)
        top_k = int(top_k)
        top_p = float(top_p)
        if not temperature == temperature:            # NaN
            raise ValueError("temperature must not be NaN")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if not 0.0 <= top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {top_p}")
        if seed is not None:
            seed = int(seed)
            if not 0 <= seed < 2 ** 31:
                raise ValueError(f"seed must fit in 31 bits, got {seed}")
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = seed

    @property
    def greedy(self):
        return self.temperature <= 0.0

    def __repr__(self):
        return (f"SamplingParams(temperature={self.temperature}, "
                f"top_k={self.top_k}, top_p={self.top_p}, "
                f"seed={self.seed})")


GREEDY = SamplingParams()


def sample_tokens(logits, temperature, top_k, top_p, seed, position):
    """Draw one token per row from ``logits`` -- traceable, fixed-shape.

    logits       (rows, vocab) float
    temperature  (rows,) float; <= 0 selects greedy for that row
    top_k        (rows,) int32; <= 0 disables
    top_p        (rows,) float in [0, 1]
    seed         (rows,) int32/uint32 per-request RNG seed
    position     (rows,) int32 sequence position of the token being
                 drawn -- the fold-in counter, so the draw is a pure
                 function of (seed, position)

    Returns (rows,) int32 token ids.
    """
    vocab = logits.shape[-1]
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Work in sorted order (descending): top-k is a rank cut and top-p a
    # cumulative-mass cut over the same sort.
    order = jnp.argsort(-logits, axis=-1)
    ranked = jnp.take_along_axis(logits, order, axis=-1)
    temp = jnp.maximum(temperature, 1e-6).astype(jnp.float32)[:, None]
    scaled = ranked / temp

    rank = jnp.arange(vocab, dtype=jnp.int32)[None, :]
    k = jnp.where(top_k > 0, top_k, vocab).astype(jnp.int32)[:, None]
    keep = rank < k
    probs = jax.nn.softmax(scaled, axis=-1)
    # keep a token iff the mass STRICTLY BEFORE it is < top_p: the
    # smallest prefix reaching top_p survives, and rank 0 always does
    # (mass-before is 0), so top_p=0.0 degenerates to argmax not to an
    # empty support
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    keep = keep & (mass_before < top_p[:, None])
    keep = keep.at[:, 0].set(True)

    masked = jnp.where(keep, scaled, -jnp.inf)
    # Gumbel-max: argmax(masked + G) ~ softmax(masked).  One fold_in per
    # row keyed purely on (seed, position).
    keys = jax.vmap(
        lambda s, p: jax.random.fold_in(
            jax.random.PRNGKey(s.astype(jnp.uint32)), p))(
        seed, position.astype(jnp.uint32))
    gumbel = jax.vmap(lambda key, row: jax.random.gumbel(
        key, row.shape, dtype=row.dtype))(keys, masked)
    pick = jnp.argmax(masked + gumbel, axis=-1)
    sampled = jnp.take_along_axis(
        order, pick[:, None], axis=-1)[:, 0].astype(jnp.int32)

    return jnp.where(temperature > 0.0, sampled, greedy)
