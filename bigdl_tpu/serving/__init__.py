"""High-throughput inference serving (the TPU-native redesign of the
reference's ``optim/PredictionService.scala`` instance pool).

- ``ServingEngine`` -- request coalescing behind a bounded queue with a
  ``max_batch_size`` / ``max_wait_ms`` deadline policy, bucketed shape
  padding (closed executable set, ``precompile()`` warms it), and
  sharded multi-device predict over a mesh's data axis with host-side
  round-robin as the fallback.
- ``BucketLadder`` -- the shape ladder (batch and, for sequence
  models, length buckets).
- ``ServingEngine(quantize=True, accuracy_gate=...)`` -- the int8
  serving path: the model's post-training-quantized twin serves on the
  same machinery, fp32 checkpoints quantize at ``refresh_params`` swap
  time, and an ``optim.validation.AccuracyDeltaGate`` rejects swaps
  whose fp32-vs-int8 divergence exceeds tolerance.

- ``ServingEngine.generate()`` (``serving/generation.py``) --
  autoregressive generation: KV-cache prefill/decode steps compiled
  once (cache donated in place), a slot-based continuous-batching
  scheduler (sequences join/leave a fixed decode-slot pool mid-flight
  with zero steady-state recompiles), per-request
  ``max_new_tokens``/EOS stops, and streaming ``GenerateFuture``
  handles that yield tokens as decode ticks complete.
- ``PagedGenerateScheduler`` / ``BlockAllocator``
  (``serving/generation.py`` + ``serving/paging.py``) -- the paged KV
  cache: a fixed device block pool with host-side block tables,
  refcounted prefix sharing (content-hashed full blocks, LRU-cached,
  copy-on-write on divergence), chunked prefill interleaved with
  decode ticks, typed ``BlockPoolExhausted`` admission sheds, and
  in-jit ``SamplingParams`` temperature/top-k/top-p sampling
  (``serving/sampling.py``).  The engine default
  (``kv_cache="paged"``); ``kv_cache="contiguous"`` keeps the flat
  pool as the A/B baseline.
- ``ModelRegistry`` / ``RolloutController`` (``serving/deploy.py``) --
  the train->serve loop closed: versioned hot-swap with shadow/canary
  staged exposure, atomic cutover, automatic rollback to the retained
  previous version, durable ``kind: "deploy"`` audit events.
- ``ServingFleet`` (``serving/fleet.py``) -- N replicas (in-process
  engines and/or ``serving/worker.py`` subprocess workers) behind
  health-aware least-loaded routing with per-replica circuit breakers,
  deadline-budgeted retries, tail-latency hedging and load shedding;
  ``FleetSupervisor`` restarts dead workers from the registry's
  committed version, and the ``RolloutController`` performs ROLLING
  deploys across a fleet (drain -> gate -> commit -> undrain, one
  replica at a time).
- ``serving/transport.py`` -- the fleet's binary wire: versioned
  magic+type+length frames with typed refusals, zero-copy tensor
  frames (``np.frombuffer`` on receive, no array transits pickle),
  persistent ``WirePool`` connections with request-id multiplexing, a
  digest-authed handshake (``BIGDL_RUN_TOKEN``), and blockwise-int8
  weight distribution (``quantize_tree_for_wire``) for staging
  traffic.  The PR 14 pickle wire survives behind
  ``transport="pickle"``.

See docs/performance.md ("Inference serving", "Int8 inference",
"Fleet transport"),
docs/robustness.md ("Continuous deployment", "Serving fleets") and
docs/observability.md (extended ``kind: "inference"`` event schema,
serving-precision + version header stamp, the ``deploy``/``fleet``
event schemas).
"""

from bigdl_tpu.serving.buckets import BucketLadder
from bigdl_tpu.serving.deploy import (ModelRegistry, ModelVersion,
                                      RolloutController, snapshot_digest)
from bigdl_tpu.serving.engine import (EngineDraining, ServeFuture,
                                      ServingEngine)
from bigdl_tpu.serving.fleet import (CircuitBreaker, FleetOverloadedError,
                                     FleetSupervisor,
                                     FleetUnavailableError,
                                     InProcessReplica, ServingFleet,
                                     SubprocessReplica)
from bigdl_tpu.serving.generation import (GenerateFuture,
                                          GenerateScheduler,
                                          PagedGenerateScheduler)
from bigdl_tpu.serving.paging import BlockAllocator, BlockPoolExhausted
from bigdl_tpu.serving.sampling import SamplingParams
from bigdl_tpu.serving.transport import (ReplicaCallError, WireAuthError,
                                         WireClient, WireError,
                                         WireFrameError, WirePool,
                                         WireProtocolError,
                                         WireVersionError)

__all__ = ["BlockAllocator", "BlockPoolExhausted", "BucketLadder",
           "CircuitBreaker", "EngineDraining", "FleetOverloadedError",
           "FleetSupervisor", "FleetUnavailableError", "GenerateFuture",
           "GenerateScheduler", "InProcessReplica", "ModelRegistry",
           "ModelVersion", "PagedGenerateScheduler", "ReplicaCallError",
           "RolloutController", "SamplingParams", "ServeFuture",
           "ServingEngine", "ServingFleet", "SubprocessReplica",
           "WireAuthError", "WireClient", "WireError", "WireFrameError",
           "WirePool", "WireProtocolError", "WireVersionError",
           "snapshot_digest"]
