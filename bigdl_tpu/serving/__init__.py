"""High-throughput inference serving (the TPU-native redesign of the
reference's ``optim/PredictionService.scala`` instance pool).

- ``ServingEngine`` -- request coalescing behind a bounded queue with a
  ``max_batch_size`` / ``max_wait_ms`` deadline policy, bucketed shape
  padding (closed executable set, ``precompile()`` warms it), and
  sharded multi-device predict over a mesh's data axis with host-side
  round-robin as the fallback.
- ``BucketLadder`` -- the shape ladder (batch and, for sequence
  models, length buckets).

See docs/performance.md ("Inference serving") and docs/observability.md
(extended ``kind: "inference"`` event schema).
"""

from bigdl_tpu.serving.buckets import BucketLadder
from bigdl_tpu.serving.engine import ServeFuture, ServingEngine

__all__ = ["BucketLadder", "ServeFuture", "ServingEngine"]
