"""Subprocess serving replica: a ``ServingEngine`` behind a small
length-prefixed socket protocol.

A fleet replica that lives in its own PROCESS is a real failure domain:
a crash is a process death the supervisor observes (SIGKILL included),
not an exception a try/except can paper over -- the explicit rebuild of
the worker-failure tolerance BigDL inherited from Spark task
re-execution (arxiv 1804.05839 section 3).  ``ReplicaServer`` wraps one
engine; ``serving/fleet.py``'s ``SubprocessReplica`` is the client side
and ``tools/serve_fleet.py`` the CLI that spawns workers.

Transport: the DEFAULT wire is the versioned binary frame protocol in
``serving/transport.py`` -- persistent multiplexed connections, a
digest-authed handshake (``BIGDL_RUN_TOKEN``), zero-copy tensor
frames, typed refusals for oversize/foreign/truncated frames
(docs/performance.md, "Fleet transport").  The PR 14 length-prefixed
pickle wire is kept one release behind ``transport="pickle"`` as an
escape hatch: each message is a 4-byte big-endian length followed by a
pickled payload, one fresh loopback connection per request, trusted
peer assumed.  Requests are ``{"op": ..., **kwargs}`` on either wire;
responses ``{"ok": True, "result": ...}`` or ``{"ok": False, "error":
..., "error_type": ...}``.  Ops:

- ``predict``  {feature, timeout, trace?} -> output tree (numpy
  leaves).  ``trace`` is the OPTIONAL versioned request-trace context
  (``{"v": 1, "traceparent": ...}``, docs/observability.md "Request
  tracing") -- absent from traceless clients and ignored by older
  workers, so the field is backward-compatible in both directions
- ``generate`` {prompt, max_new_tokens, eos_id, timeout, trace?,
  temperature?, top_k?, top_p?, seed?} -> generated
  token-id list (the engine's continuous-batching decode slots;
  tokens stream WITHIN the worker, the socket answers once the
  sequence finishes -- per-token streaming over this one-shot
  framing would need a protocol change).  The sampling knobs are
  optional and default to greedy, so traceless/greedy clients and
  older workers interoperate unchanged; ``seed`` rides the wire so a
  fleet retry REPLAYS the same stream on a sibling replica
- ``probe``    {features, bucket}   -> sha256 digest of the unbatched
  reference outputs (``predict_at``) -- the bit-for-bit serving
  fingerprint the rejoin drill compares across processes
- ``health``   {}                   -> {status, draining, version,
  stats, pid}
- ``drain``    {timeout} / ``undrain`` {}
- ``capture``  {}                   -> token for the LIVE weights
- ``stage``    {path}               -> token for a snapshot staged
  beside the serving weights (nothing committed)
- ``stage_tree`` {params, mstate?, weight_wire?, wire_bytes?} -> token:
  in-memory weights shipped OVER the wire (binary transport; arrays
  ride as raw tensor frames, optionally blockwise-int8 via
  ``transport.quantize_tree_for_wire`` -- the worker dequantizes
  before staging, and the measured ``wire_bytes`` lands on the
  ``param_refresh`` audit event at commit)
- ``gate``     {token}              -> (ok, reason): the staged
  candidate evaluated on the worker's probe batch, outputs must be
  finite
- ``commit``   {token, version, digest} -- the atomic pointer swap
- ``release``  {token} / ``set_version`` {version, digest} / ``stop``

Deploy verbs run under one server-side lock (they mutate staging
state); predict traffic is served concurrently by the threading server
and stays lock-free.

No jax at module top: the FRAMING half (``send_msg``/``recv_msg``) is
imported by the fleet router, which may live in a supervisor process
with no accelerator.
"""

import hashlib
import logging
import os
import pickle
import socket
import socketserver
import struct
import threading

from bigdl_tpu.observability.tracing import TraceContext
from bigdl_tpu.serving.transport import (ReplicaCallError, WireFrameError,
                                         run_token, serve_connection)

log = logging.getLogger("bigdl_tpu.serving")

#: refuse absurd frames instead of allocating them (a corrupt length
#: prefix must not OOM the worker)
MAX_MESSAGE_BYTES = 1 << 28


def send_msg(sock, obj):
    """One length-prefixed pickled message."""
    data = pickle.dumps(obj)
    if len(data) > MAX_MESSAGE_BYTES:
        raise WireFrameError(f"message of {len(data)} bytes exceeds the "
                             f"{MAX_MESSAGE_BYTES}-byte frame cap")
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-message ({len(buf)}/{n} bytes)")
        buf += chunk
    return buf


def recv_msg(sock):
    """The matching read: length prefix, then exactly that many bytes."""
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    if n > MAX_MESSAGE_BYTES:
        raise WireFrameError(f"frame of {n} bytes exceeds the "
                             f"{MAX_MESSAGE_BYTES}-byte cap "
                             f"(corrupt prefix?)")
    return pickle.loads(_recv_exact(sock, n))


def call(host, port, op, rpc_timeout=30.0, transport="binary",
         auth_token=None, **kwargs):
    """One request/response round trip on a throwaway connection.

    The default rides the binary wire (``transport.call_once``:
    handshake + framed message on a fresh connection -- fleets keep a
    ``WirePool`` instead, this is the tooling/test shape).
    ``transport="pickle"`` keeps the PR 14 length-prefixed pickle wire.
    ``rpc_timeout`` bounds the socket (the payload may carry its own
    engine-level ``timeout`` field).  ``auth_token`` overrides the
    ``BIGDL_RUN_TOKEN`` handshake secret (NOT the staged-handle
    ``token=`` request field, which stays a plain kwarg).  Raises
    ``ReplicaCallError`` when the worker answered an error;
    ``ConnectionError``/``OSError`` when it is unreachable (dead)."""
    if transport == "binary":
        from bigdl_tpu.serving.transport import call_once

        return call_once(host, port, op, rpc_timeout=rpc_timeout,
                         auth_token=auth_token, **kwargs)
    with socket.create_connection((host, int(port)),
                                  timeout=rpc_timeout) as s:
        s.settimeout(rpc_timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_msg(s, {"op": op, **kwargs})
        resp = recv_msg(s)
    if not isinstance(resp, dict) or not resp.get("ok"):
        err = (resp or {}).get("error", "malformed response")
        raise ReplicaCallError(
            f"{op} failed on worker {host}:{port}: {err}",
            error_type=(resp or {}).get("error_type"))
    return resp.get("result")


def gate_staged(engine, handle, probe_features, probe_bucket=None):
    """THE per-replica deploy gate: the staged candidate's outputs on
    the probe batch must be finite on the REAL rows (``[:n]`` -- a
    bucket-padding row's garbage is not the candidate's fault).  One
    implementation shared by ``fleet.InProcessReplica.gate`` and the
    worker's ``gate`` op, so the two replica kinds can never disagree
    about the same candidate."""
    import numpy as np

    import jax

    if probe_features is None:
        return True, "no probe features configured"
    n = len(probe_features)
    bucket = int(probe_bucket) if probe_bucket else \
        (engine.ladder.bucket_for(n) or n)
    x = engine._form_batch(list(probe_features), bucket)
    y = engine.eval_staged(handle, x)
    bad = sum(1 for l in jax.tree.leaves(y)
              if not np.all(np.isfinite(np.asarray(l)[:n])))
    if bad:
        return False, (f"staged candidate produced non-finite outputs "
                       f"on the probe batch ({bad} leaf/leaves)")
    return True, None


def probe_digest(engine, probe_features, bucket):
    """Bit-for-bit serving fingerprint: each probe row through the
    UNBATCHED reference path (``predict_at`` at one fixed bucket, where
    logits are bit-exact), every OUTPUT LEAF hashed (a multi-output
    model returns a tree) -- two processes serving the same committed
    version produce the same digest."""
    import numpy as np

    import jax

    h = hashlib.sha256()
    for r in probe_features:
        for leaf in jax.tree.leaves(engine.predict_at(r, bucket)):
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()[:16]


def boot_from_registry(engine, registry_path):
    """Point a fresh worker at the fleet's COMMITTED version: read the
    durable registry, refuse a digest imposter, stage+commit the live
    version's snapshot.  Returns the served (version, digest), or None
    when the registry has no live snapshot yet (the worker then serves
    its deterministic boot weights, which the baseline version IS)."""
    if registry_path is None or not os.path.exists(str(registry_path)):
        return None
    from bigdl_tpu.serving.deploy import ModelRegistry, snapshot_digest

    reg = ModelRegistry(str(registry_path))
    live = reg.live
    if live is None or live.path is None:
        return None
    digest = snapshot_digest(live.path)
    if live.digest is not None and digest != live.digest:
        raise RuntimeError(
            f"snapshot {live.path} does not match the registry's live "
            f"version v{live.version} (digest {digest} != {live.digest});"
            f" refusing to boot a replica on an imposter")
    engine.refresh_from_snapshot(live.path)
    engine.set_serving_version(live.version, live.digest)
    return live.version, live.digest


class ReplicaServer:
    """One engine served over the socket protocol.

    >>> srv = ReplicaServer(engine, port=0, probe_features=x[:4])
    >>> srv.port                       # the auto-assigned port
    >>> srv.serve_forever()            # or srv.start() for a thread

    ``probe_features`` feed the ``gate`` op (per-replica deploy gate:
    the staged candidate's outputs on this batch must be finite) and
    the ``probe`` digest.  ``max_handles`` bounds the token store so a
    long-lived worker cannot leak staged device buffers (oldest
    released first).

    ``transport="binary"`` (default) serves the versioned frame
    protocol: persistent multiplexed connections, digest-auth
    handshake against ``token`` (default: the ``BIGDL_RUN_TOKEN``
    env; ``token=None`` with no env set handshakes without auth).
    ``transport="pickle"`` keeps the PR 14 one-shot pickle wire."""

    def __init__(self, engine, host="127.0.0.1", port=0,
                 probe_features=None, probe_bucket=None, max_handles=8,
                 transport="binary", token=None, max_frame_bytes=None):
        if transport not in ("binary", "pickle"):
            raise ValueError(f"unknown transport {transport!r}; "
                             f"expected 'binary' or 'pickle'")
        self.engine = engine
        self.transport = transport
        self.token = token if token is not None else run_token()
        self.max_frame_bytes = max_frame_bytes
        self.probe_features = probe_features
        self.probe_bucket = int(probe_bucket) if probe_bucket \
            else (len(probe_features) if probe_features is not None else 1)
        self.max_handles = int(max_handles)
        self._handles = {}
        self._next_token = 0
        self._deploy_lock = threading.Lock()
        server = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                if server.transport == "binary":
                    # handshake + per-connection message loop; each
                    # message dispatches on its own thread
                    serve_connection(self.request,
                                     server._handle_request,
                                     token=server.token,
                                     max_frame_bytes=
                                     server.max_frame_bytes)
                    return
                try:
                    self.request.setsockopt(socket.IPPROTO_TCP,
                                            socket.TCP_NODELAY, 1)
                    req = recv_msg(self.request)
                except Exception:
                    return                     # half-open scanner etc.
                resp = server._handle_request(req)
                try:
                    send_msg(self.request, resp)
                except Exception:
                    pass                       # client hung up

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, int(port)), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = None

    def _handle_request(self, req):
        """One request -> one response envelope; op errors cross the
        wire typed, the worker lives."""
        try:
            return {"ok": True, "result": self._dispatch(req)}
        except Exception as e:
            log.exception("replica op %r failed",
                          req.get("op") if isinstance(req, dict) else req)
            return {"ok": False, "error": str(e)[:500],
                    "error_type": type(e).__name__}

    # ----- op dispatch ------------------------------------------------------- #
    def _dispatch(self, req):
        op = req.get("op")
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            raise ValueError(f"unknown op {op!r}")
        return fn(req)

    def _op_predict(self, req):
        import jax
        import numpy as np

        # optional versioned trace field (docs/observability.md,
        # "Request tracing"): a traceless/older client never sends it,
        # a malformed one parses to None -- both serve untraced
        trace = TraceContext.from_wire(req.get("trace"))
        y = self.engine.predict(req["feature"],
                                timeout=req.get("timeout"),
                                trace=trace)
        return jax.tree.map(np.asarray, y)

    def _op_generate(self, req):
        # ONE budget for the whole call (queue admission and the token
        # wait draw it down together, like engine.predict), and a
        # timed-out request is abandoned: still-pending, it leaves the
        # queue now; already decoding, the scheduler evicts it at the
        # next tick boundary -- either way no decode slot keeps
        # streaming tokens nobody reads while a fleet retry re-runs
        # the prompt on a sibling
        import time
        from concurrent.futures import TimeoutError as FutureTimeoutError

        timeout = req.get("timeout")
        t0 = time.perf_counter()
        fut = self.engine.generate(
            req["prompt"],
            max_new_tokens=int(req.get("max_new_tokens", 16)),
            eos_id=req.get("eos_id"), timeout=timeout,
            temperature=float(req.get("temperature", 0.0)),
            top_k=int(req.get("top_k", 0)),
            top_p=float(req.get("top_p", 1.0)),
            seed=req.get("seed"),
            trace=TraceContext.from_wire(req.get("trace")))
        remaining = None if timeout is None \
            else max(0.0, timeout - (time.perf_counter() - t0))
        try:
            toks = fut.result(remaining)
        except FutureTimeoutError:
            self.engine._abandon(fut)    # frees its generation queue slot
            raise
        return [int(t) for t in toks]

    def _op_probe(self, req):
        feats = req.get("features")
        if feats is None:
            feats = self.probe_features
        if feats is None:
            raise ValueError("no probe features configured on this worker")
        return probe_digest(self.engine, feats,
                            int(req.get("bucket") or self.probe_bucket))

    def _op_health(self, req):
        return {"status": "draining" if self.engine.draining else "ok",
                "draining": self.engine.draining,
                "version": self.engine._version_info,
                "stats": self.engine.stats(),
                "pid": os.getpid()}

    def _op_drain(self, req):
        return self.engine.drain(timeout=req.get("timeout"))

    def _op_undrain(self, req):
        self.engine.undrain()
        return True

    def _op_set_version(self, req):
        self.engine.set_serving_version(req["version"], req.get("digest"))
        return True

    def _put_handle(self, handle):
        self._next_token += 1
        token = f"h{self._next_token}"
        self._handles[token] = handle
        while len(self._handles) > self.max_handles:
            evicted = next(iter(self._handles))
            del self._handles[evicted]
            log.warning("replica handle store full: released oldest "
                        "staged handle %s", evicted)
        return token

    def _op_capture(self, req):
        with self._deploy_lock:
            return self._put_handle(self.engine.capture_staged())

    def _op_stage(self, req):
        from bigdl_tpu.parallel.reshard import read_snapshot_layout
        from bigdl_tpu.serving.engine import ServingEngine

        with self._deploy_lock:
            p = ServingEngine._resolve_snapshot(req["path"])
            src = read_snapshot_layout(p)
            params, mstate = self.engine._load_snapshot_weights(p, src)
            handle = self.engine.stage_weights(params, mstate,
                                               src_layout=src)
            return self._put_handle(handle)

    def _op_stage_tree(self, req):
        # in-memory weights shipped over the wire (binary transport:
        # raw tensor frames, optionally blockwise-int8 -- the client
        # quantized with transport.quantize_tree_for_wire, we invert
        # it here; a plain fp32 tree passes through unchanged)
        from bigdl_tpu.serving.transport import dequantize_wire_tree

        if req.get("src_layout") is not None:
            raise ValueError(
                "stage_tree ships weights already in the serving "
                "layout; resharding snapshots cross as a PATH via the "
                "stage op")
        with self._deploy_lock:
            params = dequantize_wire_tree(req["params"])
            mstate = req.get("mstate")
            if mstate is not None:
                mstate = dequantize_wire_tree(mstate)
            handle = self.engine.stage_weights(params, mstate)
            handle["weight_wire"] = req.get("weight_wire") or "fp32"
            if req.get("wire_bytes") is not None:
                handle["wire_bytes"] = int(req["wire_bytes"])
            return self._put_handle(handle)

    def _handle_of(self, req):
        token = req.get("token")
        handle = self._handles.get(token)
        if handle is None:
            raise KeyError(
                f"unknown staged-handle token {token!r} (released, "
                f"evicted, or from before a worker restart)")
        return handle

    def _op_gate(self, req):
        with self._deploy_lock:
            handle = self._handle_of(req)
            return gate_staged(self.engine, handle, self.probe_features,
                               self.probe_bucket)

    def _op_commit(self, req):
        with self._deploy_lock:
            handle = self._handle_of(req)
            if req.get("wire_bytes") is not None:
                # the CLIENT measured what actually crossed the wire
                # for this staged tree; the commit audit records it
                handle["wire_bytes"] = int(req["wire_bytes"])
                if req.get("weight_wire"):
                    handle["weight_wire"] = req["weight_wire"]
            self.engine.commit_staged(handle, version=req.get("version"),
                                      digest=req.get("digest"))
            return True

    def _op_release(self, req):
        with self._deploy_lock:
            self._handles.pop(req.get("token"), None)
            return True

    def _op_stop(self, req):
        threading.Thread(target=self._server.shutdown,
                         daemon=True).start()
        return True

    # ----- lifecycle --------------------------------------------------------- #
    def start(self):
        """Serve from a daemon thread (the CLI worker uses
        ``serve_forever`` on its main thread instead)."""
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="bigdl-replica-server", daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self._server.serve_forever()

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(5)
