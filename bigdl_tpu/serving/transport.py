"""The fleet's binary wire: versioned frames, zero-copy tensors,
pooled multiplexed connections, digest auth, int8 weight distribution.

The PR 14 worker protocol was a deliberate stopgap: one fresh loopback
TCP connection per request carrying a length prefix and a full
``pickle.dumps`` -- every array crossed the interpreter byte-for-byte
through pickle, every predict paid a connect/teardown, and there was no
handshake, version, auth, or resistance to a hostile peer.  ROADMAP
item 2 calls that out ("pickle over a network is not a production wire
format"); BigDL's premise is cluster-wide execution on commodity
networks (arxiv 1804.05839 section 3).  This module is the transport
seam that turns the loopback process tree into a cross-host-ready
fabric:

**Frame layout** (all integers big-endian)::

    +-------+---------+------------+----------------+-----------------+
    | magic | version | frame type | payload length | payload ...     |
    | 2B BW | 1B      | 1B         | 4B (bounded)   |                 |
    +-------+---------+------------+----------------+-----------------+

Bad magic, a foreign version byte, or a length beyond the frame cap
refuse with a TYPED error (``WireProtocolError`` / ``WireVersionError``
/ ``WireFrameError``) instead of a hung ``recv`` or a 4 GiB
allocation; a peer that closes mid-frame raises legibly with the
byte count it got to.

**Messages** are one ``FT_MSG`` skeleton frame -- a small JSON envelope
``{"id", "nt", "body"}`` where every array in the payload tree has
been replaced by a ``{"__t__": i}`` placeholder -- followed by ``nt``
``FT_TENSOR`` frames, each a tiny dtype/shape JSON header plus the raw
buffer.  The receive side reconstructs arrays with ``np.frombuffer``
over the frame's own buffer: one copy socket->buffer, zero further
copies, and **no array ever transits pickle**.  Non-JSON-able legacy
metadata falls back to a RESTRICTED unpickler (an explicit stdlib
allowlist; anything else -- ``os.system``, arbitrary globals -- is
refused as a protocol error).

**Handshake** (first frames on every connection): the server sends
``FT_HELLO {v, nonce}``; the client answers ``FT_AUTH {v, digest}``
where ``digest = HMAC-SHA256(run_token, nonce)``; the server replies
``FT_OK`` or a typed ``FT_ERR`` (version mismatch, bad token).  The
shared run token rides ``BIGDL_RUN_TOKEN`` (``tools/serve_fleet.py``
mints one per run); a worker with a token configured refuses clients
that cannot present it.  Loopback tests with no token configured skip
the digest check but still handshake, so version/protocol mismatches
always answer typed.

**Connections are persistent and multiplexed**: ``WireClient`` tags
every request with an id, a reader thread matches responses back to
per-request waiters, so many fleet RPC threads share one socket.
``WirePool`` keeps a small capped set of them per replica, evicts
broken connections, and re-dials under the existing
``optim.recovery.capped_backoff``.

**Weight distribution** reuses the PR 4 blockwise-int8 kernels
(``ops/quantization.py``, the EQuARX direction -- arxiv 2506.17615):
``quantize_tree_for_wire`` rewrites each floating leaf into an int8
payload + fp32 per-block scales marker dict, ``dequantize_wire_tree``
reverses it worker-side, and the measured bytes land as honest
``wire_bytes`` on the engine's ``param_refresh`` audit event.

No jax at module top -- the fleet router imports this from processes
with no accelerator; the quantization helpers import jax lazily (both
endpoints of a weight ship run engines).
"""

import base64
import hmac
import hashlib
import io
import json
import logging
import os
import pickle
import secrets
import socket
import struct
import threading
import time

import numpy as np

log = logging.getLogger("bigdl_tpu.serving")

# --------------------------------------------------------------------------- #
# Protocol constants.
# --------------------------------------------------------------------------- #

WIRE_MAGIC = b"BW"
WIRE_VERSION = 1
#: refuse absurd frames instead of allocating them (a corrupt or
#: malicious length must not OOM the process)
MAX_FRAME_BYTES = 1 << 28
#: a message's tensor count is bounded too (the skeleton is parsed
#: before the tensor frames are read)
MAX_TENSORS_PER_MESSAGE = 1 << 16
#: handshake frames are tiny JSON; cap them hard
_HANDSHAKE_FRAME_CAP = 1 << 14

_HEADER = struct.Struct(">2sBBI")

FT_HELLO = 1       # server -> client  {v, nonce, auth}
FT_AUTH = 2        # client -> server  {v, digest}
FT_OK = 3          # server -> client  {v}
FT_MSG = 4         # message skeleton  {id, nt, body}
FT_TENSOR = 5      # dtype/shape header + raw buffer
FT_ERR = 6         # typed wire error  {error, error_type}

#: coalesce buffers smaller than this into one send (TCP_NODELAY means
#: every sendall may flush a packet; headers should ride with payloads)
_COALESCE_BYTES = 1 << 16


def run_token():
    """The shared per-run auth token, if one is configured
    (``BIGDL_RUN_TOKEN``); servers and clients both default to it."""
    tok = os.environ.get("BIGDL_RUN_TOKEN")
    return tok or None


def mint_run_token():
    """A fresh run token for ``BIGDL_RUN_TOKEN`` (the fleet CLI mints
    one per run so restarted workers re-auth against the same secret)."""
    return secrets.token_hex(16)


# --------------------------------------------------------------------------- #
# Typed wire errors.
# --------------------------------------------------------------------------- #


class WireError(RuntimeError):
    """Base of every transport-level failure (never an op-level error:
    those cross as ``{"ok": False, ...}`` responses)."""


class WireProtocolError(WireError, ConnectionError):
    """Malformed stream: bad magic, truncated frame, unexpected frame
    type, refused pickle fallback.  Subclasses ``ConnectionError`` on
    purpose -- a peer speaking garbage is as dead to the router as one
    that hung up."""


class WireVersionError(WireError):
    """The peer speaks a different wire version -- answered as a typed
    error instead of a hung recv, in both directions."""


class WireAuthError(WireError):
    """The client did not present a digest of the shared run token."""


class WireFrameError(WireError, ValueError):
    """A frame exceeds the bounded size (``ValueError`` too, so legacy
    callers of the pickle wire's cap keep their except clauses)."""


_ERROR_TYPES = {
    "WireProtocolError": WireProtocolError,
    "WireVersionError": WireVersionError,
    "WireAuthError": WireAuthError,
    "WireFrameError": WireFrameError,
}


class ReplicaCallError(RuntimeError):
    """The worker answered, but the op failed there (its error text
    rides along) -- distinct from a dead/unreachable worker.
    ``error_type`` carries the worker-side exception's class name so a
    router can recognize typed refusals (e.g. ``EngineDraining``)
    across the socket."""

    def __init__(self, message, error_type=None):
        super().__init__(message)
        self.error_type = error_type


# --------------------------------------------------------------------------- #
# Payload <-> (skeleton, tensors).
# --------------------------------------------------------------------------- #

#: skeleton marker keys; a user dict carrying any of them is shipped as
#: an explicit pair list so markers can never be spoofed by payload data
_RESERVED_KEYS = frozenset(
    {"__t__", "__b__", "__np__", "__py__", "__tup__", "__map__", "__q8__"})

#: the restricted unpickler's entire world: module -> allowed globals.
#: Arrays NEVER take this path (they are split out as tensor frames
#: before the fallback is consulted); this exists only for legacy
#: non-tensor metadata.
_SAFE_PICKLE_GLOBALS = {
    "builtins": {"set", "frozenset", "complex", "bytearray", "slice",
                 "range", "tuple", "list", "dict"},
    "collections": {"OrderedDict", "deque", "defaultdict"},
    "datetime": {"datetime", "date", "time", "timedelta", "timezone"},
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if name in _SAFE_PICKLE_GLOBALS.get(module, ()):
            return super().find_class(module, name)
        raise WireProtocolError(
            f"wire pickle fallback refused {module}.{name}: only "
            f"{sorted(_SAFE_PICKLE_GLOBALS)} metadata may ride the "
            f"legacy path")


def _restricted_loads(data):
    return _RestrictedUnpickler(io.BytesIO(data)).load()


def _is_array(x):
    # numpy arrays and anything array-flavored (jax Arrays) -- but not
    # numpy scalars, which are np.generic and JSON-sized
    if isinstance(x, np.ndarray):
        return True
    return (hasattr(x, "__array__") and hasattr(x, "dtype")
            and hasattr(x, "shape")
            and not isinstance(x, (np.generic, bytes, bytearray, str)))


def encode_payload(obj):
    """-> ``(skeleton, tensors, stats)``: the JSON-able skeleton with
    every array replaced by a ``{"__t__": i}`` placeholder, the arrays
    themselves (contiguous, ready to ship raw), and honesty counters
    (``pickle_fallbacks`` pins the no-arrays-through-pickle claim)."""
    tensors = []
    stats = {"pickle_fallbacks": 0}

    def enc(x):
        if x is None or isinstance(x, (bool, int, float, str)):
            return x
        if _is_array(x):
            a = np.ascontiguousarray(np.asarray(x))
            tensors.append(a)
            return {"__t__": len(tensors) - 1}
        if isinstance(x, np.generic):
            a = np.asarray(x)
            return {"__np__": [str(a.dtype),
                               base64.b64encode(a.tobytes()).decode()]}
        if isinstance(x, (bytes, bytearray)):
            return {"__b__": base64.b64encode(bytes(x)).decode()}
        if isinstance(x, tuple):
            return {"__tup__": [enc(v) for v in x]}
        if isinstance(x, list):
            return [enc(v) for v in x]
        if isinstance(x, dict):
            keys = list(x.keys())
            if all(isinstance(k, str) for k in keys) \
                    and not (_RESERVED_KEYS & set(keys)):
                return {k: enc(v) for k, v in x.items()}
            return {"__map__": [[enc(k), enc(v)] for k, v in x.items()]}
        # legacy metadata only; arrays were already split out above
        stats["pickle_fallbacks"] += 1
        return {"__py__":
                base64.b64encode(pickle.dumps(x)).decode()}

    return enc(obj), tensors, stats


def decode_payload(skeleton, tensors):
    """The inverse of ``encode_payload`` (``tensors`` are the decoded
    tensor-frame arrays, placeholder order)."""

    def dec(x):
        if isinstance(x, list):
            return [dec(v) for v in x]
        if not isinstance(x, dict):
            return x
        if "__t__" in x:
            return tensors[int(x["__t__"])]
        if "__np__" in x:
            dt, b = x["__np__"]
            return np.frombuffer(base64.b64decode(b),
                                 dtype=_dtype_of(dt))[0]
        if "__b__" in x:
            return base64.b64decode(x["__b__"])
        if "__tup__" in x:
            return tuple(dec(v) for v in x["__tup__"])
        if "__map__" in x:
            return {dec(k): dec(v) for k, v in x["__map__"]}
        if "__py__" in x:
            return _restricted_loads(base64.b64decode(x["__py__"]))
        return {k: dec(v) for k, v in x.items()}

    return dec(skeleton)


def _dtype_of(name):
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 and friends register through ml_dtypes
        import ml_dtypes  # noqa: F401

        return np.dtype(name)


def _tensor_frame_parts(a):
    """One tensor as its frame payload parts: ``>I`` header length +
    JSON ``{d, s}`` header + the raw buffer (a no-copy memoryview)."""
    hdr = json.dumps({"d": str(a.dtype), "s": list(a.shape)}).encode()
    if a.nbytes:
        buf = memoryview(a).cast("B")
    else:
        buf = memoryview(b"")
    return [struct.pack(">I", len(hdr)), hdr, buf]


def _decode_tensor(payload):
    """Tensor frame payload -> array: ``np.frombuffer`` over the
    frame's own buffer (writable: the buffer is a fresh bytearray the
    array now owns -- the zero-copy receive contract)."""
    if len(payload) < 4:
        raise WireProtocolError(
            f"tensor frame too short ({len(payload)} bytes)")
    (hl,) = struct.unpack_from(">I", payload, 0)
    if 4 + hl > len(payload):
        raise WireProtocolError(
            f"tensor header claims {hl} bytes, frame has "
            f"{len(payload) - 4}")
    hdr = json.loads(bytes(payload[4:4 + hl]))
    dt = _dtype_of(hdr["d"])
    shape = tuple(int(s) for s in hdr["s"])
    want = int(np.prod(shape, dtype=np.int64)) if shape else 1
    body = memoryview(payload)[4 + hl:]
    if body.nbytes != want * dt.itemsize:
        raise WireProtocolError(
            f"tensor frame carries {body.nbytes} bytes, dtype {dt} "
            f"shape {shape} needs {want * dt.itemsize}")
    return np.frombuffer(body, dtype=dt).reshape(shape)


# --------------------------------------------------------------------------- #
# Raw frame I/O.
# --------------------------------------------------------------------------- #


def _nbytes(b):
    return b.nbytes if isinstance(b, memoryview) else len(b)


def _send_buffers(sock, bufs):
    """Send a buffer list: small parts coalesce into one write, large
    tensor buffers go out as-is (no copy)."""
    small = []
    small_n = 0
    for b in bufs:
        n = _nbytes(b)
        if n <= _COALESCE_BYTES:
            small.append(bytes(b) if isinstance(b, memoryview) else b)
            small_n += n
            if small_n >= _COALESCE_BYTES:
                sock.sendall(b"".join(small))
                small, small_n = [], 0
        else:
            if small:
                sock.sendall(b"".join(small))
                small, small_n = [], 0
            sock.sendall(b)
    if small:
        sock.sendall(b"".join(small))


def _send_frame(sock, ftype, parts, max_frame=MAX_FRAME_BYTES):
    """One frame: header + payload parts.  Returns bytes written."""
    n = sum(_nbytes(p) for p in parts)
    if n > max_frame:
        raise WireFrameError(
            f"outbound frame of {n} bytes exceeds the {max_frame}-byte "
            f"frame cap")
    _send_buffers(sock,
                  [_HEADER.pack(WIRE_MAGIC, WIRE_VERSION, ftype, n),
                   *parts])
    return _HEADER.size + n


def _recv_exact_into(sock, view):
    got = 0
    n = len(view)
    while got < n:
        k = sock.recv_into(view[got:])
        if not k:
            raise WireProtocolError(
                f"peer closed mid-frame ({got}/{n} bytes)")
        got += k


def _recv_frame(sock, max_frame=MAX_FRAME_BYTES):
    """-> ``(ftype, payload bytearray)``.  Refuses bad magic, foreign
    versions, oversize lengths BEFORE allocating the payload."""
    hdr = bytearray(_HEADER.size)
    _recv_exact_into(sock, memoryview(hdr))
    magic, ver, ftype, n = _HEADER.unpack(bytes(hdr))
    if magic != WIRE_MAGIC:
        raise WireProtocolError(
            f"bad frame magic {bytes(magic)!r}: peer is not speaking "
            f"the bigdl wire protocol")
    if ver != WIRE_VERSION:
        raise WireVersionError(
            f"peer speaks wire version {ver}, this end speaks "
            f"{WIRE_VERSION}")
    if n > max_frame:
        raise WireFrameError(
            f"inbound frame of {n} bytes exceeds the {max_frame}-byte "
            f"frame cap (refused before allocation)")
    payload = bytearray(n)
    if n:
        _recv_exact_into(sock, memoryview(payload))
    return ftype, payload


def _send_error(sock, exc, max_frame=MAX_FRAME_BYTES):
    body = json.dumps({"error": str(exc)[:500],
                       "error_type": type(exc).__name__}).encode()
    _send_frame(sock, FT_ERR, [body], max_frame)


def _raise_wire_error(payload):
    try:
        msg = json.loads(bytes(payload))
    except Exception:
        raise WireProtocolError("peer sent an undecodable error frame")
    cls = _ERROR_TYPES.get(str(msg.get("error_type")), WireError)
    raise cls(str(msg.get("error", "peer refused the connection")))


# --------------------------------------------------------------------------- #
# Handshake.
# --------------------------------------------------------------------------- #


def _auth_digest(token, nonce):
    return hmac.new((token or "").encode(), nonce.encode(),
                    hashlib.sha256).hexdigest()


def server_handshake(sock, token=None, max_frame_bytes=None,
                     timeout=10.0):
    """Accept side: HELLO out, AUTH in, OK/typed-ERR out.  Raises the
    typed error it answered with; on success returns a
    ``WireConnection`` ready for messages."""
    max_frame = int(max_frame_bytes or MAX_FRAME_BYTES)
    sock.settimeout(timeout)
    nonce = secrets.token_hex(16)
    _send_frame(sock, FT_HELLO,
                [json.dumps({"v": WIRE_VERSION, "nonce": nonce,
                             "auth": bool(token)}).encode()])
    try:
        ftype, payload = _recv_frame(sock, _HANDSHAKE_FRAME_CAP)
        if ftype != FT_AUTH:
            raise WireProtocolError(
                f"expected AUTH frame, got type {ftype}")
        msg = json.loads(bytes(payload))
        if int(msg.get("v", -1)) != WIRE_VERSION:
            raise WireVersionError(
                f"client speaks wire version {msg.get('v')}, this "
                f"worker speaks {WIRE_VERSION}")
        if token:
            want = _auth_digest(token, nonce)
            got = str(msg.get("digest", ""))
            if not hmac.compare_digest(want, got):
                raise WireAuthError(
                    "client did not present a digest of the shared "
                    "run token; refusing")
    except WireError as e:
        try:
            _send_error(sock, e)
        except OSError:
            pass
        raise
    _send_frame(sock, FT_OK, [json.dumps({"v": WIRE_VERSION}).encode()])
    sock.settimeout(None)
    return WireConnection(sock, max_frame_bytes=max_frame)


def client_handshake(sock, token=None, timeout=10.0):
    """Dial side of the handshake (see ``server_handshake``)."""
    sock.settimeout(timeout)
    ftype, payload = _recv_frame(sock, _HANDSHAKE_FRAME_CAP)
    if ftype == FT_ERR:
        _raise_wire_error(payload)
    if ftype != FT_HELLO:
        raise WireProtocolError(f"expected HELLO frame, got type {ftype}")
    hello = json.loads(bytes(payload))
    if int(hello.get("v", -1)) != WIRE_VERSION:
        raise WireVersionError(
            f"server speaks wire version {hello.get('v')}, this "
            f"client speaks {WIRE_VERSION}")
    digest = _auth_digest(token, str(hello.get("nonce", "")))
    _send_frame(sock, FT_AUTH,
                [json.dumps({"v": WIRE_VERSION,
                             "digest": digest}).encode()])
    ftype, payload = _recv_frame(sock, _HANDSHAKE_FRAME_CAP)
    if ftype == FT_ERR:
        _raise_wire_error(payload)
    if ftype != FT_OK:
        raise WireProtocolError(f"expected OK frame, got type {ftype}")
    sock.settimeout(None)


# --------------------------------------------------------------------------- #
# A framed connection (post-handshake).
# --------------------------------------------------------------------------- #


class WireConnection:
    """One handshaken socket speaking framed messages.  NOT internally
    locked: callers serialize sends (the client under its send lock,
    the server under its per-connection response lock); receives are
    single-threaded by construction (one reader per connection)."""

    def __init__(self, sock, max_frame_bytes=None):
        self.sock = sock
        self.max_frame = int(max_frame_bytes or MAX_FRAME_BYTES)
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.pickle_fallbacks = 0

    def send_message(self, obj, msg_id):
        """Encode + ship one message; returns bytes written."""
        skeleton, tensors, stats = encode_payload(obj)
        if len(tensors) > MAX_TENSORS_PER_MESSAGE:
            raise WireFrameError(
                f"message carries {len(tensors)} tensors, cap is "
                f"{MAX_TENSORS_PER_MESSAGE}")
        self.pickle_fallbacks += stats["pickle_fallbacks"]
        env = json.dumps({"id": int(msg_id), "nt": len(tensors),
                          "body": skeleton}).encode()
        frames = [(FT_MSG, [env])]
        frames += [(FT_TENSOR, _tensor_frame_parts(a)) for a in tensors]
        for _, parts in frames:
            nf = sum(_nbytes(p) for p in parts)
            if nf > self.max_frame:
                # refuse BEFORE any frame leaves: a skeleton already on
                # the wire with its tensor frames missing would desync
                # every later message on this multiplexed stream
                raise WireFrameError(
                    f"outbound frame of {nf} bytes exceeds the "
                    f"{self.max_frame}-byte frame cap")
        n = 0
        for ftype, parts in frames:
            n += _send_frame(self.sock, ftype, parts, self.max_frame)
        self.bytes_sent += n
        return n

    def send_error(self, exc):
        _send_error(self.sock, exc, self.max_frame)

    def recv_message(self):
        """-> ``(msg_id, obj, nbytes)``.  Raises the typed error when
        the peer answered ``FT_ERR``."""
        ftype, payload = _recv_frame(self.sock, self.max_frame)
        n = _HEADER.size + len(payload)
        if ftype == FT_ERR:
            _raise_wire_error(payload)
        if ftype != FT_MSG:
            raise WireProtocolError(
                f"expected message frame, got type {ftype}")
        env = json.loads(bytes(payload))
        nt = int(env.get("nt", 0))
        if nt < 0 or nt > MAX_TENSORS_PER_MESSAGE:
            raise WireProtocolError(f"message claims {nt} tensors")
        tensors = []
        for _ in range(nt):
            ft2, tp = _recv_frame(self.sock, self.max_frame)
            n += _HEADER.size + len(tp)
            if ft2 == FT_ERR:
                _raise_wire_error(tp)
            if ft2 != FT_TENSOR:
                raise WireProtocolError(
                    f"expected tensor frame, got type {ft2}")
            tensors.append(_decode_tensor(tp))
        self.bytes_recv += n
        return int(env["id"]), decode_payload(env["body"], tensors), n

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# --------------------------------------------------------------------------- #
# Server side: one connection served.
# --------------------------------------------------------------------------- #


def serve_connection(sock, handler, token=None, max_frame_bytes=None,
                     max_workers=8):
    """The worker's per-connection loop: handshake, then read messages
    until the peer hangs up, dispatching each message onto a small
    per-connection thread pool so one slow op cannot
    head-of-line-block the multiplexed connection (responses serialize
    under a per-connection lock).  A POOL, not a thread per message:
    thread spawn is ~50us of pure dispatch latency on the predict hot
    path, and ``max_workers`` bounds how much concurrent op work one
    connection can demand of the worker.

    ``handler(req) -> response`` must not raise (the worker wraps op
    errors into ``{"ok": False, ...}`` envelopes itself).  An oversize
    inbound frame is refused with a typed ``FT_ERR`` and the connection
    closed (the stream position is unrecoverable past an unread
    payload)."""
    from concurrent.futures import ThreadPoolExecutor

    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    try:
        conn = server_handshake(sock, token=token,
                                max_frame_bytes=max_frame_bytes)
    except (WireError, OSError, ConnectionError, ValueError):
        return                          # refusal already answered typed
    send_lock = threading.Lock()
    ops = ThreadPoolExecutor(max_workers=max_workers,
                             thread_name_prefix="bigdl-wire-op")

    def serve_one(mid, req):
        resp = handler(req)
        try:
            with send_lock:
                conn.send_message(resp, mid)
        except WireFrameError as e:
            # the RESPONSE outgrew the cap: tell the waiter instead of
            # silently dropping its request id
            try:
                with send_lock:
                    conn.send_message(
                        {"ok": False, "error": str(e)[:500],
                         "error_type": type(e).__name__}, mid)
            except OSError:
                pass
        except OSError:
            pass                        # client hung up mid-response

    while True:
        try:
            mid, req, _ = conn.recv_message()
        except WireFrameError as e:
            try:
                with send_lock:
                    conn.send_error(e)
            except OSError:
                pass
            conn.close()
            ops.shutdown(wait=False)
            return
        except (WireError, OSError, ConnectionError):
            conn.close()
            ops.shutdown(wait=False)
            return
        ops.submit(serve_one, mid, req)


# --------------------------------------------------------------------------- #
# Client side: multiplexed connection + capped pool.
# --------------------------------------------------------------------------- #


class WireClient:
    """One persistent multiplexed connection: requests are tagged with
    ids, a reader thread matches responses back to waiters, so many
    RPC threads share this socket concurrently."""

    def __init__(self, host, port, token=None, dial_timeout=5.0,
                 max_frame_bytes=None):
        self.host, self.port = host, int(port)
        if token is None:
            token = run_token()
        sock = socket.create_connection((host, int(port)),
                                        timeout=dial_timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            client_handshake(sock, token=token, timeout=dial_timeout)
        except BaseException:
            sock.close()
            raise
        self._conn = WireConnection(sock, max_frame_bytes=max_frame_bytes)
        self._send_lock = threading.Lock()
        self._plock = threading.Lock()
        self._pending = {}
        self._next_id = 0
        self._broken = None
        self._reader = threading.Thread(target=self._read_loop,
                                        name="bigdl-wire-reader",
                                        daemon=True)
        self._reader.start()

    # -- internals -- #
    def _read_loop(self):
        while True:
            try:
                mid, obj, nbytes = self._conn.recv_message()
            except Exception as e:
                self._fail_all(e)
                return
            with self._plock:
                ent = self._pending.pop(mid, None)
            if ent is None:
                continue                # waiter timed out and left
            ent["resp"], ent["nbytes"] = obj, nbytes
            ent["evt"].set()

    def _fail_all(self, exc):
        with self._plock:
            if self._broken is None:
                self._broken = exc
            pending = list(self._pending.values())
            self._pending.clear()
        for ent in pending:
            ent["err"] = exc
            ent["evt"].set()
        self._conn.close()

    @property
    def broken(self):
        return self._broken is not None

    @property
    def bytes_sent(self):
        return self._conn.bytes_sent

    @property
    def bytes_recv(self):
        return self._conn.bytes_recv

    @property
    def pickle_fallbacks(self):
        return self._conn.pickle_fallbacks

    # -- requests -- #
    def request_ex(self, op, rpc_timeout=30.0, **kwargs):
        """-> ``(result, bytes_out, bytes_in)``; raises
        ``ReplicaCallError`` when the worker answered an error, a
        ``WireError``/``OSError`` when the connection failed, and
        ``TimeoutError`` when no response landed in time (the
        connection itself stays healthy: the late response is dropped
        by the reader)."""
        if self._broken is not None:
            raise ConnectionError(
                f"wire connection to {self.host}:{self.port} is "
                f"broken: {self._broken}") from self._broken
        with self._plock:
            self._next_id += 1
            mid = self._next_id
            ent = {"evt": threading.Event(), "resp": None, "err": None,
                   "nbytes": 0}
            self._pending[mid] = ent
        try:
            with self._send_lock:
                out = self._conn.send_message({"op": op, **kwargs}, mid)
        except WireFrameError:
            with self._plock:
                self._pending.pop(mid, None)
            raise
        except OSError as e:
            self._fail_all(e)
            raise ConnectionError(
                f"send to worker {self.host}:{self.port} failed: {e}"
            ) from e
        if not ent["evt"].wait(rpc_timeout):
            with self._plock:
                self._pending.pop(mid, None)
            raise TimeoutError(
                f"no response for {op} from worker "
                f"{self.host}:{self.port} within {rpc_timeout}s")
        if ent["err"] is not None:
            raise ent["err"]
        resp = ent["resp"]
        if not isinstance(resp, dict) or not resp.get("ok"):
            err = (resp or {}).get("error", "malformed response")
            raise ReplicaCallError(
                f"{op} failed on worker {self.host}:{self.port}: {err}",
                error_type=(resp or {}).get("error_type"))
        return resp.get("result"), out, ent["nbytes"]

    def request(self, op, rpc_timeout=30.0, **kwargs):
        return self.request_ex(op, rpc_timeout=rpc_timeout, **kwargs)[0]

    def close(self):
        self._fail_all(ConnectionError("client closed"))


class WirePool:
    """A small capped set of persistent ``WireClient`` connections to
    ONE replica: requests round-robin over healthy connections, broken
    ones are evicted, and re-dials back off under the existing
    ``capped_backoff`` so a dead worker is not hammered."""

    def __init__(self, host, port, token=None, size=2,
                 dial_timeout=5.0, backoff_base_s=0.05,
                 backoff_max_s=2.0, max_frame_bytes=None, on_wire=None,
                 clock=time.monotonic):
        self.host, self.port = host, int(port)
        self.token = token
        self.size = max(1, int(size))
        self.dial_timeout = float(dial_timeout)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.max_frame_bytes = max_frame_bytes
        self.on_wire = on_wire          # cb(verb, rtt_s, out, in)
        self.clock = clock
        self._lock = threading.Lock()
        self._clients = []
        self._rr = 0
        self._dial_fails = 0
        self._next_dial = 0.0

    def _acquire(self):
        from bigdl_tpu.optim.recovery import capped_backoff

        with self._lock:
            self._clients = [c for c in self._clients if not c.broken]
            if len(self._clients) < self.size:
                now = self.clock()
                if now >= self._next_dial:
                    try:
                        self._clients.append(
                            WireClient(self.host, self.port,
                                       token=self.token,
                                       dial_timeout=self.dial_timeout,
                                       max_frame_bytes=
                                       self.max_frame_bytes))
                        self._dial_fails = 0
                    except (OSError, ConnectionError) as e:
                        self._dial_fails += 1
                        self._next_dial = now + capped_backoff(
                            self._dial_fails - 1, self.backoff_base_s,
                            self.backoff_max_s)
                        if not self._clients:
                            raise ConnectionError(
                                f"dial to worker {self.host}:"
                                f"{self.port} failed: {e}") from e
                elif not self._clients:
                    raise ConnectionError(
                        f"worker {self.host}:{self.port} unreachable; "
                        f"re-dial backing off another "
                        f"{self._next_dial - now:.3f}s")
            self._rr += 1
            return self._clients[self._rr % len(self._clients)]

    def _evict(self, client):
        with self._lock:
            self._clients = [c for c in self._clients if c is not client]
        client.close()

    def request_ex(self, op, rpc_timeout=30.0, **kwargs):
        client = self._acquire()
        t0 = time.perf_counter()
        try:
            result, out, inn = client.request_ex(
                op, rpc_timeout=rpc_timeout, **kwargs)
        except Exception:
            if client.broken:
                self._evict(client)
            raise
        if self.on_wire is not None:
            try:
                self.on_wire(op, time.perf_counter() - t0, out, inn)
            except Exception:
                log.exception("wire stats callback failed")
        return result, out, inn

    def request(self, op, rpc_timeout=30.0, **kwargs):
        return self.request_ex(op, rpc_timeout=rpc_timeout, **kwargs)[0]

    @property
    def connections(self):
        with self._lock:
            return len(self._clients)

    def stats(self):
        """Aggregate live-connection counters -- read BEFORE ``close``
        (``pickle_fallbacks`` pins the no-arrays-through-pickle claim)."""
        with self._lock:
            return {"connections": len(self._clients),
                    "bytes_sent": sum(c.bytes_sent
                                      for c in self._clients),
                    "bytes_recv": sum(c.bytes_recv
                                      for c in self._clients),
                    "pickle_fallbacks": sum(c.pickle_fallbacks
                                            for c in self._clients)}

    def close(self):
        with self._lock:
            clients, self._clients = self._clients, []
        for c in clients:
            c.close()


def call_once(host, port, op, rpc_timeout=30.0, auth_token=None,
              **kwargs):
    """One request/response on a throwaway binary-wire connection (the
    tooling/test shape; fleets keep a ``WirePool``).  The handshake
    secret is named ``auth_token`` ON PURPOSE: the deploy ops carry a
    staged-handle ``token=`` request field through ``**kwargs``."""
    client = WireClient(host, port, token=auth_token,
                        dial_timeout=rpc_timeout)
    try:
        return client.request(op, rpc_timeout=rpc_timeout, **kwargs)
    finally:
        client.close()


# --------------------------------------------------------------------------- #
# Blockwise-int8 weight distribution (EQuARX direction).
# --------------------------------------------------------------------------- #

WIRE_QUANT_BLOCK = 256


def quantize_tree_for_wire(tree, block_size=WIRE_QUANT_BLOCK,
                           min_size=1024):
    """Rewrite floating leaves into blockwise-int8 wire form: each
    becomes ``{"__q8__": 1, "q": int8 payload, "s": fp32 per-block
    scales, "shape", "n", "bs", "dtype"}`` using the PR 4 kernels
    (``ops/quantization.py``; scales are fp32 so the worker-side
    dequantization is bit-deterministic).  Leaves smaller than
    ``min_size`` elements or non-floating ship raw -- the bookkeeping
    overhead would beat the savings.  Per-element roundtrip error is
    bounded by ~0.51 int8 ulp of the block absmax (the kernels'
    documented bound); the deploy gate still judges the staged result.
    """
    from bigdl_tpu.ops.quantization import quantize_blockwise

    bs = int(block_size)

    def walk(x):
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(walk(v) for v in x)
        if x is None or not _is_array(x) and not isinstance(x, np.generic):
            return x
        a = np.asarray(x)
        if a.dtype.kind != "f" or a.size < int(min_size):
            return x
        flat = np.ascontiguousarray(a, dtype=np.float32).ravel()
        pad = (-flat.size) % bs
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, np.float32)])
        q, s = quantize_blockwise(flat, bs, scale_dtype="fp32")
        return {"__q8__": 1, "q": np.asarray(q),
                "s": np.asarray(s, np.float32),
                "shape": [int(d) for d in a.shape], "n": int(a.size),
                "bs": bs, "dtype": str(a.dtype)}

    return walk(tree)


def dequantize_wire_tree(tree):
    """Invert ``quantize_tree_for_wire`` (identity on trees with no
    ``__q8__`` markers, so fp32 staging traffic takes the same call)."""
    def walk(x):
        if isinstance(x, dict):
            if x.get("__q8__"):
                from bigdl_tpu.ops.quantization import \
                    dequantize_blockwise

                flat = np.asarray(
                    dequantize_blockwise(np.asarray(x["q"]),
                                         np.asarray(x["s"],
                                                    np.float32),
                                         int(x["bs"])))
                n = int(x["n"])
                a = flat[:n].reshape([int(d) for d in x["shape"]])
                return a.astype(_dtype_of(x["dtype"]))
            return {k: walk(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(walk(v) for v in x)
        return x

    return walk(tree)


def tree_wire_bytes(tree):
    """The tensor-frame bytes a tree will put on the wire (payload
    buffers only; the JSON skeleton adds a few hundred bytes)."""
    _, tensors, _ = encode_payload(tree)
    return int(sum(a.nbytes for a in tensors))
